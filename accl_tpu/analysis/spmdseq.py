"""acclint collective-sequence check: static SPMD sequence analysis.

The cross-rank contract (``accl_tpu.contract``, runtime half) says every
rank of a communicator issues the same collective sequence — same op,
dtype, count, root and tag, in the same order.  This check proves the
*static* half over the code that issues collectives (the facade entry
points, ``tests/shared_scenarios.py``, the model zoo, the parallel
helpers): a collective call whose **op choice** (control flow) or
**contract field** (count / root / tag / function / comm) derives from a
*rank-varying* value — the local rank id, per-rank buffer identity,
``id()``, a health map, the process-global RNG — is flagged, because
each rank would issue a different call and wedge the fabric.

Abstract interpretation, per function, with one interprocedural pass:

1. every function in the module gets a summary — "does its return value
   derive from rank-varying state?" — computed by a forward taint walk
   over its body (two passes, so loop-carried taint converges);
2. each function body is then walked again with those summaries in
   scope: calls to a tainted-returning same-module function taint their
   result, calls to an ``@analysis.markers.spmd_uniform``-marked
   function *sanitize* it (the marker is the audited "this is uniform
   across ranks" assertion — the same marker machinery the
   spmd-uniformity check verifies);
3. at each collective call site (``<handle>.allreduce(...)`` etc.) the
   governing branch conditions and the contract-field arguments are
   checked for taint.

Operand positions (the leading buffer arguments) are deliberately NOT
contract fields: a root legitimately passes a real buffer where
non-roots pass ``None``/Dummy — rank-varying *operands* are the API
working as designed; rank-varying *op choice or shape fields* are the
bug.  Audited-safe sites carry ``# acclint: allow[collective-sequence]
<reason>`` like every other check.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .base import Finding, SourceFile

__all__ = ["check_collective_sequence", "CONTRACT_CALLS", "extra_scope"]

#: method names that issue sequence-contract collectives on a facade
#: handle (P2P send/recv/stream_put are rank-asymmetric by design and
#: exempt); begin/end/batch ride along because batch boundaries extend
#: the same contract (every rank must flush at the same call-sequence
#: point)
CONTRACT_CALLS = frozenset((
    "bcast", "scatter", "gather", "allgather", "reduce", "allreduce",
    "reduce_scatter", "alltoall", "barrier",
    "begin_batch", "end_batch", "soft_reset",
))

#: per-op count of leading positional OPERAND slots (buffers — allowed
#: to vary per rank); positionals past these are contract fields
_OPERAND_SLOTS = {
    "bcast": 1, "scatter": 2, "gather": 2, "allgather": 2,
    "reduce": 2, "allreduce": 2, "reduce_scatter": 2, "alltoall": 2,
    "barrier": 0, "begin_batch": 0, "end_batch": 0, "soft_reset": 0,
}

#: keyword arguments that are contract fields (operand/buffer keywords
#: and run_async are not — run_async only changes who waits, not what
#: the engine matches)
_CONTRACT_KWARGS = frozenset((
    "count", "root", "tag", "function", "comm", "compress_dtype",
    "stream_id", "dtype",
))

#: names that are rank-varying wherever they appear (parameters and
#: locals): the per-rank identity itself, and buffer-identity flags
_TAINT_NAMES = frozenset((
    "rank", "local_rank", "world_rank",
))
#: attribute terminals that read process-local state
#: (``self_evicted`` is the membership plane's per-rank verdict bit —
#: true on exactly one rank of the old group, the definition of
#: rank-varying)
_TAINT_ATTRS = frozenset((
    "rank", "local_rank", "world_rank", "is_dummy", "is_host_only",
    "process_index", "process_id", "self_evicted",
))
#: ``last_join`` covers raw join-state reads (snapshot["last_join"],
#: view._last_join): members and a just-admitted candidate observe the
#: join at different moments, so branching a collective on the raw
#: record diverges — route it through the latched ``join_decision()``
#: accessor instead
_TAINT_SUBSTR = ("health", "tenant_class", "last_join")

#: built-in sanitizers (beyond same-module @spmd_uniform functions):
#: ``create_communicator`` is the blessed MPI_Comm_split-style
#: constructor — its MEMBERS argument legitimately varies per rank (each
#: rank passes its own group) while the returned communicator is the
#: uniform handle the new group's contract runs over.  The membership
#: plane's EXCHANGED-verdict accessors join it: ``demote_decision`` /
#: ``suggest_root`` derive from the shared demotion ledger (latched per
#: (comm, call index) — every rank reads the same decision) and
#: ``evict_rank``/``take_cutover`` apply a majority-confirmed plan —
#: SPMD-uniform by construction.  The QoS arbiter plane's decision
#: accessor joins them: ``admit`` returns the per-(comm, call index)
#: admission record latched on the shared arbiter — every rank reads
#: the same class/throttle verdict.  The elastic-expansion admission
#: accessor ``join_decision`` joins the membership set: it returns the
#: latest APPLIED join record — majority-confirmed and cutover-applied,
#: identical on every member by the agreement protocol.  Raw
#: health-map, tenant-class and join-state reads stay taint SOURCES
#: (_TAINT_SUBSTR above): a collective branched on a locally-read
#: ``tenant_class`` field or raw ``last_join`` record still flags —
#: route it through the latched decision instead.
_BUILTIN_SANITIZERS = frozenset((
    "create_communicator", "split",
    "demote_decision", "suggest_root", "join_decision",
    "admit",
    # topology accessors: slice/leader facts are pure functions of the
    # descriptor every rank constructed identically (the collective
    # set_topology contract), so leader-only cross-slice calls —
    # `if topo.is_leader(rank): leaders_comm.allreduce(...)` — branch
    # on uniform data, not rank-varying state
    "slice_leader", "is_leader", "leaders", "slice_of",
    "bcast_representatives",
    # the facade's hierarchical subcomm cache rides split() — its
    # result is a communicator whose members all make the same call,
    # even though WHICH subcomm a rank holds varies by rank (each rail
    # is its own collective domain)
    "_hier_subcomm",
))


def _is_spmd_marked(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", ()):
        d = dec.func if isinstance(dec, ast.Call) else dec
        name = (
            d.id if isinstance(d, ast.Name)
            else d.attr if isinstance(d, ast.Attribute) else None
        )
        if name == "spmd_uniform":
            return True
    return False


class _Taint:
    """Forward taint walk over one function body."""

    def __init__(self, sanitizers: Set[str], tainted_fns: Set[str]):
        self.sanitizers = sanitizers
        self.tainted_fns = tainted_fns
        self.vars: Set[str] = set()

    # -- expression taint ----------------------------------------------------
    def expr_refs(self, node: ast.AST) -> List[str]:
        """The rank-varying references an expression derives from
        (empty = uniform as far as this analysis can tell).  Sanitizer
        calls (same-module @spmd_uniform helpers, the blessed
        create_communicator constructor) prune their whole subtree —
        their result is uniform by audited contract even when their
        arguments are not."""
        refs: List[str] = []
        self._expr_walk(node, refs)
        return refs

    def _expr_walk(self, node: ast.AST, refs: List[str]) -> None:
        if isinstance(node, ast.Call):
            f = node.func
            fname = (
                f.id if isinstance(f, ast.Name)
                else f.attr if isinstance(f, ast.Attribute) else None
            )
            if fname in self.sanitizers or fname in _BUILTIN_SANITIZERS:
                return  # uniform by marker/constructor contract
            if fname == "id":
                refs.append("id()")
            elif fname in self.tainted_fns:
                refs.append(f"{fname}()")
            elif fname == "rank":
                refs.append("rank()")
            elif isinstance(f, ast.Attribute) and (
                (isinstance(f.value, ast.Name) and f.value.id == "random")
                or (isinstance(f.value, ast.Attribute)
                    and f.value.attr == "random")
            ):
                refs.append(f"random.{f.attr}()")
            for child in ast.iter_child_nodes(node):
                self._expr_walk(child, refs)
            return
        if isinstance(node, ast.Attribute):
            if node.attr in _TAINT_ATTRS or any(
                s in node.attr.lower() for s in _TAINT_SUBSTR
            ):
                refs.append(node.attr)
        elif isinstance(node, ast.Subscript):
            # caps["health"] / snapshot["health"]: the canonical way
            # the per-rank health map is read
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                if any(s in sl.value.lower() for s in _TAINT_SUBSTR):
                    refs.append(f"[{sl.value!r}]")
        elif isinstance(node, ast.Name):
            if (
                node.id in _TAINT_NAMES
                or node.id in self.vars
                or any(s in node.id.lower() for s in _TAINT_SUBSTR)
            ):
                refs.append(node.id)
        for child in ast.iter_child_nodes(node):
            self._expr_walk(child, refs)

    # -- statement walk (assignment propagation) -----------------------------
    def propagate(self, body: List[ast.stmt]) -> None:
        """Two passes over the statement list so taint assigned late in
        a loop body reaches uses earlier in the next iteration."""
        for _ in range(2):
            for node in body:
                for sub in ast.walk(node):
                    targets: List[ast.AST] = []
                    value = None
                    if isinstance(sub, ast.Assign):
                        targets, value = sub.targets, sub.value
                    elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                        if sub.value is not None:
                            targets, value = [sub.target], sub.value
                    elif isinstance(sub, ast.For):
                        targets, value = [sub.target], sub.iter
                    elif isinstance(sub, ast.withitem):
                        if sub.optional_vars is not None:
                            targets = [sub.optional_vars]
                            value = sub.context_expr
                    elif isinstance(sub, ast.NamedExpr):
                        targets, value = [sub.target], sub.value
                    if value is None or not targets:
                        continue
                    if not self.expr_refs(value):
                        continue
                    for t in targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                self.vars.add(n.id)


def _mentions_taint(fn: ast.AST, extra_names: Set[str]) -> bool:
    """Cheap single-walk pre-filter: can this function possibly touch
    rank-varying state?  Most functions mention no taint token at all
    and skip the full propagation pass (the whole-tree run must stay
    ~2 s — the same budget every acclint check lives under)."""
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Name):
            if (
                sub.id in _TAINT_NAMES or sub.id in extra_names
                or sub.id == "id" or sub.id == "random"
                or any(s in sub.id.lower() for s in _TAINT_SUBSTR)
            ):
                return True
        elif isinstance(sub, ast.Attribute):
            if sub.attr in _TAINT_ATTRS or sub.attr in extra_names or any(
                s in sub.attr.lower() for s in _TAINT_SUBSTR
            ):
                return True
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            if any(s in sub.value.lower() for s in _TAINT_SUBSTR):
                return True
    return False


def _function_summaries(
    src: SourceFile, relevant: Optional[Set[str]] = None
) -> tuple:
    """(sanitizer names, tainted-return names) for the module: phase 1
    of the interprocedural pass.  A function whose ``return`` derives
    from rank-varying state taints its callers' results; an
    ``@spmd_uniform``-marked one sanitizes them.  ``relevant`` limits
    the summary pass to names reachable from collective-issuing code
    (the only summaries phase 2 can consume) — the rest of the module
    never pays the propagation walk."""
    sanitizers: Set[str] = set()
    fns: Dict[str, ast.AST] = {}
    for node in src.nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if _is_spmd_marked(node):
                sanitizers.add(node.name)
            if relevant is not None and node.name not in relevant:
                continue
            fns.setdefault(node.name, node)
    tainted: Set[str] = set()
    for _ in range(2):  # one level of same-module call nesting converges
        for name, fn in fns.items():
            if name in sanitizers or name in tainted:
                continue
            if not _mentions_taint(fn, tainted):
                continue
            t = _Taint(sanitizers, tainted)
            t.propagate(fn.body)
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Return) and sub.value is not None:
                    if t.expr_refs(sub.value):
                        tainted.add(name)
                        break
    return sanitizers, tainted


def _op_of(call: ast.Call) -> Optional[str]:
    """The contract-collective name this call issues, or None.  Only
    attribute calls count (``handle.allreduce(...)``): a bare name like
    ``reduce(...)`` is functools.reduce, not a collective."""
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in CONTRACT_CALLS:
        return f.attr
    return None


class _SiteVisitor(ast.NodeVisitor):
    """Walk one function carrying the stack of governing branch
    conditions, flagging contract-call sites."""

    def __init__(self, src: SourceFile, fn, taint: _Taint,
                 findings: List[Finding]):
        self.src = src
        self.fn = fn
        self.taint = taint
        self.findings = findings
        self.cond_refs: List[List[str]] = []

    # nested defs/lambdas get their own top-level walk; don't descend
    def visit_FunctionDef(self, node):  # noqa: N802
        if node is not self.fn:
            return
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def _visit_branch(self, test: ast.AST, bodies) -> None:
        refs = self.taint.expr_refs(test)
        self.visit(test)
        if refs:
            self.cond_refs.append(refs)
        for body in bodies:
            for stmt in body:
                self.visit(stmt)
        if refs:
            self.cond_refs.pop()

    def visit_If(self, node):  # noqa: N802
        self._visit_branch(node.test, [node.body, node.orelse])

    def visit_While(self, node):  # noqa: N802
        self._visit_branch(node.test, [node.body, node.orelse])

    def visit_For(self, node):  # noqa: N802
        # a rank-varying ITERABLE governs the loop's trip count: a
        # collective in the body runs a different number of times per
        # rank — call-COUNT divergence, same bug class as a branch
        self._visit_branch(node.iter, [node.body, node.orelse])

    visit_AsyncFor = visit_For

    def visit_IfExp(self, node):  # noqa: N802
        refs = self.taint.expr_refs(node.test)
        self.visit(node.test)
        if refs:
            self.cond_refs.append(refs)
        self.visit(node.body)
        self.visit(node.orelse)
        if refs:
            self.cond_refs.pop()

    def visit_Call(self, node):  # noqa: N802
        op = _op_of(node)
        if op is None:
            self.generic_visit(node)
            return
        if self.cond_refs:
            governing = sorted({r for refs in self.cond_refs for r in refs})
            self.findings.append(self.src.finding(
                "collective-sequence", node,
                f"collective '{op}' is issued under a branch on "
                f"rank-varying state ({', '.join(governing)}): ranks "
                f"taking different branches issue different call "
                f"sequences and wedge the fabric; hoist the collective "
                f"or mark the condition's source @spmd_uniform",
            ))
        nops = _OPERAND_SLOTS.get(op, 0)
        for i, arg in enumerate(node.args):
            if i < nops or isinstance(arg, ast.Starred):
                continue
            refs = self.taint.expr_refs(arg)
            if refs:
                self.findings.append(self.src.finding(
                    "collective-sequence", node,
                    f"collective '{op}' positional argument {i} (a "
                    f"contract field) derives from rank-varying state "
                    f"({', '.join(sorted(set(refs)))}): every rank must "
                    f"pass the same value",
                ))
        for kw in node.keywords:
            if kw.arg not in _CONTRACT_KWARGS:
                continue
            refs = self.taint.expr_refs(kw.value)
            if refs:
                self.findings.append(self.src.finding(
                    "collective-sequence", node,
                    f"collective '{op}' field {kw.arg}= derives from "
                    f"rank-varying state ({', '.join(sorted(set(refs)))}): "
                    f"every rank must pass the same value (audited-"
                    f"uniform derivations go through an @spmd_uniform "
                    f"helper or carry a suppression reason)",
                ))
        self.generic_visit(node)


def check_collective_sequence(src: SourceFile) -> List[Finding]:
    # fast reject on the shared flattened walk: any contract-call site
    # at all?  (cheaper than re-walking per function; most modules have
    # none and exit here)
    if not any(
        isinstance(n, ast.Call) and _op_of(n) is not None
        for n in src.nodes
    ):
        return []
    findings: List[Finding] = []
    # candidate functions (those issuing contract collectives) and the
    # names they call: only THOSE need phase-1 return-taint summaries
    candidates = []
    called: Set[str] = set()
    for fn in src.nodes:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if any(
            isinstance(sub, ast.Call) and _op_of(sub) is not None
            for sub in ast.walk(fn)
        ):
            candidates.append(fn)
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call):
                    f = sub.func
                    if isinstance(f, ast.Name):
                        called.add(f.id)
                    elif isinstance(f, ast.Attribute):
                        called.add(f.attr)
    sanitizers, tainted_fns = _function_summaries(src, relevant=called)
    for fn in candidates:
        if not _mentions_taint(fn, tainted_fns):
            continue  # no rank-varying token anywhere: nothing to flag
        taint = _Taint(sanitizers, tainted_fns)
        taint.propagate(fn.body)
        _SiteVisitor(src, fn, taint, findings).visit(fn)
    return findings


def extra_scope() -> List[str]:
    """Files OUTSIDE the package default scope this check also covers:
    the shared scenario library every transport tier executes (its
    collective sequences are the contract's highest-traffic users)."""
    import os

    from .base import package_root

    repo = os.path.dirname(package_root())
    path = os.path.join(repo, "tests", "shared_scenarios.py")
    return [path] if os.path.isfile(path) else []
