"""Invariant markers: zero-dependency decorators the analyzer keys on.

Importable from anywhere (engines, jax-free planes, tests) — this
module must never grow imports.
"""

from __future__ import annotations

__all__ = ["spmd_uniform"]


def spmd_uniform(fn):
    """Mark a function as SPMD-uniform: it runs identically on every
    rank of an SPMD program stream, so its control flow must never
    branch on process-local state (rank, buffer identity/aliasing,
    health maps).  Purely declarative at runtime; the acclint
    ``spmd-uniformity`` check statically audits the body of every
    marked function (tests/test_analysis.py proves the detection)."""
    fn.__spmd_uniform__ = True
    return fn
