"""acclint core model: findings, suppressions, and the check registry.

The analyzer encodes the project's concurrency/architecture invariants
as named, individually-suppressible checks (the list lives in
``accl_tpu.analysis.CHECKS``).  Everything here is stdlib-only — the
analyzer must be runnable from CI shells and jax-free processes, and
fast enough to gate every bench capture.

Suppression syntax (audited-safe sites)::

    something.wait()  # acclint: allow[unbounded-wait] watchdog bounds this

A suppression names the check it silences in square brackets and MUST
carry a non-empty reason — a bare ``allow[check]`` does not apply (the
reviewed justification is the point of the syntax).  It applies to the
line it sits on, or, when written on its own line, to the line directly
below it.  Several checks can share one comment:
``allow[unbounded-wait,timer-discipline] reason``.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Finding",
    "SourceFile",
    "package_root",
    "iter_source_files",
    "load_source",
]

#: ``# acclint: allow[check-a,check-b] reason...``
_SUPPRESS_RE = re.compile(
    r"#\s*acclint:\s*allow\[([A-Za-z0-9_,\- ]+)\]\s*(.*)"
)


@dataclasses.dataclass
class Finding:
    """One rule violation at one source location."""

    check: str
    path: str  # path as given to the analyzer (repo-relative in CI)
    line: int
    message: str
    suppressed: bool = False
    suppress_reason: str = ""

    def render(self) -> str:
        tag = " [suppressed]" if self.suppressed else ""
        return f"{self.path}:{self.line}: {self.check}: {self.message}{tag}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class SourceFile:
    """One parsed module: AST + per-line suppression table.

    ``suppressions`` maps line number -> {check-name: reason} covering
    both same-line comments and own-line comments (which bind to the
    next line).  A malformed suppression (no reason) is recorded in
    ``bad_suppressions`` so the analyzer can surface it instead of
    silently granting or ignoring it.
    """

    def __init__(self, path: str, text: str):
        self.path = path
        self.text = text
        self.tree = ast.parse(text, filename=path)
        self.lines = text.splitlines()
        # one flattened walk, shared by every per-file check (walking
        # the tree once per check made the analyzer seconds-slow)
        self.nodes = list(ast.walk(self.tree))
        self.suppressions: Dict[int, Dict[str, str]] = {}
        self.bad_suppressions: List[int] = []
        if "acclint" in text:  # comment scan only where it can matter
            self._scan_comments()

    def _scan_comments(self) -> None:
        import io

        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.text).readline
            ))
        except tokenize.TokenError:  # pragma: no cover - parse succeeded
            tokens = []
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            checks = [c.strip() for c in m.group(1).split(",") if c.strip()]
            reason = m.group(2).strip()
            line = tok.start[0]
            if not reason:
                self.bad_suppressions.append(line)
                continue
            # an own-line comment binds to the next CODE line too
            # (skipping the rest of its own comment block)
            targets = [line]
            stripped = (
                self.lines[line - 1].strip() if line <= len(self.lines) else ""
            )
            if stripped.startswith("#"):
                nxt = line + 1
                while nxt <= len(self.lines) and (
                    not self.lines[nxt - 1].strip()
                    or self.lines[nxt - 1].strip().startswith("#")
                ):
                    nxt += 1
                targets.append(nxt)
            for t in targets:
                slot = self.suppressions.setdefault(t, {})
                for c in checks:
                    slot[c] = reason

    def suppression_for(self, check: str, line: int) -> Optional[str]:
        slot = self.suppressions.get(line)
        if slot is None:
            return None
        return slot.get(check)

    def finding(self, check: str, node_or_line, message: str) -> Finding:
        line = getattr(node_or_line, "lineno", node_or_line)
        reason = self.suppression_for(check, line)
        return Finding(
            check=check,
            path=self.path,
            line=line,
            message=message,
            suppressed=reason is not None,
            suppress_reason=reason or "",
        )


def package_root() -> str:
    """The accl_tpu package directory (the default analysis scope)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def iter_source_files(paths: Optional[Iterable[str]] = None) -> List[str]:
    """Every ``.py`` file under ``paths`` (default: the package),
    sorted for deterministic output.  Explicit file paths pass through."""
    roots = list(paths) if paths else [package_root()]
    out: List[str] = []
    for root in roots:
        if os.path.isfile(root):
            out.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in filenames:
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(out)


def load_source(path: str) -> Tuple[Optional[SourceFile], Optional[Finding]]:
    """Parse one file; a syntax error is itself a finding (the analyzer
    must not silently skip what it cannot read)."""
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
        return SourceFile(path, text), None
    except (OSError, SyntaxError, ValueError) as e:
        return None, Finding(
            check="parse",
            path=path,
            line=getattr(e, "lineno", None) or 1,
            message=f"cannot analyze: {e}",
        )
