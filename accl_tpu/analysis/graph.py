"""acclint cross-file checks: the import graph and drain-path checks.

* **jax-free-module** — the modules the overlap/telemetry/chaos planes
  promise are importable from jax-free processes (``overlap``,
  ``telemetry``, ``faults``, ``plans``, ``constants``) must not import
  jax/numpy at module scope, directly OR through anything they import
  at module scope.  A socket-fabric rank process, the telemetry merge
  CLI, and the lock-order shim all rely on this staying true.
* **drain-before-config** — every config-write path (a function that
  constructs an ``Operation.CONFIG`` call) and every ``soft_reset``
  must reach a drain call before abandoning/overwriting engine state:
  a config write that overtakes in-flight work observes (and corrupts)
  a state snapshot mid-collective.  The check walks the intra-module
  call graph from each entry point looking for a drain-family call.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set

from .base import Finding, SourceFile, load_source, package_root

__all__ = [
    "CROSS_FILE_CHECKS",
    "check_jax_free_modules",
    "check_drain_before_config",
    "check_cmdring_slot_layout",
    "JAX_FREE_MODULES",
    "FORBIDDEN_HEAVY_IMPORTS",
]

#: accl_tpu modules that must stay importable without jax/numpy
JAX_FREE_MODULES = (
    "accl_tpu.overlap",
    "accl_tpu.telemetry",
    "accl_tpu.faults",
    "accl_tpu.plans",
    "accl_tpu.constants",
    "accl_tpu.contract",
    "accl_tpu.monitor",
    "accl_tpu.membership",
    "accl_tpu.arbiter",
    # quantized wire plane: the shared host codec + error-feedback
    # residual store (lazy numpy, the constants.py pattern) — socket
    # rank processes and the analysis tooling import both
    "accl_tpu.wire",
    "accl_tpu.errorfeedback",
    # multi-slice plane: the descriptor and decomposition math are
    # stdlib-only so every rank (and the analysis tooling, and the
    # numpy-only CI smokes) derives identical plans without jax
    "accl_tpu.topology",
    "accl_tpu.hierarchical",
)

#: top-level packages whose module-scope import breaks jax-freedom
#: (ml_dtypes transitively imports numpy)
FORBIDDEN_HEAVY_IMPORTS = frozenset((
    "jax", "jaxlib", "numpy", "ml_dtypes",
))


def _module_name(path: str, root: str) -> Optional[str]:
    """``accl_tpu.backends.base`` for ``<root>/backends/base.py`` where
    root is the accl_tpu package dir; None for files outside it."""
    rel = os.path.relpath(os.path.abspath(path), root)
    if rel.startswith(".."):
        return None
    parts = rel.replace(os.sep, "/").split("/")
    if parts[-1] == "__init__.py":
        parts = parts[:-1]
    else:
        parts[-1] = parts[-1][:-3]  # strip .py
    return ".".join(["accl_tpu"] + [p for p in parts if p])


def _module_scope_imports(tree: ast.Module):
    """(node, imported-module-name, level, from-aliases) for every
    import that runs at import time: top-level statements plus those
    nested in module-level ``if``/``try`` blocks (a ``try: import
    ml_dtypes`` still executes).  ``from-aliases`` carries the names an
    ImportFrom binds — ``from . import constants`` names a MODULE via
    its alias, which the consumer must try as a module too."""
    stack = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Import):
            for alias in node.names:
                yield node, alias.name, 0, ()
        elif isinstance(node, ast.ImportFrom):
            yield node, node.module or "", node.level, tuple(
                a.name for a in node.names
            )
        elif isinstance(node, (ast.If, ast.Try, ast.With, ast.For,
                               ast.While)):
            # all of these EXECUTE their bodies at import time when they
            # sit at module level (`with suppress(ImportError): import
            # numpy` is the sneaky one — the idiom the old constants.py
            # try-block used, spelled via contextlib)
            for field in ("body", "orelse", "finalbody", "handlers"):
                for sub in getattr(node, field, ()):
                    if isinstance(sub, ast.ExceptHandler):
                        stack.extend(sub.body)
                    else:
                        stack.append(sub)
        # FunctionDef/ClassDef bodies do NOT run at import time


def _resolve_relative(mod: str, name: str, level: int, is_pkg: bool) -> str:
    """Absolute module name for a (possibly relative) import found in
    ``mod`` (e.g. level=1 name='constants' in accl_tpu.overlap ->
    accl_tpu.constants)."""
    if level == 0:
        return name
    parts = mod.split(".")
    if not is_pkg:
        parts = parts[:-1]
    if level > 1:
        parts = parts[: len(parts) - (level - 1)]
    return ".".join(parts + ([name] if name else []))


def check_jax_free_modules(sources: List[SourceFile]) -> List[Finding]:
    root = package_root()
    by_mod: Dict[str, SourceFile] = {}
    for src in sources:
        mod = _module_name(src.path, root)
        if mod:
            by_mod[mod] = src

    findings: List[Finding] = []
    if not by_mod:
        # analyzing loose files outside the package (fixture snippets,
        # a path override): the contract modules are out of scope
        return findings

    def _load_pkg_module(mod: str) -> Optional[SourceFile]:
        """The import closure is a WHOLE-PACKAGE fact: when the
        analyzer was pointed at a path subset, pull the missing
        package modules from disk so per-file invocations (pre-commit,
        editors) see the same verdict as the full run."""
        rel = mod.split(".")[1:]
        for cand in (
            os.path.join(root, *rel) + ".py",
            os.path.join(root, *rel, "__init__.py") if rel else None,
        ):
            if cand and os.path.isfile(cand):
                src, _ = load_source(cand)
                return src
        return None

    def _source_for(mod: str) -> Optional[SourceFile]:
        src = by_mod.get(mod)
        if src is None:
            src = _load_pkg_module(mod)
            if src is not None:
                by_mod[mod] = src
        return src

    # module -> [(line-node, imported absolute module)] at module scope;
    # ImportFrom aliases and ancestor subpackage __init__s are expanded
    # (both execute at import time)
    edge_cache: Dict[str, List] = {}

    def _edges(mod: str, src: SourceFile) -> List:
        outs = edge_cache.get(mod)
        if outs is not None:
            return outs
        is_pkg = src.path.endswith("__init__.py")
        outs = []
        for node, name, level, aliases in _module_scope_imports(src.tree):
            target = _resolve_relative(mod, name, level, is_pkg)
            candidates = [target]
            # 'from X import y': each alias may itself name a module
            for a in aliases:
                if a != "*":
                    candidates.append(f"{target}.{a}" if target else a)
            for t in candidates:
                outs.append((node, t))
                # importing accl_tpu.a.b also executes accl_tpu.a's
                # __init__ (the top package's init is bypassed by the
                # jax-free loaders, so it is deliberately excluded)
                parts = t.split(".")
                for i in range(2, len(parts)):
                    outs.append((node, ".".join(parts[:i])))
        edge_cache[mod] = outs
        return outs

    for entry in JAX_FREE_MODULES:
        if _source_for(entry) is None:
            findings.append(Finding(
                check="jax-free-module", path=entry, line=1,
                message=f"declared jax-free module {entry} not found in "
                        f"the package",
            ))
            continue
        # DFS over module-scope imports reachable from the entry module
        seen: Set[str] = set()
        reported: Set[tuple] = set()
        stack = [entry]
        while stack:
            mod = stack.pop()
            if mod in seen:
                continue
            seen.add(mod)
            src = _source_for(mod)
            if src is None:
                continue
            for node, target in _edges(mod, src):
                top = target.split(".")[0]
                if top in FORBIDDEN_HEAVY_IMPORTS:
                    key = (src.path, node.lineno, top)
                    if key in reported:
                        continue
                    reported.add(key)
                    chain = f" (imported via {mod})" if mod != entry else ""
                    findings.append(src.finding(
                        "jax-free-module", node,
                        f"module-scope import of {top!r} breaks the "
                        f"jax-free contract of {entry}{chain}; import it "
                        f"lazily inside the function that needs it",
                    ))
                elif top == "accl_tpu" and target != "accl_tpu":
                    stack.append(target)
    return findings


# ---------------------------------------------------------------------------
# drain-before-config
# ---------------------------------------------------------------------------

#: a call whose terminal attribute/name is one of these counts as
#: reaching the drain machinery
_DRAIN_NAMES = frozenset((
    "flush", "drain", "drain_key", "drain_inflight",
))


def _is_config_call(node: ast.AST) -> bool:
    """Is this node a CallOptions(op=Operation.CONFIG...) construction?"""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    name = (
        f.id if isinstance(f, ast.Name)
        else f.attr if isinstance(f, ast.Attribute) else None
    )
    if name != "CallOptions":
        return False
    for kw in node.keywords:
        if kw.arg == "op" and isinstance(kw.value, ast.Attribute):
            if (
                kw.value.attr == "CONFIG"
                and isinstance(kw.value.value, ast.Name)
                and kw.value.value.id == "Operation"
            ):
                return True
    return False


def _called_names(fn: ast.AST) -> Set[str]:
    """Terminal names of every call made in ``fn``'s body."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name):
                out.add(f.id)
            elif isinstance(f, ast.Attribute):
                out.add(f.attr)
    return out


def check_drain_before_config(sources: List[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for src in sources:
        # function name -> ALL same-named AST nodes, module-wide (two
        # classes in one module can both define soft_reset — every one
        # is an entry point, and a callee name may resolve to any of
        # them).  Use the shared flattened walk; call-name sets are
        # memoized per node.
        fns: Dict[str, List[ast.AST]] = {}
        config_lines: List[int] = []
        for node in src.nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fns.setdefault(node.name, []).append(node)
            elif _is_config_call(node):
                config_lines.append(node.lineno)
        called_cache: Dict[ast.AST, Set[str]] = {}

        def _called(f):
            got = called_cache.get(f)
            if got is None:
                got = called_cache[f] = _called_names(f)
            return got

        entries = [
            (name, fn)
            for name, nodes in fns.items()
            for fn in nodes
            if name == "soft_reset" or any(
                fn.lineno <= ln <= getattr(fn, "end_lineno", fn.lineno)
                for ln in config_lines
            )
        ]
        for name, fn in entries:
            # BFS through same-module callees (depth-limited) looking
            # for a drain-family call; a called name fans out to EVERY
            # same-named definition (static name resolution can't pick
            # the class, so reachability is the union)
            reached = False
            seen: Set[int] = set()
            frontier = [fn]
            for _ in range(4):  # entry + 3 levels of same-module calls
                nxt = []
                for f in frontier:
                    called = _called(f)
                    if called & _DRAIN_NAMES:
                        reached = True
                        break
                    for c in called:
                        for cand in fns.get(c, ()):
                            if id(cand) not in seen:
                                seen.add(id(cand))
                                nxt.append(cand)
                if reached or not nxt:
                    break
                frontier = nxt
            if not reached:
                findings.append(src.finding(
                    "drain-before-config", fn,
                    f"{name!r} writes engine config / resets state but "
                    f"never reaches a drain call "
                    f"({', '.join(sorted(_DRAIN_NAMES))}); in-flight "
                    f"work must complete before state is abandoned",
                ))
    return findings


# ---------------------------------------------------------------------------
# cmdring-slot-layout
# ---------------------------------------------------------------------------

#: names that constitute the command-ring slot contract; exactly ONE
#: definition (constants.py) may exist — the host-side encoder and the
#: device-side sequencer must both read it from there
_CMDRING_CANONICAL_NAMES = frozenset((
    "CMDRING_FIELDS", "CMDRING_SLOT_WORDS", "CmdOpcode",
    "CMDRING_ST_OK", "CMDRING_ST_BAD_OP",
))

#: modules that encode/decode slots (relative to the accl_tpu root)
_CMDRING_MODULES = (
    "cmdring.py",            # host half: slot codec + mailbox protocol
    "ops/pallas/cmdring.py",  # device half: both sequencer lowerings
    "backends/xla/cmdring.py",  # engine half: sessions + refills
)

#: the module holding the decode loop both lowerings share — it must
#: reference every executable opcode (the cross-file presence check)
_CMDRING_DECODE_MODULE = "ops/pallas/cmdring.py"

#: the shared device-side wire-lane module: its literal ``WIRE_LANES``
#: table must cover every dtype constants.WIRE_LANE_DTYPES registers
_WIRE_LANE_MODULE = "ops/wire.py"

#: the decode module's two sequencer lowerings: EACH must route its
#: wire cast through the shared lane machinery (a wire value only one
#: lowering decodes is a finding — the quantized-wire cross-check)
_CMDRING_LOWERING_FUNCS = ("_decode_slot_xla", "_pallas_windows")

#: names that constitute "routing through the shared lane machinery":
#: the roundtrip helper, or the cast+scaled lane pair it is built from
_WIRE_LANE_HELPERS = frozenset((
    "wire_lane_roundtrip", "_cast_lane", "quantize_int8",
    "dequantize_int8",
))

#: opcodes exempt from the decode-presence requirement: NOP is the
#: padding slot (decoded, skipped), HALT the teardown marker — neither
#: executes a collective
_CMDRING_MARKER_OPCODES = frozenset(("NOP", "HALT"))


def _cmdring_table(src: SourceFile):
    """(fields: {name: index} | None, slot_words: int | None) from the
    constants module's literal table."""
    fields = None
    slot_words = None
    for node in src.tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        if tgt.id == "CMDRING_FIELDS" and isinstance(node.value, ast.Dict):
            fields = {}
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) and isinstance(
                    v, ast.Constant
                ):
                    fields[k.value] = v.value
        elif tgt.id == "CMDRING_SLOT_WORDS" and isinstance(
            node.value, ast.Constant
        ):
            slot_words = node.value.value
    return fields, slot_words


def _cmdring_opcodes(src: SourceFile):
    """(opcode name -> value, opcode-map line) from the constants
    module: the ``CmdOpcode`` IntEnum body (literal member assigns) and
    the names referenced as values of the ``CMDRING_OPCODES``
    Operation-map literal."""
    opcodes = None
    mapped = None
    map_line = 1
    for node in src.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "CmdOpcode":
            opcodes = {}
            for stmt in node.body:
                if (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Constant)
                ):
                    opcodes[stmt.targets[0].id] = stmt.value.value
        elif (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "CMDRING_OPCODES"
            and isinstance(node.value, ast.Dict)
        ):
            map_line = node.lineno
            mapped = set()
            for v in node.value.values:
                if isinstance(v, ast.Attribute):
                    mapped.add(v.attr)
    return opcodes, mapped, map_line


def _wire_lane_dtypes(src: SourceFile):
    """constants.WIRE_LANE_DTYPES as a literal {member: numpy name}
    dict (None when absent — pre-quantized-wire trees)."""
    for node in src.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "WIRE_LANE_DTYPES"
            and isinstance(node.value, ast.Dict)
        ):
            out = {}
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) and isinstance(
                    v, ast.Constant
                ):
                    out[k.value] = v.value
            return out, node.lineno
    return None, 1


def _wire_lanes_table(src: SourceFile):
    """ops/wire.py's literal ``WIRE_LANES`` table (numpy-name keys)."""
    for node in src.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "WIRE_LANES"
            and isinstance(node.value, ast.Dict)
        ):
            keys = set()
            for k in node.value.keys:
                if isinstance(k, ast.Constant):
                    keys.add(k.value)
            return keys, node.lineno
    return None, 1


def _func_wire_refs(src: SourceFile, fn_name: str):
    """(found_fn, helper names referenced) for one lowering function:
    every ``X.helper`` / bare ``helper`` reference inside its body."""
    for node in src.tree.body:
        if isinstance(node, ast.FunctionDef) and node.name == fn_name:
            refs = set()
            for sub in ast.walk(node):
                if (
                    isinstance(sub, ast.Attribute)
                    and sub.attr in _WIRE_LANE_HELPERS
                ):
                    refs.add(sub.attr)
                elif (
                    isinstance(sub, ast.Name)
                    and sub.id in _WIRE_LANE_HELPERS
                ):
                    refs.add(sub.id)
            return node, refs
    return None, set()


def _cmdopcode_refs(src: SourceFile):
    """Every ``CmdOpcode.<NAME>`` attribute referenced in a module (the
    presence evidence that its decode path handles the opcode)."""
    refs = set()
    for node in src.nodes:
        if isinstance(node, ast.Attribute):
            base = node.value
            if isinstance(base, ast.Name) and base.id == "CmdOpcode":
                refs.add(node.attr)
            elif (
                isinstance(base, ast.Attribute)
                and base.attr == "CmdOpcode"
            ):
                refs.add(node.attr)
    return refs


def check_cmdring_slot_layout(sources: List[SourceFile]) -> List[Finding]:
    """Encoder and sequencer must agree on the slot layout AND the
    opcode space from ONE definition each:

    * ``constants.CMDRING_FIELDS``/``CMDRING_SLOT_WORDS`` must be
      well-formed (dense, unique, in-bounds int indices); the cmdring
      modules may not REDEFINE any canonical layout name with a local
      literal (aliasing the imported table is fine), and every string
      subscript into a fields-table alias must name a field the
      canonical table defines — a typo'd or locally-invented field
      silently decodes the wrong word on device;
    * ``constants.CmdOpcode`` must be dense unique int values from 0
      (the sequencer's range-check status path depends on density);
    * every executable opcode (non-NOP/HALT) must appear as a value of
      the ``CMDRING_OPCODES`` Operation map (the engine's eligibility
      table covers the space) AND be referenced by the decode module's
      shared epilogue (``ops/pallas/cmdring.py`` — both lowerings run
      that one decode loop, so presence there is presence in both):
      the cross-file guarantee that growing the enum without wiring a
      lowering fails the tree, not a workload."""
    root = package_root()
    findings: List[Finding] = []
    consts = None
    ringmods: List[SourceFile] = []
    decode_mod = None
    lane_mod = None
    for src in sources:
        mod = _module_name(src.path, root)
        if mod == "accl_tpu.constants":
            consts = src
        rel = os.path.relpath(os.path.abspath(src.path), root)
        rel = rel.replace(os.sep, "/")
        if rel in _CMDRING_MODULES:
            ringmods.append(src)
        if rel == _CMDRING_DECODE_MODULE:
            decode_mod = src
        if rel == _WIRE_LANE_MODULE:
            lane_mod = src
    if consts is None:
        return findings  # partial-scope run without constants.py
    fields, slot_words = _cmdring_table(consts)
    opcodes, mapped, map_line = _cmdring_opcodes(consts)
    # quantized-wire cross-check: every REGISTERED wire dtype must be
    # handled by BOTH decode-loop lowerings.  Handling is proven
    # structurally: (a) each lowering function routes its wire cast
    # through the shared lane machinery (ops/wire helpers), so one lane
    # table serves both; (b) that table covers every registered lane.
    # A lane only one lowering decodes — or a registered dtype the
    # shared table misses — fails the tree before it can surface as a
    # silent workload fallback.
    lanes, lanes_line = _wire_lane_dtypes(consts)
    if lanes and decode_mod is not None:
        for fn_name in _CMDRING_LOWERING_FUNCS:
            fn_node, refs = _func_wire_refs(decode_mod, fn_name)
            if fn_node is None:
                findings.append(Finding(
                    check="cmdring-slot-layout", path=decode_mod.path,
                    line=1,
                    message=f"decode module lost lowering function "
                            f"{fn_name!r}: the wire-lane cross-check "
                            "anchors on both lowerings by name",
                ))
            elif not refs:
                findings.append(decode_mod.finding(
                    "cmdring-slot-layout", fn_node,
                    f"lowering {fn_name!r} never routes through the "
                    f"shared wire-lane helpers "
                    f"({sorted(_WIRE_LANE_HELPERS)}): a wire dtype "
                    "this lowering decodes privately can diverge from "
                    "the other lowering's lane",
                ))
        if lane_mod is not None:
            table, table_line = _wire_lanes_table(lane_mod)
            if table is None:
                findings.append(Finding(
                    check="cmdring-slot-layout", path=lane_mod.path,
                    line=1,
                    message="ops/wire.py lost its literal WIRE_LANES "
                            "table — the registered-lane coverage "
                            "cross-check reads it",
                ))
            else:
                missing = sorted(set(lanes.values()) - table)
                if missing:
                    findings.append(Finding(
                        check="cmdring-slot-layout",
                        path=lane_mod.path, line=table_line,
                        message=f"registered wire dtypes {missing} "
                                "(constants.WIRE_LANE_DTYPES) missing "
                                "from the shared WIRE_LANES table: "
                                "both lowerings would fall back on "
                                "them",
                    ))
    if opcodes is not None and ringmods:
        vals = list(opcodes.values())
        if (
            not all(isinstance(v, int) for v in vals)
            or len(set(vals)) != len(vals)
            or sorted(vals) != list(range(len(vals)))
        ):
            findings.append(Finding(
                check="cmdring-slot-layout", path=consts.path, line=1,
                message=f"CmdOpcode values {sorted(vals)} must be "
                        "dense, unique ints from 0 — the sequencer's "
                        "status range-check depends on density",
            ))
        executable = set(opcodes) - set(_CMDRING_MARKER_OPCODES)
        if mapped is not None:
            missing_map = sorted(executable - mapped)
            if missing_map:
                findings.append(Finding(
                    check="cmdring-slot-layout", path=consts.path,
                    line=map_line,
                    message=f"CMDRING_OPCODES maps no Operation onto "
                            f"{missing_map}: the engine can never "
                            "encode these opcodes — dead enum growth",
                ))
        if decode_mod is not None:
            refs = _cmdopcode_refs(decode_mod)
            missing_dec = sorted(executable - refs)
            if missing_dec:
                findings.append(Finding(
                    check="cmdring-slot-layout", path=decode_mod.path,
                    line=1,
                    message=f"decode module never references CmdOpcode "
                            f"{missing_dec}: both lowerings run this "
                            "module's decode loop, so an unreferenced "
                            "opcode is an unimplemented one",
                ))
    if fields is None or slot_words is None:
        if ringmods:  # the ring exists but its contract table is gone
            findings.append(Finding(
                check="cmdring-slot-layout", path=consts.path, line=1,
                message="constants.py lost the literal CMDRING_FIELDS/"
                        "CMDRING_SLOT_WORDS table the encoder and "
                        "sequencer decode slots from",
            ))
        return findings
    # table well-formedness: dense unique int indices inside the slot
    idxs = list(fields.values())
    if (
        not all(isinstance(i, int) for i in idxs)
        or len(set(idxs)) != len(idxs)
        or any(i < 0 or i >= slot_words for i in idxs)
        or sorted(idxs) != list(range(len(idxs)))
    ):
        findings.append(Finding(
            check="cmdring-slot-layout", path=consts.path, line=1,
            message=f"CMDRING_FIELDS indices {sorted(idxs)} must be "
                    f"dense, unique ints in [0, CMDRING_SLOT_WORDS="
                    f"{slot_words})",
        ))
    for src in ringmods:
        # aliases of the canonical fields table in this module
        aliases = set()
        for node in src.nodes:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if not isinstance(tgt, ast.Name):
                    continue
                val = node.value
                refs_canonical = (
                    isinstance(val, ast.Name)
                    and val.id == "CMDRING_FIELDS"
                ) or (
                    isinstance(val, ast.Attribute)
                    and val.attr == "CMDRING_FIELDS"
                )
                if refs_canonical:
                    aliases.add(tgt.id)
                elif tgt.id in _CMDRING_CANONICAL_NAMES:
                    findings.append(src.finding(
                        "cmdring-slot-layout", node,
                        f"{tgt.id!r} redefined locally: the slot layout "
                        f"has exactly one definition (constants.py); "
                        f"import it instead of re-deriving",
                    ))
        aliases.add("CMDRING_FIELDS")
        for node in src.nodes:
            if not isinstance(node, ast.Subscript):
                continue
            base = node.value
            base_name = (
                base.id if isinstance(base, ast.Name)
                else base.attr if isinstance(base, ast.Attribute)
                else None
            )
            if base_name not in aliases:
                continue
            key = node.slice
            if isinstance(key, ast.Constant) and isinstance(
                key.value, str
            ) and key.value not in fields:
                findings.append(src.finding(
                    "cmdring-slot-layout", node,
                    f"slot field {key.value!r} is not in "
                    f"constants.CMDRING_FIELDS ({sorted(fields)}): "
                    f"encoder and sequencer must agree on one table",
                ))
    return findings


# ---------------------------------------------------------------------------
# postmortem-path
# ---------------------------------------------------------------------------

#: facade error codes the postmortem plane covers: every ACCLError the
#: facade raises with one of these must reach the BlackBox capture hook
_POSTMORTEM_ERROR_CODES = frozenset((
    "CONTRACT_VIOLATION", "RANK_EVICTED", "DEADLOCK_SUSPECTED",
))

#: a call whose terminal name is one of these counts as reaching the
#: postmortem machinery
_POSTMORTEM_NAMES = frozenset((
    "_structured_failure", "capture",
))

#: the module the rule scopes to (the facade owns the covered raises;
#: engines surface codes through Request retcodes, which the facade's
#: _check_failed funnels)
_POSTMORTEM_MODULE = "core.py"


def _postmortem_code_of(node: ast.AST) -> Optional[str]:
    """The covered ErrorCode name when ``node`` constructs
    ``ACCLError(ErrorCode.<covered>, ...)``; None otherwise."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    name = (
        f.id if isinstance(f, ast.Name)
        else f.attr if isinstance(f, ast.Attribute) else None
    )
    if name != "ACCLError" or not node.args:
        return None
    code = node.args[0]
    if (
        isinstance(code, ast.Attribute)
        and isinstance(code.value, ast.Name)
        and code.value.id == "ErrorCode"
        and code.attr in _POSTMORTEM_ERROR_CODES
    ):
        return code.attr
    return None


def check_postmortem_path(sources: List[SourceFile]) -> List[Finding]:
    """Every facade construction of a covered structured-failure
    ACCLError (CONTRACT_VIOLATION / RANK_EVICTED / DEADLOCK_SUSPECTED)
    must reach the BlackBox hook (``_structured_failure`` /
    ``capture``) within a depth-bounded walk of the same-module call
    graph — the drain-before-config machinery applied to the
    postmortem contract: a covered failure that skips the hook dies
    with only the local flight-recorder tail, exactly the evidence
    loss the bundle plane exists to remove."""
    findings: List[Finding] = []
    for src in sources:
        if not src.path.replace("\\", "/").endswith(
            "accl_tpu/" + _POSTMORTEM_MODULE
        ):
            continue
        fns: Dict[str, List[ast.AST]] = {}
        for node in src.nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fns.setdefault(node.name, []).append(node)
        called_cache: Dict[int, Set[str]] = {}

        def _called(f):
            got = called_cache.get(id(f))
            if got is None:
                got = called_cache[id(f)] = _called_names(f)
            return got

        for node in src.nodes:
            code = _postmortem_code_of(node)
            if code is None:
                continue
            # the function whose body constructs the error is the walk
            # entry (innermost enclosing function)
            entry = None
            for name, defs in fns.items():
                for fn in defs:
                    if fn.lineno <= node.lineno <= getattr(
                        fn, "end_lineno", fn.lineno
                    ):
                        if entry is None or fn.lineno > entry.lineno:
                            entry = fn
            if entry is None:
                findings.append(src.finding(
                    "postmortem-path", node,
                    f"module-scope ACCLError(ErrorCode.{code}) can "
                    f"never reach the BlackBox hook",
                ))
                continue
            reached = False
            seen: Set[int] = set()
            frontier = [entry]
            for _ in range(4):  # entry + 3 levels of same-module calls
                nxt = []
                for f in frontier:
                    called = _called(f)
                    if called & _POSTMORTEM_NAMES:
                        reached = True
                        break
                    for c in called:
                        for cand in fns.get(c, ()):
                            if id(cand) not in seen:
                                seen.add(id(cand))
                                nxt.append(cand)
                if reached or not nxt:
                    break
                frontier = nxt
            if not reached:
                findings.append(src.finding(
                    "postmortem-path", node,
                    f"{entry.name!r} raises ACCLError(ErrorCode.{code}) "
                    f"but never reaches the BlackBox hook "
                    f"({', '.join(sorted(_POSTMORTEM_NAMES))}); covered "
                    f"structured failures must capture their evidence "
                    f"bundle",
                ))
    return findings


CROSS_FILE_CHECKS = {
    "jax-free-module": check_jax_free_modules,
    "drain-before-config": check_drain_before_config,
    "cmdring-slot-layout": check_cmdring_slot_layout,
    "postmortem-path": check_postmortem_path,
}
