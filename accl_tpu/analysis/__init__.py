"""acclint: the project-invariant static analyzer + lock-order detector.

Usage::

    python -m accl_tpu.analysis            # analyze the package, report
    python -m accl_tpu.analysis --check    # quiet gate mode (CI / bench)
    python -m accl_tpu.analysis --json     # machine-readable findings

    from accl_tpu.analysis import run_checks
    findings = [f for f in run_checks() if not f.suppressed]

Checks (each individually suppressible with
``# acclint: allow[<check>] <reason>``):

========================  ==================================================
unbounded-wait            blocking acquire/wait/join/get without a timeout
jax-free-module           overlap/telemetry/faults/plans/constants/
                          contract/monitor must import without jax/numpy
                          at module scope
timer-discipline          no time.time() windows; use utils.timing
spmd-uniformity           @spmd_uniform functions must not branch on
                          process-local state
collective-sequence       collective op choice / count / root / tag must
                          not derive from rank-varying values (the static
                          half of the contract plane; also covers
                          tests/shared_scenarios.py)
thread-naming             threading.Thread(...) under accl_tpu must pass
                          name="accl-..." (the conftest excepthook guard
                          keys on the prefix)
metric-naming             registry metric names (.inc / gauge) must
                          carry the accl_ prefix (the scrape endpoint
                          exposes them verbatim)
drain-before-config       config writes / soft_reset reach a drain call
error-context             raised ACCLError carries structured details
========================  ==================================================

The dynamic lock-order registry (``accl_tpu.analysis.lockorder``) is
the runtime companion: ``ACCL_LOCKCHECK=1`` wraps project locks and
fails the test session on lock-order cycles or unreviewed edges vs the
committed ``tests/lock_hierarchy.json``.

Zero dependencies beyond the stdlib; importing this package must never
pull jax/numpy (it runs in CI shells and jax-free rank processes).
"""

from __future__ import annotations

from typing import Iterable, List, Optional

from .astchecks import PER_FILE_CHECKS as _AST_CHECKS
from .base import Finding, iter_source_files, load_source, package_root
from .graph import CROSS_FILE_CHECKS
from .markers import spmd_uniform  # noqa: F401  (re-export)
from .spmdseq import check_collective_sequence, extra_scope

#: per-file checks: the astchecks set plus the SPMD sequence analysis
#: (accl_tpu.analysis.spmdseq — the static half of the contract plane)
PER_FILE_CHECKS = dict(
    _AST_CHECKS, **{"collective-sequence": check_collective_sequence}
)

__all__ = [
    "Finding",
    "CHECKS",
    "run_checks",
    "spmd_uniform",
    "package_root",
]

#: every named check, in report order
CHECKS = tuple(PER_FILE_CHECKS) + tuple(CROSS_FILE_CHECKS)


def run_checks(
    paths: Optional[Iterable[str]] = None,
    checks: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Run the named ``checks`` (default: all) over ``paths`` (default:
    the accl_tpu package).  Returns EVERY finding, suppressed ones
    included — gate callers filter on ``not f.suppressed``."""
    selected = set(checks) if checks is not None else set(CHECKS)
    unknown = selected - set(CHECKS)
    if unknown:
        raise ValueError(
            f"unknown checks: {sorted(unknown)} (known: {sorted(CHECKS)})"
        )
    findings: List[Finding] = []

    def _load(path):
        """Parse one file, appending its parse / suppression-syntax
        findings; returns the SourceFile or None (shared by the main
        scope and the extra-scope loops)."""
        src, parse_finding = load_source(path)
        if parse_finding is not None:
            findings.append(parse_finding)
            return None
        for line in src.bad_suppressions:
            findings.append(Finding(
                check="suppression-syntax", path=src.path, line=line,
                message="acclint suppression without a reason does not "
                        "apply; write '# acclint: allow[check] <why>'",
            ))
        return src

    sources = []
    for path in iter_source_files(paths):
        src = _load(path)
        if src is not None:
            sources.append(src)
    for name, fn in PER_FILE_CHECKS.items():
        if name not in selected:
            continue
        for src in sources:
            findings.extend(fn(src))
    if paths is None and "collective-sequence" in selected:
        # the sequence contract also covers the shared scenario library
        # outside the package (tests/shared_scenarios.py): only the
        # collective-sequence check applies there (the tests' own style
        # is not the package's), plus suppression-syntax — a reasonless
        # allow[] must be flagged wherever suppressions are honored
        for path in extra_scope():
            src = _load(path)
            if src is not None:
                findings.extend(check_collective_sequence(src))
    for name, fn in CROSS_FILE_CHECKS.items():
        if name not in selected:
            continue
        findings.extend(fn(sources))
    findings.sort(key=lambda f: (f.path, f.line, f.check))
    return findings
