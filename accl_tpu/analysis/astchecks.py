"""acclint per-file AST checks.

Each check is a function ``(SourceFile) -> list[Finding]`` registered in
``PER_FILE_CHECKS``; the cross-file checks (import graph, drain paths)
live in :mod:`accl_tpu.analysis.graph`.

The checks encode invariants this project has paid review tax for at
least once each:

* **unbounded-wait** — PR 5's review pass hand-hunted waits with no
  deadline across five drain points; a blocking primitive without a
  timeout turns any wedged peer/device into a wedged host thread, and
  the facade's deadlock detector can only fire if every layer below it
  stays bounded.
* **timer-discipline** — PR 4's audit removed every ``time.time()``
  duration window (wall clocks step under NTP; benches and watchdogs
  must use the monotonic clocks in ``utils.timing``).
* **error-context** — PR 2 introduced structured ``ACCLError.details``;
  a bare ACCLError loses the op/comm/peer facts that make chaos-plane
  failures diagnosable without a live session.
* **spmd-uniformity** — the bug class PR 1's batch-fusion guard dodged:
  inside a function marked ``@spmd_uniform`` (it runs identically on
  every rank of an SPMD program stream), branching on process-local
  state (rank, buffer identity/aliasing, health maps) desynchronizes
  the ranks' program streams and wedges the mesh.
"""

from __future__ import annotations

import ast
from typing import List

from .base import Finding, SourceFile

__all__ = [
    "PER_FILE_CHECKS",
    "check_unbounded_wait",
    "check_timer_discipline",
    "check_error_context",
    "check_spmd_uniformity",
    "check_thread_naming",
    "check_metric_naming",
]


# ---------------------------------------------------------------------------
# unbounded-wait
# ---------------------------------------------------------------------------

#: blocking attribute-calls that accept a deadline and run forever
#: without one: Lock/RLock/Semaphore.acquire, Event/Condition.wait,
#: Condition.wait_for, Thread/Process.join, queue.Queue.get
_BLOCKING_ATTRS = ("acquire", "wait", "wait_for", "join", "get")


def _is_unbounded_timeout(node: ast.AST, negative_blocks: bool) -> bool:
    """Is this timeout VALUE a block-forever spelling?  ``None`` always
    is; for ``Lock/RLock.acquire`` a negative number (-1, the default)
    also means wait forever (``negative_blocks``), while the other
    primitives raise or return immediately on negatives."""
    if isinstance(node, ast.Constant) and node.value is None:
        return True
    if not negative_blocks:
        return False
    if (
        isinstance(node, ast.UnaryOp)
        and isinstance(node.op, ast.USub)
        and isinstance(node.operand, ast.Constant)
        and isinstance(node.operand.value, (int, float))
    ):
        return True  # literal -N
    return False


def _has_timeout(call: ast.Call, attr: str) -> bool:
    for kw in call.keywords:
        if kw.arg == "timeout":
            return not _is_unbounded_timeout(
                kw.value, negative_blocks=(attr == "acquire")
            )
    return False


def _is_none(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value is None


def check_unbounded_wait(src: SourceFile) -> List[Finding]:
    out: List[Finding] = []
    for node in src.nodes:
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
        ):
            continue
        attr = node.func.attr
        if attr not in _BLOCKING_ATTRS:
            continue
        if _has_timeout(node, attr):
            continue
        pos = [a for a in node.args if not isinstance(a, ast.Starred)]
        none_timeout_kw = any(
            kw.arg == "timeout" and _is_unbounded_timeout(
                kw.value, negative_blocks=(attr == "acquire")
            )
            for kw in node.keywords
        )
        flag = False
        if attr in ("wait", "join", "get"):
            # zero args (or an explicit None timeout) blocks forever;
            # one non-None positional is a timeout — or a str.join /
            # dict.get operand, which is not a blocking call at all
            flag = (
                (not pos and not node.keywords)
                or (len(pos) == 1 and _is_none(pos[0]))
                or none_timeout_kw
            )
            if attr == "get":
                if node.keywords and not none_timeout_kw:
                    flag = False  # dict.get(k, default=...)-style
                # ...but the BLOCKING queue forms must still flag:
                # get(True) / get(block=True) with no timeout
                if (
                    len(pos) == 1
                    and isinstance(pos[0], ast.Constant)
                    and pos[0].value is True
                ) or any(
                    kw.arg == "block"
                    and not (
                        isinstance(kw.value, ast.Constant)
                        and kw.value.value is False
                    )
                    for kw in node.keywords
                ):
                    flag = True
        elif attr == "wait_for":
            # Condition.wait_for(predicate) with no timeout
            flag = len(pos) == 1
        elif attr == "acquire":
            # acquire() / acquire(True) / timeout=None block forever;
            # acquire(False) and blocking=False are non-blocking probes
            blocking_false = (
                pos
                and isinstance(pos[0], ast.Constant)
                and pos[0].value is False
            ) or any(
                kw.arg == "blocking"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is False
                for kw in node.keywords
            )
            if blocking_false:
                flag = False
            elif none_timeout_kw:
                flag = True
            elif not pos and not node.keywords:
                flag = True
            elif (
                len(pos) == 1
                and isinstance(pos[0], ast.Constant)
                and pos[0].value is True
            ):
                flag = True
            elif len(pos) == 2 and _is_unbounded_timeout(
                pos[1], negative_blocks=True
            ):
                flag = True  # acquire(True, -1) / acquire(True, None)
            else:
                flag = any(kw.arg == "blocking" for kw in node.keywords)
        if flag:
            # anchor on the attribute access itself: in a multi-line
            # chained call the `.wait()` line is where the suppression
            # naturally sits, not the chain's first line
            anchor = getattr(node.func, "end_lineno", None) or node.lineno
            out.append(src.finding(
                "unbounded-wait", anchor,
                f".{attr}() without a timeout can block forever; pass a "
                f"deadline (see overlap.drain_deadline_s) or suppress "
                f"with the audited reason",
            ))
    return out


# ---------------------------------------------------------------------------
# timer-discipline
# ---------------------------------------------------------------------------


def check_timer_discipline(src: SourceFile) -> List[Finding]:
    """Ban ``time.time()`` (and ``from time import time``): wall clocks
    step; every duration window must use ``utils.timing`` /
    ``time.monotonic`` / ``time.perf_counter_ns``."""
    out: List[Finding] = []
    fn_aliases = set()      # names bound to the time.time FUNCTION
    mod_aliases = {"time"}  # names bound to the time MODULE (any alias)
    for node in src.nodes:
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "time":
                    fn_aliases.add(alias.asname or "time")
                    out.append(src.finding(
                        "timer-discipline", node,
                        "'from time import time' imports the wall clock; "
                        "use utils.timing.Timer or time.monotonic / "
                        "time.perf_counter_ns",
                    ))
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "time":
                    mod_aliases.add(alias.asname or "time")
    for node in src.nodes:
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        wall = (
            isinstance(f, ast.Attribute)
            and f.attr == "time"
            and isinstance(f.value, ast.Name)
            and f.value.id in mod_aliases
        ) or (
            isinstance(f, ast.Name) and f.id in fn_aliases
        )
        if wall:
            out.append(src.finding(
                "timer-discipline", node,
                "time.time() is a wall clock (steps under NTP); use "
                "utils.timing.Timer / time.monotonic / perf_counter_ns "
                "for windows, or suppress for genuine wall timestamps",
            ))
    return out


# ---------------------------------------------------------------------------
# error-context
# ---------------------------------------------------------------------------


def check_error_context(src: SourceFile) -> List[Finding]:
    """Every constructed ACCLError must carry structured ``details``
    (PR 2's failure model: op/comm/peer/attempts, PR 4's flight-recorder
    tail all ride there — a bare message is not diagnosable)."""
    out: List[Finding] = []
    for node in src.nodes:
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = (
            f.id if isinstance(f, ast.Name)
            else f.attr if isinstance(f, ast.Attribute) else None
        )
        if name != "ACCLError":
            continue
        if len(node.args) >= 3:
            continue  # positional details
        if any(kw.arg == "details" for kw in node.keywords):
            continue
        out.append(src.finding(
            "error-context", node,
            "ACCLError without details=: attach the structured context "
            "(op/comm/peer/...) that makes the failure diagnosable",
        ))
    return out


# ---------------------------------------------------------------------------
# spmd-uniformity
# ---------------------------------------------------------------------------

#: terminal identifiers that are process-local by construction: branch
#: on them inside an @spmd_uniform function and the ranks' program
#: streams diverge
_SPMD_LOCAL_NAMES = frozenset((
    "rank", "local_rank", "world_rank",
    "is_dummy", "is_host_only",  # buffer identity (DummyBuffer on
    # non-roots, host staging): PR 1's fusion-guard bug class
))
_SPMD_LOCAL_SUBSTR = ("health",)


def _marked_spmd(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", ()):
        d = dec.func if isinstance(dec, ast.Call) else dec
        name = (
            d.id if isinstance(d, ast.Name)
            else d.attr if isinstance(d, ast.Attribute) else None
        )
        if name == "spmd_uniform":
            return True
    return False


def _local_state_refs(test: ast.AST) -> List[str]:
    refs: List[str] = []
    for sub in ast.walk(test):
        term = None
        if isinstance(sub, ast.Attribute):
            term = sub.attr
        elif isinstance(sub, ast.Name):
            term = sub.id
        elif isinstance(sub, ast.Call):
            f = sub.func
            if isinstance(f, ast.Name) and f.id == "id":
                refs.append("id()")  # object identity is per-process
            continue
        if term is None:
            continue
        if term in _SPMD_LOCAL_NAMES or any(
            s in term.lower() for s in _SPMD_LOCAL_SUBSTR
        ):
            refs.append(term)
    return refs


def check_spmd_uniformity(src: SourceFile) -> List[Finding]:
    out: List[Finding] = []
    for fn in ast.walk(src.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not _marked_spmd(fn):
            continue
        for node in ast.walk(fn):
            test = None
            if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                test = node.test
            elif isinstance(node, ast.Assert):
                test = node.test
            if test is None:
                continue
            refs = _local_state_refs(test)
            if refs:
                out.append(src.finding(
                    "spmd-uniformity", node,
                    f"@spmd_uniform function {fn.name!r} branches on "
                    f"process-local state ({', '.join(sorted(set(refs)))}); "
                    f"divergent branches desynchronize the ranks' program "
                    f"streams",
                ))
    return out


# ---------------------------------------------------------------------------
# thread-naming
# ---------------------------------------------------------------------------


def check_thread_naming(src: SourceFile) -> List[Finding]:
    """Every ``threading.Thread(...)`` created under accl_tpu must pass
    ``name="accl-..."``: the conftest excepthook guard (which fails any
    test that leaks an exception on a background thread) keys on the
    ``accl-`` prefix, so an unnamed thread silently bypasses it — PR 6
    fixed the existing ones by hand; this keeps it machine-checked."""
    out: List[Finding] = []
    # names the Thread class / threading module are bound to in this
    # module, INCLUDING aliases — 'import threading as _th' or 'from
    # threading import Thread as T' must not silently bypass the guard
    # the check exists to make unbypassable
    thread_names: set = set()
    module_names = {"threading"}
    for n in src.nodes:
        if isinstance(n, ast.ImportFrom) and n.module == "threading":
            for a in n.names:
                if a.name == "Thread":
                    thread_names.add(a.asname or "Thread")
        elif isinstance(n, ast.Import):
            for a in n.names:
                if a.name == "threading":
                    module_names.add(a.asname or "threading")
    for node in src.nodes:
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        is_thread = (
            isinstance(f, ast.Attribute)
            and f.attr == "Thread"
            and isinstance(f.value, ast.Name)
            and f.value.id in module_names
        ) or (isinstance(f, ast.Name) and f.id in thread_names)
        if not is_thread:
            continue
        name_kw = next(
            (kw for kw in node.keywords if kw.arg == "name"), None
        )
        if name_kw is None:
            out.append(src.finding(
                "thread-naming", node,
                "threading.Thread(...) without name=: the conftest "
                "excepthook guard only covers 'accl-*' threads; pass "
                "name=\"accl-<role>\"",
            ))
            continue
        v = name_kw.value
        literal = None
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            literal = v.value
        elif isinstance(v, ast.JoinedStr) and v.values and isinstance(
            v.values[0], ast.Constant
        ) and isinstance(v.values[0].value, str):
            literal = v.values[0].value  # f"accl-{...}" prefix
        if literal is not None and not literal.startswith("accl-"):
            out.append(src.finding(
                "thread-naming", node,
                f"thread name {literal!r} does not start with 'accl-': "
                f"the conftest excepthook guard keys on that prefix",
            ))
    return out


# ---------------------------------------------------------------------------
# metric-naming
# ---------------------------------------------------------------------------

#: call names whose first string argument is a registry metric name:
#: MetricsRegistry.inc / the exporter's gauge() emitter.  (record_call's
#: counter keys are literal tuples inside telemetry.py itself and carry
#: the prefix by construction.)
_METRIC_CALL_NAMES = frozenset(("inc", "gauge"))
_METRIC_PREFIX = "accl_"


def _literal_prefix(node: ast.AST):
    """The leading literal text of a str constant or f-string, or None
    when the first piece is dynamic (nothing checkable)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr) and node.values and isinstance(
        node.values[0], ast.Constant
    ) and isinstance(node.values[0].value, str):
        return node.values[0].value
    return None


def check_metric_naming(src: SourceFile) -> List[Finding]:
    """Every metric name handed to the registry (``.inc(...)`` /
    ``gauge(...)``) must carry the ``accl_`` prefix: the scrape
    endpoint exposes the registry verbatim, and an unprefixed metric
    collides with every other exporter on the Prometheus server —
    operators filter dashboards and alerts on the prefix."""
    out: List[Finding] = []
    for node in src.nodes:
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        name = (
            f.id if isinstance(f, ast.Name)
            else f.attr if isinstance(f, ast.Attribute) else None
        )
        if name not in _METRIC_CALL_NAMES:
            continue
        literal = _literal_prefix(node.args[0])
        if literal is None:
            # dynamic first piece: nothing to check statically (dict
            # .inc lookalikes pass a variable; real metric sites in
            # this tree all start with a literal)
            continue
        if not literal.startswith(_METRIC_PREFIX):
            # `inc` is a common method name (collections.Counter-style
            # helpers): only flag when the literal LOOKS like a metric
            # name (a snake_case identifier) to keep false positives
            # out of non-registry call sites
            if name == "inc" and not literal.replace("_", "").isalnum():
                continue
            out.append(src.finding(
                "metric-naming", node,
                f"metric name {literal!r} does not start with "
                f"'{_METRIC_PREFIX}': every registry metric must carry "
                f"the project prefix so scrapes stay filterable",
            ))
    return out


PER_FILE_CHECKS = {
    "unbounded-wait": check_unbounded_wait,
    "timer-discipline": check_timer_discipline,
    "error-context": check_error_context,
    "spmd-uniformity": check_spmd_uniformity,
    "thread-naming": check_thread_naming,
    "metric-naming": check_metric_naming,
}
