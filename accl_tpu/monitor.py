"""The live observability service: continuous monitoring over the
telemetry plane.

Role model: the reference's observability is *always on and live* — a
free-running hardware perf counter copied into exchange memory on every
call, ``ACCL::get_duration``, the 27-bit per-call error bitmask.  PR 4
built the signals (flight recorder, metrics registry, trace export) but
left them pull-on-demand and single-rank: you could not watch a running
job, and nothing correlated windows *across* ranks — so a persistently
slow rank was invisible until it became a timeout.  This module makes
the plane continuous:

* **Scrape service** (:class:`MonitorServer`) — an opt-in stdlib
  ``http.server`` on an ``accl-monitor`` thread serving ``/metrics``
  (Prometheus text rendered from the existing registry), ``/snapshot``
  (the ``telemetry_snapshot()`` JSON) and ``/trace`` (the rolling
  Chrome-trace window).  Armed by ``ACCL.start_monitor()`` or the
  ``ACCL_MONITOR_PORT`` env var.
* **Streaming trace export** (:class:`TraceStreamWriter`) — a bounded
  rolling-file writer (``ACCL_TRACE_STREAM=<dir>``) that continuously
  flushes completed flight-recorder records as Perfetto-loadable trace
  files (each file is a complete JSON document, written atomically), so
  a crash leaves a loadable timeline instead of nothing.
* **Cross-rank straggler analysis** (:class:`SkewTracker` /
  :class:`SkewJudge`) — two coupled signals, exchanged on the contract
  plane's window cadence (in-process tiers meet on a judge anchored
  exactly like the contract board via ``contract_anchor()``;
  one-process-per-rank fabrics piggyback on outgoing messages like the
  contract digest stamp):

  - **wait baselines** (all four tiers): per-collective wait durations
    recorded at completion fold into per-rank EWMA *relative-wait*
    baselines — the dashboard's who-waits-how-much view.  Deliberately
    NOT a conviction signal: a synchronizing collective equalizes
    completion times (a ring diffuses a slow link into every rank's
    wait within one cycle), and fire-and-forget eager sends give
    roots/senders structurally shorter waits than leaves — duration
    lag alone both misses real stragglers and convicts innocent roots.
  - **arrival skew** (fabric tiers): every delivered message carries
    its send timestamp, so each receiver measures per-SOURCE wire
    latency — the direct observable of "rank p's messages arrive
    late", which is what a slow sender/NIC/link actually looks like
    and is immune to the wash-out above.  Window means fold into
    per-rank EWMA latency baselines; a rank persistently beyond BOTH
    the absolute floor and the dominance factor over the runner-up
    yields a structured ``slow_rank`` verdict — majority-grade on
    board tiers (all receivers' observations aggregated by median),
    pairwise on wire tiers (each side blames from its own
    observations — correct on the conforming side, the contract
    plane's pairwise discipline).

  Verdicts surface in ``telemetry_snapshot()["stragglers"]``, as
  Prometheus gauges, and as a ``suspect_slow`` annotation on the
  health map (annotation only — never fail-fast: slowness is an
  operator signal, not a failure).
* **Anomaly watchdog** (:class:`AnomalyWatchdog`) — rolling EWMA
  latency baselines per (op × size bucket) emitting bounded alert
  records into the snapshot when a call regresses past a configurable
  factor of its baseline.

Clock caveat (documented honestly): send timestamps are wall-clock
(``time.time_ns`` — the only clock two processes share), so cross-HOST
latency skew inherits whatever NTP leaves; same-host fabrics (the whole
test matrix) are exact.  The absolute floor and the dominance factor
together keep µs-scale noise from ever convicting anyone — uniform
load produces zero verdicts.

Zero dependencies (stdlib only): this module rides the same jax-free
import closure as ``telemetry``/``contract`` and is machine-checked by
acclint's jax-free-module pass.

Env knobs:

* ``ACCL_MONITOR_PORT=N``         — start the scrape service at handle
  construction (0 = ephemeral; the bound port is in ``capabilities()``)
* ``ACCL_TRACE_STREAM=dir``       — stream completed trace segments
* ``ACCL_TRACE_STREAM_EVENTS=N``  — events per rolling file (def 4096)
* ``ACCL_TRACE_STREAM_FILES=N``   — rolling files kept (default 8)
* ``ACCL_TRACE_STREAM_INTERVAL_S``— flush cadence (default 0.5)
* ``ACCL_SKEW_INTERVAL=N``        — collectives per skew window (def 8)
* ``ACCL_STRAGGLER_FACTOR``       — lag dominance factor (default 4.0)
* ``ACCL_STRAGGLER_MIN_US``       — absolute lag floor (default 200.0)
* ``ACCL_STRAGGLER_WINDOWS``      — consecutive windows to convict (2)
* ``ACCL_ANOMALY_FACTOR``         — latency regression factor (4.0)
* ``ACCL_SCALE_GROW_P99_US``      — tenant p99 high-water for a *grow*
  recommendation (default 50000.0)
* ``ACCL_SCALE_SHRINK_P99_US``    — tenant p99 low-water for a *shrink*
  recommendation (default 1000.0)

Traffic-aware scale advice (:class:`ScaleAdvisor`) closes the loop from
the QoS arbiter's per-tenant latency histograms to the elastic
membership plane — advisory only, the ``suspect_slow`` annotation
discipline: a sustained p99 tail or queue backlog on guaranteed-class
tenants yields a ``grow`` recommendation, a uniformly idle tail yields
``shrink``, and the verdict surfaces in
``telemetry_snapshot()["membership"]["scale_advice"]`` and the
``/membership`` route.  Nothing ever acts on it automatically —
``join_rank``/``evict_rank`` are the operator's calls.
"""

from __future__ import annotations

import json
import os
import statistics
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .contract import anchored

__all__ = [
    "AnomalyWatchdog",
    "BlackBox",
    "Monitor",
    "MonitorServer",
    "ScaleAdvisor",
    "SkewJudge",
    "SkewTracker",
    "TraceStreamWriter",
    "env_port",
    "env_postmortem_dir",
    "judge_for",
    "load_bundle",
]

MONITOR_PORT_ENV = "ACCL_MONITOR_PORT"
TRACE_STREAM_ENV = "ACCL_TRACE_STREAM"
POSTMORTEM_DIR_ENV = "ACCL_POSTMORTEM_DIR"
POSTMORTEM_WAIT_ENV = "ACCL_POSTMORTEM_WAIT_S"
DEFAULT_POSTMORTEM_WAIT_S = 2.0
#: bundle.json layout version (bumped when the artifact shape changes)
BUNDLE_SCHEMA = 1

DEFAULT_SKEW_INTERVAL = 8
DEFAULT_STRAGGLER_FACTOR = 4.0
DEFAULT_STRAGGLER_MIN_US = 200.0
DEFAULT_STRAGGLER_WINDOWS = 2
DEFAULT_ANOMALY_FACTOR = 4.0
ANOMALY_WARMUP = 16
ANOMALY_ALPHA = 0.1
EWMA_ALPHA = 0.5

SCALE_GROW_ENV = "ACCL_SCALE_GROW_P99_US"
SCALE_SHRINK_ENV = "ACCL_SCALE_SHRINK_P99_US"
DEFAULT_SCALE_GROW_P99_US = 50_000.0
DEFAULT_SCALE_SHRINK_P99_US = 1_000.0
#: completed calls a tenant needs before its tail counts (a two-sample
#: histogram's p99 is noise, not pressure)
SCALE_MIN_SAMPLES = 32

#: skew windows / judged markers retained per communicator (a peer far
#: ahead/behind must still find its comparison point — the contract
#: plane's _WINDOW_CAP discipline)
_WINDOW_CAP = 128
_ALERT_CAP = 64
_VERDICT_CAP = 32

#: collectives whose wait durations feed the skew tracker: the contract
#: ops — every rank participates, so cross-rank wait comparison is
#: meaningful (p2p/local ops are rank-asymmetric by design)
SKEW_OPS = frozenset((
    "bcast", "scatter", "gather", "allgather", "reduce", "allreduce",
    "reduce_scatter", "alltoall", "barrier",
))


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int, floor: int = 1) -> int:
    try:
        return max(floor, int(os.environ.get(name, default)))
    except ValueError:
        return default


def env_port(environ=None) -> Optional[int]:
    """The ``ACCL_MONITOR_PORT`` opt-in (read at handle construction);
    None = not set.  0 means "bind an ephemeral port"."""
    raw = (environ or os.environ).get(MONITOR_PORT_ENV)
    if raw is None or raw == "":
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def env_postmortem_dir(environ=None) -> Optional[str]:
    """The ``ACCL_POSTMORTEM_DIR`` opt-in (read at handle
    construction); None/empty = postmortem bundles disabled (the
    always-on cost of the plane is then exactly one None check per
    structured failure)."""
    raw = (environ or os.environ).get(POSTMORTEM_DIR_ENV)
    return raw or None


def _env_wait_s() -> float:
    return max(0.0, _env_float(
        POSTMORTEM_WAIT_ENV, DEFAULT_POSTMORTEM_WAIT_S
    ))


# ---------------------------------------------------------------------------
# postmortem bundles (the flight-data-recorder plane)
# ---------------------------------------------------------------------------


class BlackBox:
    """Automatic postmortem bundles for structured failures.

    On any covered failure path (facade ``ACCLError`` with
    CONTRACT_VIOLATION / RANK_EVICTED / DEADLOCK_SUSPECTED, the
    command-ring failure latch, a membership cutover) the facade calls
    :meth:`capture`: the local evidence (flight-recorder tail +
    telemetry snapshot — which carries ring/mailbox state, the
    membership event ring, skew baselines and contract window digests)
    is snapshotted, reachable peers are solicited for THEIR evidence —
    in process over the anchored registry (the contract-board
    discipline), across processes via a POSTMORTEM wire frame — and
    everything merges into one crash-safe, atomically-written
    ``bundle.json`` whose path rides ``ACCLError.details["postmortem"]``.

    Bounded + best-effort by construction: peer solicitation waits at
    most ``ACCL_POSTMORTEM_WAIT_S`` (default 2 s); dead/partitioned
    peers are documented as ``absent`` in the bundle, never waited out.
    One bundle per failure: captures are latched per failure key
    (counter-asserted), and the latch clears with ``soft_reset`` like
    every other recovery surface.  Disabled (one None check per
    failure) unless ``ACCL_POSTMORTEM_DIR`` is set."""

    def __init__(self, rank: int, world: int,
                 evidence_fn: Callable[[], dict],
                 directory: Optional[str] = None,
                 wait_s: Optional[float] = None,
                 peers_fn: Optional[Callable[[], Dict[int, Any]]] = None,
                 solicit_fn: Optional[Callable[[int], int]] = None,
                 metrics=None):
        self.rank = int(rank)
        self.world = int(world)
        self.directory = (
            directory if directory is not None else env_postmortem_dir()
        )
        self.enabled = bool(self.directory)
        self.wait_s = wait_s if wait_s is not None else _env_wait_s()
        self._evidence_fn = evidence_fn
        # in-process solicitation: {session: evidence_fn} (the anchored
        # registry every rank handle of the process registers into)
        self._peers_fn = peers_fn
        # wire solicitation: sends POSTMORTEM request frames, returns
        # how many peers were asked (replies land via deliver_reply)
        self._solicit_fn = solicit_fn
        self._metrics = metrics
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._latched: Dict[tuple, Optional[str]] = {}
        self._replies: Dict[int, Dict[int, dict]] = {}
        self._token = 0
        self._seq = 0  # bundle-name allocator (monotone, never reused)
        self.bundles_written = 0
        self.solicit_timeouts = 0
        self.last_bundle: Optional[str] = None

    # -- wire reply intake (fabric delivery thread) --------------------------
    def deliver_reply(self, token: int, rank: int, evidence: dict) -> None:
        with self._cv:
            bucket = self._replies.get(int(token))
            if bucket is None:
                return  # late reply after the bounded deadline: dropped
            bucket[int(rank)] = evidence
            self._cv.notify_all()

    def _solicit(self) -> tuple:
        """(peer evidence {session: dict}, absent sessions).  Board
        peers answer synchronously; wire peers get the bounded wait."""
        collected: Dict[int, dict] = {}
        asked: set = set()
        if self._peers_fn is not None:
            try:
                registry = dict(self._peers_fn() or {})
            except Exception:
                registry = {}
            for session, fn in sorted(registry.items()):
                if session == self.rank:
                    continue
                asked.add(session)
                try:
                    collected[session] = fn()
                except Exception as e:  # a wedged peer must not wedge us
                    collected[session] = {
                        "error": f"{type(e).__name__}: {e}"[:200]
                    }
        if self._solicit_fn is not None:
            with self._cv:
                self._token += 1
                token = self._token
                self._replies[token] = {}
            try:
                n_asked = int(self._solicit_fn(token) or 0)
            except Exception:
                n_asked = 0
            if n_asked:
                deadline = time.monotonic() + self.wait_s
                with self._cv:
                    while len(self._replies[token]) < n_asked:
                        rem = deadline - time.monotonic()
                        if rem <= 0:
                            self.solicit_timeouts += 1
                            break
                        self._cv.wait(rem)
                    for r, ev in self._replies[token].items():
                        collected[r] = ev
                        asked.add(r)
            with self._cv:
                self._replies.pop(token, None)
        absent = sorted(
            s for s in range(self.world)
            if s != self.rank and s not in collected
        )
        return collected, absent

    # -- the capture path ----------------------------------------------------
    def capture(self, code: str, context: str = "",
                details: Optional[dict] = None,
                key: Optional[tuple] = None) -> Optional[str]:
        """Write one bundle for this failure (or return the already-
        written one when the failure key is latched).  Never raises —
        a postmortem failure must not mask the failure it documents."""
        if not self.enabled:
            return None
        key = key if key is not None else (str(code),)
        with self._lock:
            if key in self._latched:
                return self._latched[key]
            self._latched[key] = None  # claim: concurrent paths collapse
            # the bundle name is allocated HERE, atomically with the
            # claim: two concurrent captures (distinct keys, same code)
            # must never derive the same directory and clobber each
            # other's bundle.json
            seq = self._seq
            self._seq += 1
        path = None
        try:
            path = self._write_bundle(code, context, details, seq)
        except Exception:  # pragma: no cover - defensive
            import traceback

            traceback.print_exc()
        with self._lock:
            self._latched[key] = path
            if path is not None:
                self.bundles_written += 1
                self.last_bundle = path
        if path is not None and self._metrics is not None:
            try:
                self._metrics.inc("accl_postmortem_bundles_total")
            except Exception:  # pragma: no cover - defensive
                pass
        return path

    def _write_bundle(self, code: str, context: str,
                      details: Optional[dict], seq: int) -> str:
        try:
            local = self._evidence_fn()
        except Exception as e:  # evidence half-missing beats no bundle
            local = {"error": f"{type(e).__name__}: {e}"[:200]}
        peers, absent = self._solicit()
        ranks = {str(self.rank): local}
        for r, ev in sorted(peers.items()):
            ranks[str(r)] = ev
        bundle = {
            "bundle_schema": BUNDLE_SCHEMA,
            "code": str(code),
            "context": str(context),
            "rank": self.rank,
            "world": self.world,
            # wall timestamp on purpose (cross-process artifact naming/
            # correlation needs the shared clock, same as Message.
            # sent_ns) — never used as a duration
            "created_ns": time.time_ns(),
            "ranks": ranks,
            "reachable": sorted(int(r) for r in ranks),
            "absent": absent,
        }
        if details:
            bundle["details"] = _jsonable(details)
        os.makedirs(self.directory, exist_ok=True)
        name = (
            f"accl_postmortem_{str(code).lower()}_rank{self.rank}_{seq:03d}"
        )
        bdir = os.path.join(self.directory, name)
        os.makedirs(bdir, exist_ok=True)
        path = os.path.join(bdir, "bundle.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bundle, f, default=str)
        os.replace(tmp, path)  # crash-safe: the artifact is atomic
        return path

    def reset(self) -> None:
        """soft_reset recovery: clear the per-failure latches (a fresh
        regime's failures deserve fresh bundles); written-bundle
        accounting is lifetime and survives."""
        with self._lock:
            self._latched.clear()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "dir": self.directory,
                "wait_s": self.wait_s,
                "bundles_written": self.bundles_written,
                "solicit_timeouts": self.solicit_timeouts,
                "last_bundle": self.last_bundle,
                "latched": len(self._latched),
            }


def _jsonable(obj):
    """Best-effort JSON-safe copy (ACCLError.details may carry enums /
    numpy scalars; the bundle must always serialize)."""
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        return json.loads(json.dumps(obj, default=str))


def load_bundle(path: str) -> dict:
    """Load + structurally validate one ``bundle.json`` (the test/CI
    surface): raises ValueError on a malformed bundle."""
    with open(path) as f:
        doc = json.load(f)
    for k in ("bundle_schema", "code", "rank", "world", "ranks",
              "reachable", "absent"):
        if k not in doc:
            raise ValueError(f"postmortem bundle missing {k!r}: {path}")
    if not isinstance(doc["ranks"], dict) or not doc["ranks"]:
        raise ValueError(f"postmortem bundle has no rank evidence: {path}")
    return doc


# ---------------------------------------------------------------------------
# cross-rank straggler analysis
# ---------------------------------------------------------------------------


def judge_for(anchor, world: int) -> Optional["SkewJudge"]:
    """The :class:`SkewJudge` shared by every rank handle anchored on
    ``anchor`` — the same anchor discipline as the contract plane's
    ``board_for`` (InProc fabric / XLA gang context); None on
    one-process-per-rank tiers, where each tracker judges locally from
    wire-piggybacked claims instead."""
    return anchored(anchor, "_accl_skew_judge", lambda: SkewJudge(world))


class SkewJudge:
    """Folds per-(comm, window) posts from the ranks into per-rank EWMA
    baselines and standing ``slow_rank`` verdicts.

    One instance is SHARED by every in-process rank handle (board mode,
    via :func:`judge_for`) or PRIVATE per tracker (wire mode) — the
    math is identical either way, which is what makes the seeded-fault
    conviction deterministic: same posts, same verdict.

    Two post streams per window:

    * **wait means** (:meth:`post_wait`) — each rank's mean collective
      wait; folded into relative-wait EWMA baselines (``max - own``,
      how much *less* a rank waited than the slowest-waiting rank).
      Reported, never convicting: synchronizing collectives equalize
      waits and eager fire-and-forget biases roots short.
    * **arrival latency** (:meth:`post_latency`) — each rank's window
      vector of per-SOURCE wire latencies.  When every member's vector
      arrived, source ``p``'s aggregate is the MEDIAN of its receivers'
      observations (one weird receiver cannot frame a peer); a source
      whose aggregate clears the absolute floor AND the dominance
      factor over the runner-up for ``persist`` consecutive windows is
      convicted ``slow_rank``.
    """

    def __init__(self, world: int, factor: Optional[float] = None,
                 min_us: Optional[float] = None,
                 persist: Optional[int] = None):
        self.world = int(world)
        self.factor = (
            factor if factor is not None
            else _env_float("ACCL_STRAGGLER_FACTOR", DEFAULT_STRAGGLER_FACTOR)
        )
        self.min_us = (
            min_us if min_us is not None
            else _env_float("ACCL_STRAGGLER_MIN_US", DEFAULT_STRAGGLER_MIN_US)
        )
        self.persist = (
            persist if persist is not None
            else _env_int("ACCL_STRAGGLER_WINDOWS", DEFAULT_STRAGGLER_WINDOWS)
        )
        self._lock = threading.Lock()
        # (comm, window) -> {rank: mean_wait_us}
        self._wait_posts: Dict[Tuple[int, int], Dict[int, float]] = {}
        # (comm, window) -> {observer: {src: mean_latency_us}}
        self._lat_posts: Dict[Tuple[int, int], Dict[int, dict]] = {}
        self._wait_judged: Dict[int, int] = {}  # comm -> highest window
        self._lat_judged: Dict[int, int] = {}
        self._wait_ewma: Dict[int, Dict[int, float]] = {}
        self._lat_ewma: Dict[int, Dict[int, float]] = {}
        self._streak: Dict[Tuple[int, int], int] = {}
        self._slow: Dict[int, dict] = {}  # comm -> standing verdict
        self.verdicts: List[dict] = []
        self.windows_judged = 0

    @staticmethod
    def _gc(posts: Dict[Tuple[int, int], dict], comm_id: int,
            window: int) -> None:
        floor = window - _WINDOW_CAP
        for k in [k for k in posts if k[0] == comm_id and k[1] < floor]:
            del posts[k]

    def post_wait(self, comm_id: int, window: int, rank: int,
                  mean_us: float, world: Optional[int] = None) -> None:
        """One rank's completed-window mean wait; folds the window into
        the relative-wait EWMA baselines once every member (``world`` =
        the communicator's member count) posted."""
        need = int(world) if world else self.world
        with self._lock:
            if window <= self._wait_judged.get(comm_id, -1):
                return
            key = (comm_id, window)
            posts = self._wait_posts.setdefault(key, {})
            posts[rank] = float(mean_us)
            self._gc(self._wait_posts, comm_id, window)
            if len(posts) < need:
                return
            del self._wait_posts[key]
            self._wait_judged[comm_id] = max(
                self._wait_judged.get(comm_id, -1), window
            )
            mmax = max(posts.values())
            ew = self._wait_ewma.setdefault(comm_id, {})
            for r, m in sorted(posts.items()):
                lag = mmax - m
                prev = ew.get(r)
                ew[r] = round(
                    lag if prev is None
                    else EWMA_ALPHA * lag + (1.0 - EWMA_ALPHA) * prev,
                    3,
                )

    def post_latency(self, comm_id: int, window: int, observer: int,
                     latencies_us: Dict[int, float],
                     world: Optional[int] = None) -> Optional[dict]:
        """One rank's completed-window per-source latency vector; judges
        the window once every member's vector arrived.  Returns the
        (new or standing) verdict for the communicator."""
        need = int(world) if world else self.world
        with self._lock:
            if window <= self._lat_judged.get(comm_id, -1):
                return self._slow.get(comm_id)
            key = (comm_id, window)
            posts = self._lat_posts.setdefault(key, {})
            posts[int(observer)] = {
                int(p): float(v) for p, v in latencies_us.items()
            }
            self._gc(self._lat_posts, comm_id, window)
            if len(posts) < need:
                return self._slow.get(comm_id)
            del self._lat_posts[key]
            self._lat_judged[comm_id] = max(
                self._lat_judged.get(comm_id, -1), window
            )
            self.windows_judged += 1
            return self._judge(comm_id, window, posts)

    def _judge(self, comm_id: int, window: int,
               posts: Dict[int, dict]) -> Optional[dict]:
        """Judge one complete latency window (judge lock held).  Pure
        math over the posts — same posts, same verdict, on every rank."""
        sources: Dict[int, List[float]] = {}
        for observer, vec in posts.items():
            for src, lat in vec.items():
                if src != observer:
                    sources.setdefault(src, []).append(lat)
        if not sources:
            return self._slow.get(comm_id)
        agg = {p: statistics.median(obs) for p, obs in sources.items()}
        ew = self._lat_ewma.setdefault(comm_id, {})
        for p, lat in sorted(agg.items()):
            prev = ew.get(p)
            ew[p] = round(
                lat if prev is None
                else EWMA_ALPHA * lat + (1.0 - EWMA_ALPHA) * prev,
                3,
            )
        if len(agg) < 2:
            # conviction needs a genuine runner-up to dominate: with a
            # single observed source (a 2-rank wire-mode group) the
            # dominance test is vacuous and any fabric whose baseline
            # latency clears the floor — localhost TCP sits at
            # 300-900 us — would convict an innocent peer.  Mirrors
            # the contract plane's "majority needs world >= 3": 2-rank
            # wire groups get EWMA baselines, not verdicts (board
            # tiers aggregate BOTH observers, so world 2 still
            # convicts there).
            return self._slow.get(comm_id)
        ranked = sorted(agg.items(), key=lambda kv: (-kv[1], kv[0]))
        cand, lead = ranked[0]
        runner_up = ranked[1][1]
        beyond = (
            lead >= self.min_us
            and lead >= self.factor * (runner_up + 1.0)
        )
        # "persist CONSECUTIVE windows": every tracked streak on this
        # comm resets except the dominant candidate's — including ranks
        # ABSENT from this window's observations (a source that goes
        # quiet for a window has broken its streak, or two
        # non-consecutive dominant windows would sum to a conviction)
        prev = self._streak.get((comm_id, cand), 0)
        for k in [k for k in self._streak if k[0] == comm_id]:
            self._streak[k] = 0
        if not beyond:
            return self._slow.get(comm_id)
        streak = prev + 1
        self._streak[(comm_id, cand)] = streak
        if streak < self.persist:
            return self._slow.get(comm_id)
        verdict = {
            "kind": "slow_rank",
            "comm": comm_id,
            "rank": cand,
            "window": window,
            "latency_us": round(lead, 1),
            "ewma_latency_us": ew[cand],
            "streak": streak,
            "observed_us": {
                str(p): round(v, 1) for p, v in sorted(agg.items())
            },
            "basis": "majority" if len(posts) > 1 else "pairwise",
        }
        if self._slow.get(comm_id) is None or (
            self._slow[comm_id].get("rank") != cand
        ):
            if len(self.verdicts) < _VERDICT_CAP:
                self.verdicts.append(verdict)
        self._slow[comm_id] = verdict
        return verdict

    def slow_ranks(self, comm_id: int) -> List[int]:
        """Comm-relative ranks under a standing slow_rank verdict — the
        health-map ``suspect_slow`` annotation source."""
        with self._lock:
            v = self._slow.get(comm_id)
            return [v["rank"]] if v is not None else []

    def recovered(self, comm_id: int, rank: int) -> bool:
        """Has ``rank``'s arrival skew recovered?  True when its
        current EWMA latency no longer clears the conviction bar
        (below the absolute floor, or below ``factor`` × the slowest
        other rank) — the membership plane's half-open circuit-breaker
        probe: a demoted rank is re-admitted when this turns true and
        no standing verdict renews."""
        with self._lock:
            ew = self._lat_ewma.get(comm_id) or {}
            lat = ew.get(rank)
            if lat is None:
                return True  # no recent observations: nothing to hold
            if lat < self.min_us:
                return True
            others = [v for r, v in ew.items() if r != rank]
            if not others:
                return True
            return lat < self.factor * (max(others) + 1.0)

    def clear_slow(self, comm_id: int, rank: Optional[int] = None) -> bool:
        """Drop the standing slow_rank verdict (optionally only when it
        names ``rank``) and its streaks — the demotion-restore path:
        re-admission must also lift the health map's ``suspect_slow``
        annotation, or the operator keeps paging on a healed rank."""
        with self._lock:
            v = self._slow.get(comm_id)
            if v is None or (rank is not None and v.get("rank") != rank):
                return False
            del self._slow[comm_id]
            for k in [k for k in self._streak if k[0] == comm_id]:
                self._streak[k] = 0
            return True

    def reset(self) -> None:
        """soft_reset recovery: drop posts, baselines, streaks and
        standing verdicts (the collective recovery point, like the
        contract board's clear)."""
        with self._lock:
            self._wait_posts.clear()
            self._lat_posts.clear()
            self._wait_judged.clear()
            self._lat_judged.clear()
            self._wait_ewma.clear()
            self._lat_ewma.clear()
            self._streak.clear()
            self._slow.clear()
            # the verdict history is about the PRE-reset regime too: a
            # recovered group starts with a clean bill (windows_judged
            # keeps counting — it is lifetime accounting, not state)
            self.verdicts.clear()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "world": self.world,
                "factor": self.factor,
                "min_us": self.min_us,
                "persist_windows": self.persist,
                "windows_judged": self.windows_judged,
                "ewma_wait_lag_us": {
                    str(c): {str(r): v for r, v in sorted(ranks.items())}
                    for c, ranks in sorted(self._wait_ewma.items())
                },
                "ewma_latency_us": {
                    str(c): {str(r): v for r, v in sorted(ranks.items())}
                    for c, ranks in sorted(self._lat_ewma.items())
                },
                "verdicts": [dict(v) for v in self.verdicts],
                "standing": {
                    str(c): dict(v) for c, v in sorted(self._slow.items())
                },
            }


class SkewTracker:
    """One rank handle's end of the straggler exchange.

    Fed from the telemetry plane's completion observer (every tier's
    ``Request.complete`` runs through it); accumulates per-communicator
    wait durations, and at every ``interval``-call window boundary posts
    the window mean to the judge — shared in-process, or local with
    peers' posts arriving as wire-piggybacked claims
    (:meth:`observe_claim`, the contract plane's stamp cadence reused).
    """

    def __init__(self, rank: int, world: int,
                 interval: Optional[int] = None,
                 judge: Optional[SkewJudge] = None):
        self.rank = int(rank)
        self.world = int(world)
        self.interval = (
            interval if interval is not None
            else _env_int("ACCL_SKEW_INTERVAL", DEFAULT_SKEW_INTERVAL)
        )
        self.shared_judge = judge is not None
        self.judge = judge if judge is not None else SkewJudge(world)
        self._lock = threading.Lock()
        # comm -> [count, sum_ns, comm_world, comm_rank]
        self._acc: Dict[int, list] = {}
        # (comm, src) -> [count, sum_latency_ns]: per-source arrival
        # latency observed at delivery, drained at window boundaries
        self._lat: Dict[Tuple[int, int], list] = {}
        # comm -> (window, mean_us): the latest completed window — the
        # wire piggyback stamp (two header fields, zero extra traffic)
        self._stamp: Dict[int, Tuple[int, float]] = {}
        self.samples = 0
        self.latency_samples = 0
        self.windows_posted = 0

    def observe(self, comm_id: int, duration_ns: int,
                comm_rank: Optional[int] = None,
                comm_world: Optional[int] = None) -> None:
        """One completed collective's wait duration (telemetry observer
        fast lane: a dict update under one short lock; the window posts
        happen outside it)."""
        wait_post = None
        lat_post = None
        with self._lock:
            acc = self._acc.get(comm_id)
            if acc is None:
                acc = self._acc[comm_id] = [
                    0, 0,
                    int(comm_world) if comm_world else self.world,
                    int(comm_rank) if comm_rank is not None else self.rank,
                ]
            acc[0] += 1
            acc[1] += int(duration_ns)
            self.samples += 1
            if acc[0] % self.interval == 0:
                window = acc[0] // self.interval - 1
                mean_us = acc[1] / self.interval / 1e3
                acc[1] = 0
                self._stamp[comm_id] = (window, mean_us)
                self.windows_posted += 1
                wait_post = (comm_id, window, acc[3], mean_us, acc[2])
                # drain this comm's per-source latency window alongside
                vec = {}
                for (cid, src), cell in list(self._lat.items()):
                    if cid != comm_id or not cell[0]:
                        continue
                    vec[src] = cell[1] / cell[0] / 1e3
                    cell[0] = cell[1] = 0
                lat_post = (comm_id, window, acc[3], vec, acc[2])
        # judge OUTSIDE the tracker lock (the judge takes its own; no
        # cross-family hold for the lock-order registry to flag)
        if wait_post is not None:
            cid, window, r, mean_us, w = wait_post
            self.judge.post_wait(cid, window, r, mean_us, world=w)
        if lat_post is not None:
            cid, window, r, vec, w = lat_post
            # wire mode judges from this rank's OWN observations only
            # (pairwise basis — the board aggregates all receivers)
            self.judge.post_latency(
                cid, window, r, vec,
                world=w if self.shared_judge else 1,
            )

    def on_message(self, comm_id: int, src: int,
                   latency_ns: Optional[int]) -> None:
        """One delivered message's arrival latency (fabric delivery
        thread; ``latency_ns`` None when the sender did not stamp —
        monitor off on that rank)."""
        if latency_ns is None:
            return
        with self._lock:
            cell = self._lat.get((comm_id, src))
            if cell is None:
                cell = self._lat[(comm_id, src)] = [0, 0]
            cell[0] += 1
            cell[1] += max(0, int(latency_ns))
            self.latency_samples += 1

    def begin_comm(self, comm_id: int, comm_rank: int,
                   comm_world: int) -> None:
        """Register a communicator's membership up front (the facade
        calls this at handle construction and on create_communicator),
        so piggybacked claims arriving BEFORE this rank's first
        completion on the comm resolve against the real comm-relative
        identity and member count instead of the world fallbacks."""
        with self._lock:
            acc = self._acc.get(comm_id)
            if acc is None:
                self._acc[comm_id] = [0, 0, int(comm_world), int(comm_rank)]
            else:
                acc[2], acc[3] = int(comm_world), int(comm_rank)

    # -- wire piggyback (the contract stamp cadence, reused) -----------------
    def stamp(self, comm_id: int) -> Tuple[int, float]:
        """(window, mean_wait_us) of the latest completed skew window —
        stamped onto outgoing wire messages.  window -1 = nothing
        completed yet (receivers skip).  Lock-free read on the per-send
        hot path: ``_stamp`` values are immutable tuples replaced under
        the tracker lock, so a racing reader sees the old or the new
        stamp — both valid — without paying a lock per wire message."""
        s = self._stamp.get(comm_id)
        return s if s is not None else (-1, 0.0)

    def observe_claim(self, comm_id: int, src_rank: int, window: int,
                      mean_us: float) -> None:
        """A peer's piggybacked wait-window claim (fabric delivery
        thread).  ``src_rank`` is COMM-relative (the wire message's src
        field).  Feeds the relative-wait baselines; the latency signal
        needs no claim — each receiver observes it directly."""
        if window < 0:
            return
        with self._lock:
            acc = self._acc.get(comm_id)
            world = acc[2] if acc is not None else self.world
            me = acc[3] if acc is not None else self.rank
        if src_rank == me:
            return
        self.judge.post_wait(comm_id, window, src_rank, mean_us, world=world)

    def reset(self) -> None:
        with self._lock:
            self._acc.clear()
            self._lat.clear()
            self._stamp.clear()
        if not self.shared_judge:
            self.judge.reset()

    def snapshot(self) -> dict:
        with self._lock:
            samples = self.samples
            lat_samples = self.latency_samples
            windows = self.windows_posted
        doc = self.judge.snapshot()
        doc.update({
            "enabled": True,
            "interval": self.interval,
            "samples": samples,
            "latency_samples": lat_samples,
            "windows_posted": windows,
            "exchange": "board" if self.shared_judge else "wire",
        })
        return doc


# ---------------------------------------------------------------------------
# anomaly watchdog
# ---------------------------------------------------------------------------


class AnomalyWatchdog:
    """Rolling EWMA latency baselines per (op × size bucket); a call
    past ``factor`` × its baseline emits one bounded alert record into
    the snapshot.  The baseline keeps absorbing every sample (alpha
    ``ANOMALY_ALPHA``), so a persistent regime shift becomes the new
    normal instead of alerting forever."""

    def __init__(self, factor: Optional[float] = None,
                 warmup: int = ANOMALY_WARMUP):
        self.factor = (
            factor if factor is not None
            else _env_float("ACCL_ANOMALY_FACTOR", DEFAULT_ANOMALY_FACTOR)
        )
        self.warmup = int(warmup)
        self._lock = threading.Lock()
        self._base: Dict[Tuple[str, int], list] = {}  # key -> [n, ewma_us]
        self.alerts: List[dict] = []
        self.alerts_total = 0

    def observe(self, op: str, bucket: int, duration_ns: int) -> Optional[dict]:
        d_us = duration_ns / 1e3
        with self._lock:
            key = (op, bucket)
            b = self._base.get(key)
            if b is None:
                self._base[key] = [1, d_us]
                return None
            n, ewma = b
            alert = None
            if n >= self.warmup and d_us > self.factor * max(ewma, 1e-9):
                self.alerts_total += 1
                alert = {
                    "op": op,
                    "size_bucket": bucket,
                    "duration_us": round(d_us, 1),
                    "baseline_us": round(ewma, 1),
                    "factor": round(d_us / max(ewma, 1e-9), 1),
                    "sample": n,
                }
                if len(self.alerts) >= _ALERT_CAP:
                    self.alerts.pop(0)
                self.alerts.append(alert)
            b[0] = n + 1
            b[1] = ewma + ANOMALY_ALPHA * (d_us - ewma)
            return alert

    def reset(self) -> None:
        with self._lock:
            self._base.clear()
            self.alerts.clear()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "factor": self.factor,
                "warmup": self.warmup,
                "alerts_total": self.alerts_total,
                "alerts": [dict(a) for a in self.alerts],
                "baselines": {
                    f"{op}/b{b}": {"samples": n, "ewma_us": round(e, 1)}
                    for (op, b), (n, e) in sorted(self._base.items())
                },
            }


# ---------------------------------------------------------------------------
# traffic-aware scale advice
# ---------------------------------------------------------------------------


class ScaleAdvisor:
    """Advisory grow/shrink recommendations from the QoS arbiter's
    per-tenant latency histograms.

    A pure, deterministic function of the arbiter snapshot — no clocks,
    no randomness, no internal traffic state — so the same tenant
    pressure always yields the same advice (the chaos soaks assert
    this).  The verdict NEVER acts (the ``suspect_slow`` annotation
    discipline): it is surfaced through ``telemetry_snapshot()
    ["membership"]["scale_advice"]`` and the ``/membership`` route, and
    the operator decides whether to call ``join_rank``/``evict_rank``.

    Rules, in precedence order:

    * **grow** — any tenant with ≥ :data:`SCALE_MIN_SAMPLES` completed
      calls whose p99 exceeds the high-water mark, or whose queue
      backlog exceeds its own outstanding-window limit (grant starvation
      is tail pressure even before the histogram shows it).
    * **shrink** — every sampled tenant rides below the low-water p99
      with empty queues, and at least one tenant has samples (an idle
      fabric is not evidence).
    * **hold** — anything else, including no data at all.
    """

    def __init__(
        self,
        grow_p99_us: Optional[float] = None,
        shrink_p99_us: Optional[float] = None,
    ):
        self.grow_p99_us = float(
            grow_p99_us
            if grow_p99_us is not None
            else os.environ.get(SCALE_GROW_ENV, DEFAULT_SCALE_GROW_P99_US)
        )
        self.shrink_p99_us = float(
            shrink_p99_us
            if shrink_p99_us is not None
            else os.environ.get(
                SCALE_SHRINK_ENV, DEFAULT_SCALE_SHRINK_P99_US
            )
        )
        self.advisories = 0
        self._last: Optional[dict] = None
        self._lock = threading.Lock()

    def advise(self, arbiter_snapshot: Optional[dict], world: int) -> dict:
        """One advisory pass over ``QosArbiter.snapshot()`` output.
        Tolerates a disarmed/absent arbiter (→ hold, reason given)."""
        tenants = (arbiter_snapshot or {}).get("tenants") or {}
        hot: List[dict] = []
        sampled = 0
        idle = True
        for cid in sorted(tenants, key=str):
            t = tenants[cid] or {}
            lat = t.get("latency") or {}
            p99 = lat.get("p99_us")
            samples = int(lat.get("count") or 0)
            queued = int(t.get("queued") or 0)
            limit = int(t.get("outstanding_limit") or 0)
            backlogged = limit > 0 and queued > limit
            if samples >= SCALE_MIN_SAMPLES:
                sampled += 1
                if p99 is not None and p99 > self.grow_p99_us:
                    hot.append({
                        "tenant": str(cid),
                        "class": t.get("class"),
                        "p99_us": p99,
                        "reason": "p99_over_high_water",
                    })
                    idle = False
                elif p99 is not None and p99 > self.shrink_p99_us:
                    idle = False
            if backlogged:
                hot.append({
                    "tenant": str(cid),
                    "class": t.get("class"),
                    "queued": queued,
                    "outstanding_limit": limit,
                    "reason": "queue_backlog",
                })
                idle = False
        if hot:
            rec, why = "grow", "tail_pressure"
        elif sampled and idle:
            rec, why = "shrink", "idle_tail"
        else:
            rec, why = "hold", (
                "insufficient_data" if not sampled else "within_band"
            )
        advice = {
            "recommendation": rec,
            "reason": why,
            "world": int(world),
            "hot_tenants": hot,
            "tenants_sampled": sampled,
            "grow_p99_us": self.grow_p99_us,
            "shrink_p99_us": self.shrink_p99_us,
            "advisory_only": True,
        }
        with self._lock:
            self.advisories += 1
            self._last = advice
        return advice

    def last(self) -> Optional[dict]:
        with self._lock:
            return dict(self._last) if self._last is not None else None

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "advisories": self.advisories,
                "last": dict(self._last) if self._last else None,
            }


# ---------------------------------------------------------------------------
# the scrape service
# ---------------------------------------------------------------------------


class MonitorServer:
    """The live scrape endpoint: a stdlib HTTP server on an
    ``accl-monitor`` thread serving the routes the facade registers
    (``/metrics`` Prometheus, ``/snapshot`` JSON, ``/trace`` Chrome
    trace; ``/`` lists them).  Render functions run on the request
    thread — they must be the cheap, side-effect-free snapshot surface
    the telemetry plane already guarantees."""

    def __init__(self, routes: Dict[str, Tuple[Callable[[], str], str]],
                 port: int = 0, host: str = "127.0.0.1"):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.routes = dict(routes)
        self.scrapes: Dict[str, int] = {p: 0 for p in self.routes}
        self.errors = 0
        self._count_lock = threading.Lock()
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - stdlib handler contract
                path = self.path.split("?", 1)[0]
                if path == "/" and "/" not in outer.routes:
                    body = "\n".join(sorted(outer.routes)) + "\n"
                    self._reply(200, body, "text/plain; charset=utf-8")
                    return
                route = outer.routes.get(path)
                if route is None:
                    self._reply(404, f"no such route {path}\n", "text/plain")
                    return
                fn, ctype = route
                try:
                    body = fn()
                except Exception as e:  # a render failure must not kill
                    with outer._count_lock:  # the server
                        outer.errors += 1
                    self._reply(500, f"{type(e).__name__}: {e}\n",
                                "text/plain")
                    return
                with outer._count_lock:
                    outer.scrapes[path] = outer.scrapes.get(path, 0) + 1
                self._reply(200, body, ctype)

            def _reply(self, code: int, body: str, ctype: str) -> None:
                data = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *args):  # quiet: scrapes poll
                pass

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

            def process_request(self, request, client_address):
                # named so the conftest excepthook guard (accl-* prefix)
                # covers request threads like every other project thread
                t = threading.Thread(
                    target=self.process_request_thread,
                    args=(request, client_address),
                    name="accl-monitor-req", daemon=True,
                )
                t.start()

        self._server = _Server((host, int(port)), _Handler)
        self.host = host
        self.port = int(self._server.server_address[1])
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"accl-monitor-{self.port}", daemon=True,
        )

    def start(self) -> "MonitorServer":
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> bool:
        """Shut the service down; True when the serve thread joined
        within ``timeout`` (bounded — a wedged handler must not wedge
        deinit)."""
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=timeout)
        return not self._thread.is_alive()

    @property
    def serving(self) -> bool:
        return self._thread.is_alive()

    def snapshot(self) -> dict:
        with self._count_lock:
            return {
                "host": self.host,
                "port": self.port,
                "serving": self.serving,
                "scrapes": dict(self.scrapes),
                "errors": self.errors,
            }


# ---------------------------------------------------------------------------
# streaming trace export
# ---------------------------------------------------------------------------


class TraceStreamWriter:
    """Bounded rolling-file Chrome-trace streamer.

    ``pull_fn()`` returns the chrome events completed since the last
    pull (the flight recorder's since-cursor); a flusher thread drains
    it every ``interval_s`` and rewrites the CURRENT segment file as a
    complete JSON document via an atomic replace — so at every instant,
    every file on disk is independently Perfetto-loadable, and a crash
    loses at most one flush interval.  Files roll at ``max_events``
    events and the oldest beyond ``max_files`` are pruned.
    """

    def __init__(self, directory: str, rank: int,
                 pull_fn: Callable[[], List[dict]],
                 interval_s: Optional[float] = None,
                 max_events: Optional[int] = None,
                 max_files: Optional[int] = None):
        self.directory = os.fspath(directory)
        self.rank = int(rank)
        self._pull = pull_fn
        self.interval_s = (
            interval_s if interval_s is not None
            else _env_float("ACCL_TRACE_STREAM_INTERVAL_S", 0.5)
        )
        self.max_events = (
            max_events if max_events is not None
            else _env_int("ACCL_TRACE_STREAM_EVENTS", 4096)
        )
        self.max_files = (
            max_files if max_files is not None
            else _env_int("ACCL_TRACE_STREAM_FILES", 8)
        )
        os.makedirs(self.directory, exist_ok=True)
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._seq = 0
        self._files: List[str] = []
        self.events_streamed = 0
        self.flushes = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"accl-trace-stream-{rank}", daemon=True,
        )
        self._thread.start()

    def _path(self, seq: int) -> str:
        return os.path.join(
            self.directory, f"accl_trace_rank{self.rank}_{seq:04d}.json"
        )

    def _run(self) -> None:
        while not self._stop.wait(timeout=self.interval_s):
            try:
                self.flush()
            except Exception:  # a disk hiccup must not kill the stream
                pass
        try:
            self.flush()  # final drain on stop
        except Exception:
            pass

    def flush(self) -> None:
        """Drain new records and rewrite the current segment file (and
        roll it when full).  Callable from any thread — the pull runs
        UNDER the writer lock so concurrent flushes (interval thread +
        an explicit caller) cannot both advance the recorder cursor and
        double-append the same records."""
        with self._lock:
            fresh = self._pull() or []
            self._events.extend(fresh)
            self.events_streamed += len(fresh)
            self.flushes += 1
            while len(self._events) >= self.max_events:
                head = self._events[: self.max_events]
                self._events = self._events[self.max_events:]
                self._write(self._seq, head)
                self._seq += 1
            # the in-progress segment is ALWAYS on disk as a valid doc:
            # the crash-leaves-a-loadable-timeline contract
            self._write(self._seq, self._events)
            while len(self._files) > self.max_files:
                stale = self._files.pop(0)
                try:
                    os.remove(stale)
                except OSError:
                    pass

    def _write(self, seq: int, events: List[dict]) -> None:
        """One segment file, atomically (writer lock held)."""
        path = self._path(seq)
        tmp = path + ".tmp"
        doc = {"traceEvents": list(events), "displayTimeUnit": "ms"}
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        if path not in self._files:
            self._files.append(path)

    def stop(self, timeout: float = 5.0) -> bool:
        self._stop.set()
        self._thread.join(timeout=timeout)
        return not self._thread.is_alive()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "dir": self.directory,
                "files": len(self._files),
                "current_seq": self._seq,
                "events_streamed": self.events_streamed,
                "flushes": self.flushes,
                "interval_s": self.interval_s,
                "max_events": self.max_events,
                "max_files": self.max_files,
            }


# ---------------------------------------------------------------------------
# the per-handle plane
# ---------------------------------------------------------------------------


class Monitor:
    """One rank handle's continuous-observability plane: the straggler
    tracker and anomaly watchdog are always armed (they ride the
    telemetry completion observer — a couple of dict updates per call);
    the scrape server and trace streamer are opt-in services.

    Created by the ACCL facade next to its :class:`~accl_tpu.telemetry.
    Telemetry` (None under the ``ACCL_TELEMETRY=0`` kill switch — no
    records, nothing to monitor)."""

    def __init__(self, rank: int, world: int, telemetry,
                 anchor: Any = None, tier: str = ""):
        self.rank = int(rank)
        self.world = int(world)
        self.tier = tier
        self.telemetry = telemetry
        self.tracker = SkewTracker(
            rank, world, judge=judge_for(anchor, world)
        )
        self.watchdog = AnomalyWatchdog()
        self.scale = ScaleAdvisor()
        self.server: Optional[MonitorServer] = None
        self.stream: Optional[TraceStreamWriter] = None
        telemetry.add_observer(self._observe)

    # -- the telemetry completion observer -----------------------------------
    def _observe(self, meta: dict, duration_ns: int, code: int) -> None:
        op = meta.get("op") or "?"
        if code != 0:
            # failed calls carry deadline-shaped durations (the engine
            # timeout, not a wait measurement): baselines and skew must
            # not absorb them — errors are already counted as errors
            return
        comm = meta.get("comm")
        if comm is not None and op in SKEW_OPS:
            self.tracker.observe(
                comm, duration_ns,
                comm_rank=meta.get("comm_rank"),
                comm_world=meta.get("comm_world"),
            )
        self.watchdog.observe(op, meta.get("bucket") or 0, duration_ns)

    # -- services ------------------------------------------------------------
    def start_trace_stream(self, directory: str) -> TraceStreamWriter:
        """Arm the rolling-file streamer over this handle's flight
        recorder (idempotent)."""
        if self.stream is not None:
            return self.stream
        from .telemetry import record_event

        recorder = self.telemetry.recorder
        cursor = {"total": recorder.total}
        rank = self.rank

        def pull() -> List[dict]:
            recs, cursor["total"] = recorder.since(cursor["total"])
            return [record_event(r, rank) for r in recs]

        self.stream = TraceStreamWriter(directory, rank, pull)
        return self.stream

    def slow_ranks(self, comm_id: int) -> List[int]:
        return self.tracker.judge.slow_ranks(comm_id)

    def scale_advice(
        self, arbiter_snapshot: Optional[dict], world: int
    ) -> dict:
        """One :class:`ScaleAdvisor` pass (advisory only — see the
        class docstring); the result is also retained for the snapshot
        surface."""
        return self.scale.advise(arbiter_snapshot, world)

    def reset(self) -> None:
        """soft_reset recovery: clear skew accumulators, baselines and
        standing straggler verdicts (collective by contract, like the
        reset itself)."""
        self.tracker.reset()
        if self.tracker.shared_judge:
            self.tracker.judge.reset()
        self.watchdog.reset()

    def close(self) -> None:
        """Handle deinit: stop the services (bounded); the tracker and
        watchdog are passive and need no teardown."""
        if self.server is not None:
            srv, self.server = self.server, None
            srv.stop()
        if self.stream is not None:
            stream, self.stream = self.stream, None
            stream.stop()

    # -- snapshot sections ----------------------------------------------------
    def straggler_snapshot(self) -> dict:
        return self.tracker.snapshot()

    def anomaly_snapshot(self) -> dict:
        return self.watchdog.snapshot()

    def service_snapshot(self) -> dict:
        return {
            "serving": self.server is not None and self.server.serving,
            "server": self.server.snapshot() if self.server else None,
            "trace_stream": self.stream.snapshot() if self.stream else None,
        }
