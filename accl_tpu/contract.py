"""The collective contract plane: cross-rank runtime sequence verification.

The reference's CCLO — and our gang tier's SPMD seqn ordering — assume
*matched calls on every rank*: one rank issuing a different op, count,
root, tag or dtype wedges the whole fabric, and the in-flight window
(PR 5) makes the wedge surface N calls after the actual divergence.
This module turns that silent hang into a one-line verdict.

Opt-in (``ACCL_VERIFY=1`` / ``ACCL.set_contract_verify()``).  When armed:

* every collective call gets a canonical **fingerprint** (op, comm id,
  reset generation, dtype, count, root, tag, per-comm call seqn) hashed
  with crc32 — deliberately NOT Python ``hash()``, which is per-process
  salted;
* fingerprints roll into a per-communicator **digest**; every
  ``ACCL_VERIFY_INTERVAL`` calls the completed window's digest is
  exchanged with the other ranks two ways:

  - **in-process board** — rank handles sharing an engine anchor (the
    InProc fabric, the XLA gang context) post to a shared
    :class:`ContractBoard`; a strict majority that excludes some rank
    convicts it (the multi-slice gang will ride a device-side digest
    reduce instead — ROADMAP item 2);
  - **wire piggyback** — emulated fabrics stamp the latest completed
    (window, digest) onto every outgoing message (three ints; zero
    extra traffic) and the receiving endpoint compares claims against
    its own history — so one-process-per-rank socket groups verify with
    no extra round trips;

* on divergence every rank **fails fast** with
  ``ErrorCode.CONTRACT_VIOLATION`` and structured ``ACCLError.details``
  naming the diverging rank, the first mismatched call, and the local
  (plus, in-process, the diverging rank's) flight-recorder tail —
  instead of timing out one hang at a time.

A rank that is *dead* is not *diverging*: verdict construction consults
the PR 2 health map, so ``kill_rank`` faults keep failing through the
dead-peer fast path rather than being misreported as contract breaks.

Zero dependencies (stdlib only) — this module rides the same jax-free
import closure as ``faults``/``telemetry`` and is machine-checked by
acclint's jax-free-module pass.
"""

from __future__ import annotations

import os
import threading
import zlib
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

__all__ = [
    "ContractBoard",
    "ContractVerifier",
    "DEFAULT_VERIFY_INTERVAL",
    "VERIFY_ENV",
    "VERIFY_INTERVAL_ENV",
    "anchored",
    "board_for",
    "call_fingerprint",
    "env_enabled",
    "env_interval",
    "install_fault_plan",
    "kv_digest_exchange",
    "kv_tenant_exchange",
    "roll_digest",
]

VERIFY_ENV = "ACCL_VERIFY"
VERIFY_INTERVAL_ENV = "ACCL_VERIFY_INTERVAL"
DEFAULT_VERIFY_INTERVAL = 8

#: recent per-call summaries retained per communicator (the "first
#: mismatched call" evidence ring; also surfaced in telemetry)
_RING_CAP = 64
#: completed window digests retained per communicator (wire claims from
#: a peer running ahead/behind must still find their comparison point)
_WINDOW_CAP = 128


def env_enabled(environ=None) -> bool:
    """The ``ACCL_VERIFY`` opt-in (read at ACCL-handle construction)."""
    return (environ or os.environ).get(VERIFY_ENV, "0") not in ("0", "")


def env_interval(environ=None) -> int:
    try:
        n = int((environ or os.environ).get(
            VERIFY_INTERVAL_ENV, DEFAULT_VERIFY_INTERVAL
        ))
    except ValueError:
        return DEFAULT_VERIFY_INTERVAL
    return max(1, n)


def call_fingerprint(
    op: str, comm_id: int, generation: int, dtype: Optional[str],
    count: int, root, tag: int, seqn: int,
) -> int:
    """Canonical 32-bit fingerprint of one collective call.  Identical
    inputs fingerprint identically on every rank and process (crc32 of
    a canonical byte string; Python ``hash`` is per-process salted and
    must never leak in here)."""
    data = (
        f"{op}|{comm_id}|{generation}|{dtype or '-'}|{count}|{root}|"
        f"{tag}|{seqn}"
    ).encode()
    return zlib.crc32(data)


def roll_digest(digest: int, fingerprint: int) -> int:
    """Fold one fingerprint into a rolling per-communicator digest
    (order-sensitive: a transposed call sequence yields a different
    digest, which is the point)."""
    return zlib.crc32(fingerprint.to_bytes(4, "little"), digest)


# ---------------------------------------------------------------------------
# seeded fingerprint perturbation (the `diverge` fault action)
# ---------------------------------------------------------------------------

# Device tiers have no fabric to install a FaultPlan on; tests arm the
# `diverge` action there through this process-global injector instead
# (the emulated tiers keep using fabric.install_fault_plan).
_global_lock = threading.Lock()
_global_injector = None


def install_fault_plan(plan) -> None:
    """Arm (or with ``None`` disarm) a process-global FaultPlan for the
    contract plane — the `diverge` action's hook on fabric-less tiers
    (XLA gang / dist / native)."""
    global _global_injector
    from .faults import FaultInjector

    with _global_lock:
        _global_injector = FaultInjector(plan) if plan is not None else None


def _injector_for(fabric) -> Optional[object]:
    inj = getattr(fabric, "fault_injector", None) if fabric is not None else None
    if inj is not None:
        return inj
    return _global_injector


# ---------------------------------------------------------------------------
# the in-process exchange board
# ---------------------------------------------------------------------------

_board_lock = threading.Lock()


def anchored(anchor, attr: str, factory):
    """One shared exchange object per process-wide ``anchor``: the
    get-or-create-an-attribute discipline both in-process exchange
    planes use — the contract board here, and the monitor plane's skew
    judge (``accl_tpu.monitor.judge_for``) — so rank handles sharing an
    engine anchor (InProc fabric, XLA gang context) meet on one
    instance.  None when the anchor is None (one-process-per-rank
    tiers: the wire piggyback does the exchanging) or cannot hold
    attributes (slotted/foreign anchor)."""
    if anchor is None:
        return None
    with _board_lock:
        obj = getattr(anchor, attr, None)
        if obj is None:
            obj = factory()
            try:
                setattr(anchor, attr, obj)
            except (AttributeError, TypeError):  # slotted/foreign anchor
                return None
        return obj


def board_for(anchor) -> Optional["ContractBoard"]:
    """The :class:`ContractBoard` shared by every rank handle anchored
    on ``anchor`` (the engine's ``contract_anchor()``: the InProc
    fabric, the XLA gang context, or the engine itself on
    one-process-per-rank tiers, where the board degenerates to a single
    poster and the wire piggyback does the comparing)."""
    return anchored(anchor, "_accl_contract_board", ContractBoard)


class ContractBoard:
    """Shared digest exchange for rank handles in one process.

    Each verifier posts ``(comm, generation, window) -> digest`` at its
    window boundaries; a post that completes a *strict majority* whose
    digest excludes some rank convicts that rank (majority needs
    world >= 3 — two-rank groups rely on the wire piggyback's pairwise
    comparison instead).  Verdicts are standing: every later intake on
    the communicator fails fast until a soft_reset clears the board.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # (comm, generation, window) -> {rank: digest}
        self._posts: Dict[tuple, Dict[int, int]] = {}
        # (comm, generation, window, rank) -> (ring-tail, tail_fn)
        self._info: Dict[tuple, tuple] = {}
        self._verdicts: Dict[int, dict] = {}  # comm -> standing verdict
        self._listeners: List[Callable[[dict], None]] = []

    def add_listener(self, fn: Callable[[dict], None]) -> None:
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    def standing(self, comm_id: int) -> Optional[dict]:
        with self._lock:
            return self._verdicts.get(comm_id)

    def retract(self, comm_id: int, rank: int) -> None:
        """Remove one rank's posts/evidence for a communicator — the
        disarm path: a verifier that re-arms later restarts its digest
        stream at generation 1, and its own STALE posts at the same
        (comm, generation, window) keys would otherwise vote against
        its fresh digests (a false conviction).  Standing verdicts are
        deliberately kept — those were real when recorded; recovery
        from a verdict is the collective soft_reset."""
        with self._lock:
            for key in [k for k in self._posts if k[0] == comm_id]:
                self._posts[key].pop(rank, None)
                if not self._posts[key]:
                    del self._posts[key]
            for key in [
                k for k in self._info
                if k[0] == comm_id and k[3] == rank
            ]:
                del self._info[key]

    def clear(self, comm_id: Optional[int] = None) -> None:
        """Drop standing verdicts (and posts) — the soft_reset recovery
        path; ``None`` clears every communicator."""
        with self._lock:
            if comm_id is None:
                self._posts.clear()
                self._info.clear()
                self._verdicts.clear()
            else:
                self._verdicts.pop(comm_id, None)
                for key in [k for k in self._posts if k[0] == comm_id]:
                    del self._posts[key]
                for key in [k for k in self._info if k[0] == comm_id]:
                    del self._info[key]

    def post(
        self, comm_id: int, generation: int, window: int, rank: int,
        world: int, digest: int, ring: List[dict],
        tail_fn: Optional[Callable[[], list]] = None,
        sessions: Optional[tuple] = None,
    ) -> Optional[dict]:
        """Post one completed window digest.  ``rank`` and ``world``
        are COMM-relative (the posting rank within the communicator and
        the communicator's member count — a subcomm's majority is over
        ITS size); ``sessions`` maps comm-relative rank -> global
        session for the verdict report.  Returns the (new or standing)
        verdict for this communicator, if any."""
        notify = None
        with self._lock:
            stand = self._verdicts.get(comm_id)
            if stand is not None:
                return stand
            key = (comm_id, generation, window)
            posts = self._posts.setdefault(key, {})
            posts[rank] = digest
            self._info[key + (rank,)] = (list(ring), tail_fn)
            self._gc(comm_id, generation, window)
            verdict = self._judge(key, posts, world, sessions)
            if verdict is not None:
                self._verdicts[comm_id] = verdict
                notify = list(self._listeners)
                div_tail_fn = verdict.pop("_tail_fn", None)
        if notify is None:
            return None
        # the convicted rank's flight-recorder tail is fetched OUTSIDE
        # the board lock (tail_fn takes the recorder's own lock; no
        # cross-family hold)
        if div_tail_fn is not None:
            try:
                verdict["diverging_flight_recorder"] = div_tail_fn()
            except Exception:
                pass
        for fn in notify:
            try:
                fn(verdict)
            except Exception:  # a listener must never fail the call
                pass
        return verdict

    def _gc(self, comm_id: int, generation: int, window: int) -> None:
        floor = window - _WINDOW_CAP
        stale = [
            k for k in self._posts
            if k[0] == comm_id and (k[1] < generation - 1 or k[2] < floor)
        ]
        for k in stale:
            del self._posts[k]
        stale_i = [
            k for k in self._info
            if k[0] == comm_id and (k[1] < generation - 1 or k[2] < floor)
        ]
        for k in stale_i:
            del self._info[k]

    def _judge(self, key: tuple, posts: Dict[int, int], world: int,
               sessions: Optional[tuple] = None) -> Optional[dict]:
        """Majority vote over the digests posted for one window.  Only a
        STRICT majority (> world/2 agreeing posts) convicts — a 1-1
        split cannot name a culprit, and convicting early on partial
        posts would misname a merely-slow rank."""
        if len(posts) < 2 or len(set(posts.values())) < 2:
            return None
        counts: Dict[int, int] = {}
        for d in posts.values():
            counts[d] = counts.get(d, 0) + 1
        majority_digest, nmaj = max(counts.items(), key=lambda kv: kv[1])
        if nmaj * 2 <= world:
            return None  # no strict majority (yet): wait for more posts
        diverging = sorted(r for r, d in posts.items() if d != majority_digest)
        comm_id, generation, window = key
        verdict = {
            "kind": "divergence",
            "basis": "majority",
            "comm": comm_id,
            "generation": generation,
            "window": window,
            "digests": dict(posts),
            "majority_digest": majority_digest,
            "diverging_rank": diverging[0],
            "diverging_ranks": diverging,
            "diverging_session": (
                sessions[diverging[0]]
                if sessions is not None and diverging[0] < len(sessions)
                else diverging[0]
            ),
        }
        # first mismatched call: walk a majority rank's ring against the
        # convicted rank's, fingerprint by fingerprint
        maj_rank = next(
            (r for r, d in sorted(posts.items()) if d == majority_digest),
            None,
        )
        div_rank = diverging[0]
        maj_info = self._info.get(key + (maj_rank,))
        div_info = self._info.get(key + (div_rank,))
        if maj_info and div_info:
            mismatch = _first_mismatch(maj_info[0], div_info[0])
            if mismatch is not None:
                verdict["first_mismatch"] = mismatch
            if div_info[1] is not None:
                # fetched by post() AFTER the board lock is released
                verdict["_tail_fn"] = div_info[1]
        return verdict


def _first_mismatch(ring_a: List[dict], ring_b: List[dict]) -> Optional[dict]:
    """First (seqn-aligned) call where two ranks' fingerprints differ:
    the expected call (majority side) and the got call (diverging
    side), for the error report."""
    by_seq_b = {r["seqn"]: r for r in ring_b}
    for r in ring_a:
        other = by_seq_b.get(r["seqn"])
        if other is not None and other["fingerprint"] != r["fingerprint"]:
            return {"expected": dict(r), "got": dict(other)}
    # seqn sets may not overlap (epoch skew restarted one side's count)
    if ring_a and ring_b and (
        {r["seqn"] for r in ring_a} & {r["seqn"] for r in ring_b} == set()
    ):
        return {"expected": dict(ring_a[0]), "got": dict(ring_b[0])}
    return None


# ---------------------------------------------------------------------------
# the per-handle verifier
# ---------------------------------------------------------------------------


class _CommContract:
    """Per-communicator rolling state."""

    __slots__ = ("calls", "digest", "windows", "ring", "claims",
                 "pending_relays", "local_rank", "size", "sessions")

    def __init__(self, local_rank: Optional[int] = None,
                 size: Optional[int] = None,
                 sessions: Optional[tuple] = None):
        self.calls = 0          # collective calls recorded (the seqn)
        self.digest = 0         # rolling digest over ALL recorded calls
        self.windows: Dict[int, int] = {}  # completed window -> digest
        self.ring: deque = deque(maxlen=_RING_CAP)
        # wire claims from peers ahead of us: window -> (src_rank, digest)
        self.claims: Dict[int, Tuple[int, int]] = {}
        # relayed pairwise verdicts blaming a third party that we could
        # not yet tiebreak (our window lagged): resolved at the next
        # boundary (bounded; adopt_verdict explains the policy)
        self.pending_relays: List[dict] = []
        # membership (registered by begin_comm): every rank field of
        # this communicator's verdicts — wire msg.src, board posts,
        # blame — is COMM-RELATIVE; mixing in the verifier's world rank
        # misblames on subcommunicators.  sessions maps comm-relative
        # rank -> global session for health lookups + reporting.
        self.local_rank = local_rank
        self.size = size
        self.sessions = sessions


class ContractVerifier:
    """One rank handle's end of the collective contract.

    Created by the ACCL facade when verification is armed; `record` is
    called at call intake (before dispatch, so a verdict fails the call
    *pre-launch*), `observe_message` from fabric delivery threads, and
    `stamp` from the fabric send path.  Thread-safe; every public entry
    takes the verifier lock briefly and never calls out while holding it.
    """

    def __init__(
        self,
        rank: int,
        world: int,
        interval: Optional[int] = None,
        board: Optional[ContractBoard] = None,
        fabric=None,
        tail_fn: Optional[Callable[[], list]] = None,
        health_fn: Optional[Callable[[], dict]] = None,
    ):
        self.rank = rank
        self.world = world
        self.interval = max(1, int(interval or env_interval()))
        self.board = board
        self._fabric = fabric  # injector discovery (fault plan host)
        self._tail_fn = tail_fn
        self._health_fn = health_fn
        self._lock = threading.Lock()
        self._comms: Dict[int, _CommContract] = {}
        self._verdicts: Dict[int, dict] = {}
        self.has_verdict = False  # lock-free fast-path probe
        self._listeners: List[Callable[[dict], None]] = []
        self.generation = 1  # bumped by soft_reset (collective by contract)
        self.calls_verified = 0
        self.windows_exchanged = 0
        self.perturbed = 0  # `diverge` fault applications (seeded tests)
        if board is not None:
            board.add_listener(self._on_board_verdict)

    # -- wiring --------------------------------------------------------------
    def add_verdict_listener(self, fn: Callable[[dict], None]) -> None:
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def close(self) -> None:
        """Disarm: detach from the board and retract this rank's posts
        so a later (collective) re-arm cannot collide its fresh digest
        stream with this life's stale ones."""
        if self.board is None:
            return
        self.board.remove_listener(self._on_board_verdict)
        with self._lock:
            ranks = {
                cid: (
                    st.local_rank
                    if st.local_rank is not None else self.rank
                )
                for cid, st in self._comms.items()
            }
        for cid, r in ranks.items():
            self.board.retract(cid, r)

    def _on_board_verdict(self, verdict: dict) -> None:
        with self._lock:
            self._verdicts.setdefault(verdict["comm"], verdict)
            self.has_verdict = True
            notify = list(self._listeners)
        for fn in notify:
            try:
                fn(verdict)
            except Exception:
                pass

    # -- verdicts ------------------------------------------------------------
    def check(self, comm_id: int) -> Optional[dict]:
        """The standing verdict for ``comm_id`` (own or board), or None."""
        if self.has_verdict:
            with self._lock:
                v = self._verdicts.get(comm_id)
            if v is not None:
                return v
        if self.board is not None:
            v = self.board.standing(comm_id)
            if v is not None:
                with self._lock:
                    self._verdicts.setdefault(comm_id, v)
                    self.has_verdict = True
            return v
        return None

    def _set_verdict(self, comm_id: int, verdict: dict) -> None:
        with self._lock:
            if comm_id in self._verdicts:
                return
            self._verdicts[comm_id] = verdict
            self.has_verdict = True
            notify = list(self._listeners)
        for fn in notify:
            try:
                fn(verdict)
            except Exception:
                pass

    # -- recording (call intake) ---------------------------------------------
    def record(
        self, op: str, comm_id: int, dtype: Optional[str], count: int,
        root, tag: int,
    ) -> Optional[dict]:
        """Fingerprint one collective call and roll it into the
        communicator's digest; at a window boundary, exchange.  Returns
        the standing verdict if one exists (callers fail the call
        pre-dispatch)."""
        post = None
        # injector consult OUTSIDE the verifier lock (it takes its own;
        # no cross-family hold for the lock-order registry to flag) —
        # the rule's rank matches COMM-relative like every FaultRule
        # rank field, so peek the registered membership first.  The
        # no-injector production path skips the peek entirely (a
        # lock-free getattr + global read, not an extra lock round-trip
        # on the <=5%-budgeted warm path).
        mask = 0
        if _injector_for(self._fabric) is not None:
            with self._lock:
                st0 = self._comms.get(comm_id)
                rank0 = (
                    st0.local_rank
                    if st0 is not None and st0.local_rank is not None
                    else self.rank
                )
            mask = self._perturb_mask(comm_id, rank0)
        with self._lock:
            v = self._verdicts.get(comm_id)
            if v is not None:
                return v
            st = self._comm_state(comm_id)
            comm_rank = (
                st.local_rank if st.local_rank is not None else self.rank
            )
            comm_size = st.size or self.world
            sessions = st.sessions
            seqn = st.calls
            fp = call_fingerprint(
                op, comm_id, self.generation, dtype, count, root, tag, seqn
            )
            if mask:
                self.perturbed += 1
                fp ^= mask
            st.digest = roll_digest(st.digest, fp)
            st.calls = seqn + 1
            self.calls_verified += 1
            st.ring.append({
                "seqn": seqn, "op": op, "dtype": dtype, "count": count,
                "root": root, "tag": tag, "fingerprint": fp,
            })
            if st.calls % self.interval == 0:
                window = st.calls // self.interval - 1
                st.windows[window] = st.digest
                if len(st.windows) > _WINDOW_CAP:
                    for w in sorted(st.windows)[:-_WINDOW_CAP]:
                        del st.windows[w]
                self.windows_exchanged += 1
                pairwise = self._check_claims(st, comm_id, window)
                if pairwise is None and st.pending_relays:
                    # parked third-party relays: our freshly completed
                    # window is the tiebreaker they were waiting for.
                    # Adopt the first one that resolves; re-park those
                    # for windows we haven't reached; a relay for a
                    # window we PASSED but cannot tiebreak (generation
                    # skew / pruned history) is adopted as-is — its
                    # blame may be the sender's guess, but staying
                    # silent would trade wrong blame for a hang.
                    relays, st.pending_relays = st.pending_relays, []
                    keep: List[dict] = []
                    for vd in relays:
                        if pairwise is not None:
                            keep.append(vd)
                        elif self._tiebreak_pairwise(vd, st):
                            pairwise = vd
                        elif (vd.get("window") or 0) > window:
                            keep.append(vd)  # not our tiebreak point yet
                        else:
                            pairwise = vd  # passed window: best effort
                    st.pending_relays = keep
                post = (window, st.digest, list(st.ring))
        if post is None:
            return self.check(comm_id) if self.has_verdict else None
        # ALWAYS post the completed window to the board, even when a
        # pairwise claim already convicted: the other ranks' majority
        # needs this digest to form their own (better-attributed)
        # verdict — skipping the post on self-detection left peers
        # blocked in flight until their engine deadline
        if self.board is not None:
            window, digest, ring = post
            # rank and majority threshold are COMM-relative: a subcomm's
            # majority is over ITS member count, not the world's (a
            # world-sized threshold would make subcomm conviction
            # impossible on the board-only gang tier)
            verdict = self.board.post(
                comm_id, self.generation, window, comm_rank, comm_size,
                digest, ring, tail_fn=self._tail_fn, sessions=sessions,
            )
            if verdict is not None:
                # prefer the board's majority attribution over the
                # pairwise guess when both land on the same boundary
                with self._lock:
                    self._verdicts.setdefault(comm_id, verdict)
                    self.has_verdict = True
                return verdict
        if pairwise is not None:
            self._annotate_health(pairwise)
            self._set_verdict(comm_id, pairwise)
            return pairwise
        return None

    def _perturb_mask(self, comm_id: int, comm_rank: int) -> int:
        """The `diverge` fault action: a nonzero XOR mask when a seeded
        FaultRule says this rank's next fingerprint diverges (the proof
        the verifier catches real divergence); 0 otherwise.  The rule's
        ``rank`` field matches COMM-relative, like every other
        FaultRule rank field."""
        inj = _injector_for(self._fabric)
        if inj is None:
            return 0
        return inj.on_fingerprint(comm_id, comm_rank)

    # -- wire piggyback -------------------------------------------------------
    def stamp(self, comm_id: int) -> Tuple[int, int, int]:
        """(generation, window, digest) of the latest completed window
        for ``comm_id`` — stamped onto outgoing wire messages.  window
        -1 = nothing completed yet (receivers skip)."""
        with self._lock:
            st = self._comms.get(comm_id)
            if st is None or not st.windows:
                return self.generation, -1, 0
            w = max(st.windows)
            return self.generation, w, st.windows[w]

    def observe_claim(
        self, comm_id: int, src_rank: int, generation: int, window: int,
        digest: int,
    ) -> Optional[dict]:
        """A peer's piggybacked digest claim (fabric delivery thread).
        ``src_rank`` is COMM-relative (the wire message's src field).
        Claims from other generations are skipped (a soft_reset is in
        flight); a claim for a window we have completed is compared
        immediately, one ahead of us is parked until we complete it."""
        if window < 0:
            return None
        verdict = None
        with self._lock:
            if generation != self.generation:
                return None
            v = self._verdicts.get(comm_id)
            if v is not None:
                return v
            st = self._comm_state(comm_id)
            if src_rank == (
                st.local_rank if st.local_rank is not None else self.rank
            ):
                return None
            ours = st.windows.get(window)
            if ours is None:
                st.claims[window] = (src_rank, digest)
                if len(st.claims) > _WINDOW_CAP:
                    for w in sorted(st.claims)[:-_WINDOW_CAP]:
                        del st.claims[w]
                return None
            if ours != digest:
                verdict = self._pairwise_verdict(
                    st, comm_id, src_rank, window, ours, digest
                )
        if verdict is not None:
            self._annotate_health(verdict)
            self._set_verdict(comm_id, verdict)
        return verdict

    def adopt_verdict(self, comm_id: int, verdict: dict,
                      src_rank: Optional[int] = None) -> None:
        """A verdict relayed from a peer (wire VERIFY message): adopt it
        so in-flight and future calls on this rank fail fast too.

        Pairwise blame is re-resolved locally before adoption: the
        relay carries both parties' digests, and comparing them against
        OUR digest for the same window makes this rank the tiebreaker —
        the party whose digest differs from ours is the diverger (a
        two-plus-one majority).  When we cannot tiebreak (window not
        completed here, generation skew) a relay that blames US is
        re-oriented to blame the sender — from this rank's perspective
        the other side of the pair is the relaying peer."""
        verdict = dict(verdict)
        verdict["relayed"] = True
        if verdict.get("basis") == "pairwise":
            resolved = False
            digests = verdict.get("digests") or {}
            try:
                parties = {int(r): d for r, d in digests.items()}
            except (TypeError, ValueError):
                parties = {}
            window = verdict.get("window")
            ours = None
            sessions = None
            with self._lock:
                st = self._comms.get(comm_id)
                comm_rank = (
                    st.local_rank
                    if st is not None and st.local_rank is not None
                    else self.rank
                )
                if st is not None:
                    sessions = st.sessions
                if (
                    st is not None and window is not None
                    and verdict.get("generation") == self.generation
                ):
                    ours = st.windows.get(window)
            if ours is not None and parties:
                resolved = self._tiebreak_pairwise_against(
                    verdict, parties, ours, comm_rank, sessions
                )
            if not resolved and src_rank is not None:
                blamed = verdict.get("diverging_rank")
                if blamed == comm_rank:
                    self._reblame(verdict, src_rank, sessions)
                elif blamed != src_rank:
                    # blames a THIRD party and we cannot tiebreak yet
                    # (our window lags the verdict's): the sender may
                    # itself be the diverger misblaming a conforming
                    # rank.  Park until our next boundary — at most one
                    # call away on a live rank — where the local digest
                    # settles the blame before anything is reported.
                    with self._lock:
                        if comm_id in self._verdicts:
                            return
                        st = self._comm_state(comm_id)
                        if len(st.pending_relays) < 8:
                            st.pending_relays.append(verdict)
                    return
        self._set_verdict(comm_id, verdict)

    @staticmethod
    def _reblame(verdict: dict, rank: int,
                 sessions: Optional[tuple]) -> None:
        """Re-point a verdict's blame at ``rank`` — ALL three fields
        together (diverging_rank/_ranks/_session); leaving the session
        stale would send an operator to the wrong host."""
        verdict["diverging_rank"] = rank
        verdict["diverging_ranks"] = [rank]
        verdict["diverging_session"] = (
            sessions[rank]
            if sessions is not None and rank < len(sessions) else rank
        )

    def _tiebreak_pairwise_against(
        self, verdict: dict, parties: Dict[int, int], ours: int,
        comm_rank: int, sessions: Optional[tuple] = None,
    ) -> bool:
        """Resolve a relayed pairwise verdict's blame using OUR digest
        as the third vote: the party whose digest differs from ours is
        the diverger.  ``comm_rank`` is our COMM-relative rank (the
        space every party key lives in).  Mutates the verdict's blame
        fields; False when the evidence cannot decide (both parties
        differ, or none)."""
        odd = sorted(
            r for r, d in parties.items() if r != comm_rank and d != ours
        )
        if len(odd) != 1:
            return False
        self._reblame(verdict, odd[0], sessions)
        return True

    def _tiebreak_pairwise(self, verdict: dict,
                           st: _CommContract) -> bool:
        """The parked-relay form: look our digest up by the verdict's
        window (verifier lock held by the caller)."""
        digests = verdict.get("digests") or {}
        try:
            parties = {int(r): d for r, d in digests.items()}
        except (TypeError, ValueError):
            return False
        ours = st.windows.get(verdict.get("window"))
        if ours is None:
            return False
        return self._tiebreak_pairwise_against(
            verdict, parties, ours,
            st.local_rank if st.local_rank is not None else self.rank,
            st.sessions,
        )

    def _check_claims(self, st: _CommContract, comm_id: int,
                      window: int) -> Optional[dict]:
        """Compare parked peer claims against a freshly completed
        window (verifier lock held)."""
        claim = st.claims.pop(window, None)
        if claim is None:
            return None
        src_rank, digest = claim
        if digest == st.windows[window]:
            return None
        return self._pairwise_verdict(
            st, comm_id, src_rank, window, st.windows[window], digest
        )

    def _pairwise_verdict(
        self, st: _CommContract, comm_id: int, src_rank: int, window: int,
        ours: int, theirs: int,
    ) -> dict:
        """Two digests disagree and there is no majority to consult: by
        convention each side names the *peer* — correct on the
        conforming side, which is where production reads the report.
        All rank fields are COMM-relative; ``diverging_session`` maps
        the blame to the global rank identity when the membership was
        registered.  Verifier lock held by the caller — the health
        annotation (which calls out to the engine) is applied AFTER
        release by :meth:`_annotate_health`."""
        comm_rank = st.local_rank if st.local_rank is not None else self.rank
        session = (
            st.sessions[src_rank]
            if st.sessions is not None and src_rank < len(st.sessions)
            else src_rank
        )
        return {
            "kind": "divergence",
            "basis": "pairwise",
            "comm": comm_id,
            "generation": self.generation,
            "window": window,
            "digests": {comm_rank: ours, src_rank: theirs},
            "diverging_rank": src_rank,
            "diverging_ranks": [src_rank],
            "diverging_session": session,
            "local_recent_calls": list(st.ring),
        }

    def _annotate_health(self, verdict: dict) -> None:
        """Fill the kill_rank-vs-diverge distinction in OUTSIDE the
        verifier lock (health_report may call into the engine): a peer
        the health map already calls dead is reported as dead, not
        diverging."""
        if self._health_fn is None or verdict.get("relayed"):
            return
        try:
            # the health map is keyed by WORLD rank == Rank.session
            health = (self._health_fn() or {}).get(
                verdict.get("diverging_session")
            )
        except Exception:
            health = None
        verdict["peer_health"] = health
        if health is not None and health.get("state") == "dead":
            verdict["kind"] = "rank_dead"

    # -- lifecycle -----------------------------------------------------------
    def _comm_state(self, comm_id: int) -> _CommContract:
        """Per-comm state, creating a membership-less entry on first
        touch (world-comm fallbacks apply until begin_comm registers
        the real membership).  Verifier lock held by the caller."""
        st = self._comms.get(comm_id)
        if st is None:
            st = self._comms[comm_id] = _CommContract()
        return st

    def begin_comm(
        self, comm_id: int, local_rank: Optional[int] = None,
        sessions: Optional[tuple] = None, fresh: bool = True,
    ) -> None:
        """Register a communicator's membership (COMM-relative local
        rank + comm-relative-rank -> global session map — the spaces
        every wire src / board post / blame field live in) and, for a
        (re-)created instance (``fresh=True``), fold a begin marker
        into the continuous digest stream instead of resetting it — a
        rank that re-creates a subcomm when its peers don't diverges at
        the next window boundary (the subcomm-epoch-skew failure)."""
        with self._lock:
            st = self._comm_state(comm_id)
            if local_rank is not None:
                st.local_rank = local_rank
            if sessions is not None:
                st.sessions = tuple(sessions)
                st.size = len(st.sessions)
            if not fresh or comm_id in self._verdicts:
                return
            fp = call_fingerprint(
                "__begin__", comm_id, self.generation, None, 0, 0, 0,
                st.calls,
            )
            st.digest = roll_digest(st.digest, fp)

    def shrink_comm(self, comm_id: int, local_rank: int,
                    sessions: tuple, membership_epoch: int) -> None:
        """Membership-plane cutover (``accl_tpu.membership``): fold a
        ``__shrink__`` marker into the CONTINUOUS digest stream — the
        ``__begin__`` discipline applied to eviction — and re-register
        the shrunk membership (new comm-relative local rank + rank ->
        session map).  A rank that missed the cutover keeps digesting
        the old membership and diverges at the next window boundary:
        one window of delay instead of a silent hang.  Pre-shrink wire
        claims are dropped — their src ranks live in the old rank
        space."""
        with self._lock:
            st = self._comm_state(comm_id)
            st.local_rank = int(local_rank)
            st.sessions = tuple(sessions)
            st.size = len(st.sessions)
            fp = call_fingerprint(
                "__shrink__", comm_id, self.generation, None,
                len(sessions), membership_epoch, 0, st.calls,
            )
            st.digest = roll_digest(st.digest, fp)
            st.claims.clear()
            st.pending_relays.clear()

    def join_comm(self, comm_id: int, local_rank: int, sessions: tuple,
                  membership_epoch: int,
                  base: Optional[tuple] = None) -> None:
        """Membership-plane GROW cutover: re-register the grown
        membership and fold a ``__join__`` marker into the digest
        stream — the ``__shrink__`` discipline run in the other
        direction.  ``base`` is the agreed ``(calls, digest)`` restart
        point carried by the confirmed join plan's warm handoff: every
        member (survivor and candidate alike) rebases its stream on it
        before folding the marker, so the candidate — whose local
        stream is empty or belongs to a previous life — converges on
        the group's digest at the cutover boundary instead of
        diverging forever.  A rank that MISSED the cutover keeps
        rolling its old stream and diverges within one verification
        window, exactly like a missed shrink.  Without a base
        (defensive: a plan with no handoff) the marker folds into the
        continuous stream, shrink-style."""
        with self._lock:
            st = self._comm_state(comm_id)
            st.local_rank = int(local_rank)
            st.sessions = tuple(sessions)
            st.size = len(st.sessions)
            if base is not None:
                try:
                    st.calls = int(base[0])
                    st.digest = int(base[1])
                except (TypeError, ValueError, IndexError):
                    pass
            fp = call_fingerprint(
                "__join__", comm_id, self.generation, None,
                len(sessions), membership_epoch, 0, st.calls,
            )
            st.digest = roll_digest(st.digest, fp)
            st.claims.clear()
            st.pending_relays.clear()

    def export_handoff(self) -> dict:
        """The contract half of the warm-handoff artifacts an admitting
        member exports for the candidate (JSON-serializable): the
        generation (so stale wire stamps from the candidate's previous
        life are ignored by ``observe_claim``) and each registered
        communicator's ``(calls, digest)`` baseline — the agreed
        restart point :meth:`join_comm` rebases every member on."""
        with self._lock:
            return {
                "generation": self.generation,
                "interval": self.interval,
                "comms": {
                    str(cid): {"calls": st.calls, "digest": st.digest}
                    for cid, st in self._comms.items()
                },
            }

    def adopt_generation(self, generation: int) -> None:
        """Candidate-side handoff adoption: align the verification
        generation with the group's (the candidate's own generation
        belongs to its previous life — its posts would be ignored and
        peers' claims skipped without this)."""
        with self._lock:
            self.generation = int(generation)

    def reset(self) -> None:
        """soft_reset recovery: drop every verdict, digest and claim and
        start a new generation (collective by contract, so generations
        stay aligned across ranks; stale wire stamps from the old
        generation are ignored by ``observe_claim``).  Registered
        memberships survive — only the rolling state restarts."""
        with self._lock:
            self._comms = {
                cid: _CommContract(st.local_rank, st.size, st.sessions)
                for cid, st in self._comms.items()
            }
            self._verdicts.clear()
            self.has_verdict = False
            self.generation += 1
        if self.board is not None:
            self.board.clear()

    # -- telemetry ------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "interval": self.interval,
                "generation": self.generation,
                "calls_verified": self.calls_verified,
                "windows_exchanged": self.windows_exchanged,
                "perturbed": self.perturbed,
                "verdicts": {
                    str(c): {
                        k: v for k, v in vd.items()
                        if k not in ("local_recent_calls",
                                     "diverging_flight_recorder")
                    }
                    for c, vd in self._verdicts.items()
                },
                "comms": {
                    str(c): {"calls": st.calls, "digest": st.digest,
                             "windows": len(st.windows)}
                    for c, st in self._comms.items()
                },
            }


def kv_digest_exchange(kv, verifier: "ContractVerifier", comm_id: int,
                       local_rank: int, size: int,
                       state: Optional[dict] = None,
                       is_notfound=None) -> dict:
    """Piggyback the verifier's rolling digest onto a distributed KV
    plane — the dist tier's exchange path (the PR 7 deferral): post
    this rank's latest completed window digest under
    ``accl/vfy/<comm>/<gen>/<window>/<rank>`` and compare every peer's
    posted digest via :meth:`ContractVerifier.observe_claim`, so
    cross-host divergence fails fast exactly like in-process.

    ``kv`` needs ``key_value_set_bytes(key, bytes)`` and
    ``key_value_try_get_bytes(key) -> bytes|None`` (the compat-wrapped
    jax KV client surface); ``state`` carries the per-comm cursor
    (``{"posted": window, "checked": {peer: window}}``) between calls
    so warm calls cost one stamp read.  Missing peer keys (a rank
    behind us) are skipped — ``is_notfound(exc)`` classifies raisy KV
    clients.  Returns counter deltas for telemetry.  Stdlib-only so
    the exchange is unit-testable without jax (a dict-backed fake KV).
    """
    out = {"posted": 0, "claims": 0, "errors": 0}
    gen, window, digest = verifier.stamp(comm_id)
    if window < 0:
        return out
    st = state if state is not None else {}
    base = f"accl/vfy/{comm_id}/{gen}"
    if st.get("posted") != (gen, window):
        try:
            kv.key_value_set_bytes(
                f"{base}/{window}/{local_rank}", str(digest).encode()
            )
            st["posted"] = (gen, window)
            out["posted"] = 1
        except Exception:
            out["errors"] += 1
            return out  # the KV is unreachable: nothing to compare
    checked = st.setdefault("checked", {})
    for peer in range(size):
        if peer == local_rank or checked.get(peer) == (gen, window):
            continue
        try:
            raw = kv.key_value_try_get_bytes(f"{base}/{window}/{peer}")
        except Exception as e:
            if is_notfound is not None and is_notfound(e):
                continue  # peer hasn't completed this window yet
            out["errors"] += 1
            continue
        if raw is None:
            continue
        try:
            theirs = int(raw)
        except ValueError:
            out["errors"] += 1
            continue
        checked[peer] = (gen, window)
        out["claims"] += 1
        verifier.observe_claim(comm_id, peer, gen, window, theirs)
    return out


def kv_tenant_exchange(kv, process_key: str, weights: dict,
                       state: Optional[dict] = None,
                       is_notfound=None, slot_cap: int = 64):
    """Share one process's QoS tenant weight table through the dist
    tier's KV plane — the cross-process tenant registry (the arbiter's
    per-process DRR is fair only among tenants it can SEE; two
    one-process-per-rank jobs sharing a fabric each run a blind
    arbiter, and the bulk job starves the serving job exactly as if
    no arbiter existed).

    Rendezvous rides the PR 12 contract-digest ledger discipline: the
    first call claims a dense slot index via
    ``key_value_increment("accl/arb/slots")`` (the KV plane's atomic
    counter — no registry key to race), then posts this process's
    table as JSON under ``accl/arb/slot/<i>`` (re-posted only when the
    table changes, so warm exchanges cost one sweep).  The sweep scans
    slots upward and stops at the first gap past our own slot (slots
    are claimed densely; a gap *below* us is a peer that claimed but
    has not posted yet, and is skipped, not a stop), bounded by
    ``slot_cap``.

    ``kv`` needs ``key_value_set_bytes`` / ``key_value_try_get_bytes``
    / ``key_value_increment`` (the compat-wrapped jax KV client
    surface); ``state`` carries the slot claim and last-posted doc
    between calls.  Returns ``(foreign, counters)``: foreign maps each
    peer process key to ``{"weights": {...}, "total": int}``.
    Stdlib-only so the exchange is unit-testable without jax (a
    dict-backed fake KV)."""
    import json as _json

    out = {"posted": 0, "peers": 0, "errors": 0}
    st = state if state is not None else {}
    slot = st.get("slot")
    if slot is None:
        try:
            slot = int(kv.key_value_increment("accl/arb/slots", 1)) - 1
        except Exception:
            out["errors"] += 1
            return {}, out  # the KV is unreachable: nothing to share
        st["slot"] = slot
    doc = _json.dumps(
        {
            "process": str(process_key),
            "weights": {str(k): int(v) for k, v in sorted(weights.items())},
        },
        sort_keys=True,
    )
    if st.get("posted_doc") != doc:
        try:
            kv.key_value_set_bytes(f"accl/arb/slot/{slot}", doc.encode())
            st["posted_doc"] = doc
            out["posted"] = 1
        except Exception:
            out["errors"] += 1
            return {}, out
    foreign: dict = {}
    for i in range(max(slot + 1, int(slot_cap))):
        if i == slot:
            continue
        try:
            raw = kv.key_value_try_get_bytes(f"accl/arb/slot/{i}")
        except Exception as e:
            if is_notfound is not None and is_notfound(e):
                raw = None
            else:
                out["errors"] += 1
                continue
        if raw is None:
            if i > slot:
                break  # past the dense frontier: no more claimed slots
            continue  # a lower slot claimed but not yet posted
        try:
            peer_doc = _json.loads(
                raw.decode() if isinstance(raw, (bytes, bytearray))
                else str(raw)
            )
            pk = str(peer_doc["process"])
            w = {
                str(k): int(v)
                for k, v in (peer_doc.get("weights") or {}).items()
            }
        except (KeyError, TypeError, ValueError):
            out["errors"] += 1
            continue
        if pk == str(process_key):
            continue  # a stale slot from a restarted self
        foreign[pk] = {"weights": w, "total": sum(w.values())}
        out["peers"] += 1
    return foreign, out


def verdict_context(verdict: dict, op: Optional[str] = None) -> dict:
    """Structured ``ACCLError.details`` for a contract verdict: the
    diverging rank rides at top level (the one-line answer), the full
    verdict underneath."""
    ctx = {
        "diverging_rank": verdict.get("diverging_rank"),
        "contract": verdict,
        "comm": verdict.get("comm"),
    }
    if op is not None:
        ctx["op"] = op
    return ctx
