"""The QoS arbiter plane: weighted-fair scheduling of concurrent tenants.

Role model: the reference multiplexes many command clients into ONE
offload engine via the ``client_arbiter`` plugin — a hardware round-robin
in front of the CCLO's command FIFO, so a long-lived engine can serve
several host applications at once (PAPER.md L1/Lx).  Our production
analog is many concurrent *jobs* sharing one fabric: a latency-bound
serving communicator and a bulk best-effort communicator live on the
same ICI links, the same engine scheduler, the same in-flight window and
the same command-ring refill windows — and every one of those queues is
first-come-first-served today, so the bulk job can starve the serving
job arbitrarily.  This module is the scheduling half of ROADMAP item 3
(the elastic-membership half landed in PR 12): per-communicator
**tenants** with priority classes, a **deficit-weighted round-robin**
admission queue in front of engine dispatch, and quota levers at the two
places contention actually lives — per-tenant shares of the overlap
plane's in-flight window depth and per-tenant slot budgets in the
command ring's refill windows — plus optional token-bucket bytes/s caps.

Three coupled pieces:

* **Tenant registry** (:class:`Tenant` + :meth:`QosArbiter.register`) —
  one tenant per communicator id, carrying a :class:`TenantClass`
  (GUARANTEED / BURST / BEST_EFFORT), a DRR weight (class default,
  overridable), a per-OWNER (= per rank handle) outstanding-admission
  bound at the tenant's in-flight window share — bounding ranks
  independently keeps one rank's intake thread from hoarding the
  tenant allowance and starving its peers' halves of the same
  collectives — and the optional token bucket.

* **DRR admission** (:meth:`QosArbiter.admit`) — every gated collective
  enqueues a ticket; tickets are granted in deficit-weighted round-robin
  order across tenants: each round refills every tenant's deficit by
  ``weight x quantum`` bytes and grants affordable queue heads
  round-robin, and a tenant at its outstanding limit simply waits for a
  completion (:meth:`QosArbiter.release`) to free a slot — the
  backpressure a flooder absorbs while a guaranteed tenant's small calls
  keep flowing.  Rounds advance the moment no queued tenant can afford
  its head (classic DRR: no time dimension, work-conserving when a
  tenant is alone).  Every wait is bounded (``ACCL_ARBITER_MAX_WAIT_S``):
  a starved ticket over-admits with a counted reason rather than wedging
  the submitting thread — the overlap plane's ``park`` discipline.

* **Decision latch** (the ``admit`` ledger) — scheduling must be
  SPMD-uniform: every rank of a communicator must admit the same call
  with the same throttle, or the ranks' call timings diverge and the
  contract verifier starts arguing.  The per-(comm, call index) decision
  record — tenant class and token-bucket throttle — is therefore
  computed ONCE by the first rank to reach a call index and latched on
  the shared arbiter (the PR 12 ``DemotionLedger`` discipline: one
  shared state machine per process anchor, every in-process rank reads
  the same decision; one-process-per-rank tiers replay identical
  per-comm call streams through identical per-process state, which
  derives the same records).  The DRR grant itself never alters call
  CONTENT or intra-comm order — admission can only delay a whole call
  uniformly — so the latch covers everything that must agree.

Opt-in: registration and quota writes are always accepted (sensing),
but the acting half — DRR queueing, throttles — arms via
``ACCL_ARBITER=1`` or ``ACCL.set_arbiter(True)``.  Disabled, the intake
gate is one attribute check.  Zero dependencies (stdlib only): this
module joins the jax-free import closure next to ``membership`` and is
machine-checked by acclint's jax-free-module pass.
"""

from __future__ import annotations

import enum
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from .analysis.markers import spmd_uniform
from .constants import (
    CMDRING_MAX_DEPTH,
    ConfigFunction,
    DEFAULT_INFLIGHT_WINDOW,
    MAX_INFLIGHT_WINDOW,
)
from .contract import anchored

__all__ = [
    "ARBITER_ENV",
    "CLASS_WEIGHTS",
    "LEDGER_ENV",
    "QosArbiter",
    "Tenant",
    "TenantClass",
    "TenantLedger",
    "TokenBucket",
    "arbiter_for",
    "env_arbiter",
    "env_ledger",
    "hist_p99_us",
    "tenant_config_field",
    "tenant_config_valid",
]

ARBITER_ENV = "ACCL_ARBITER"
MAX_WAIT_ENV = "ACCL_ARBITER_MAX_WAIT_S"
QUANTUM_ENV = "ACCL_ARBITER_QUANTUM"
#: opt-in for the CROSS-PROCESS tenant registry (dist tier): per-process
#: arbiters publish their tenant weight tables through the KV plane and
#: derive fabric-share token-bucket caps from the fleet-wide totals
LEDGER_ENV = "ACCL_ARBITER_LEDGER"
#: modeled fabric capacity the ledger divides into per-tenant shares
#: (bytes/s); the honest default is deliberately generous — the ledger
#: exists for *relative* fairness, and an operator who knows the link
#: sets the real number
LEDGER_FABRIC_ENV = "ACCL_ARBITER_FABRIC_BYTES_S"
DEFAULT_LEDGER_FABRIC_BYTES_S = 1e9

#: DRR credit granted per weight unit per round, in bytes.  Small
#: enough that a BEST_EFFORT flooder's large payloads span several
#: rounds (real interleaving), large enough that a GUARANTEED tenant's
#: small serving messages never wait a round for credit.
DEFAULT_QUANTUM = 64 * 1024
#: bounded admission wait before a ticket over-admits (counted): the
#: park_timeout_s discipline — the arbiter must never wedge intake.
DEFAULT_MAX_WAIT_S = 30.0
#: latched per-(comm, seq) admission decisions retained (the
#: DemotionLedger cap discipline)
_DECISION_CAP = 512
#: deficit accrual cap, in rounds-worth of quantum: an idle-ish tenant
#: must not bank unbounded credit and then monopolize a burst
_DEFICIT_CAP_ROUNDS = 2


class TenantClass(enum.IntEnum):
    """Priority class of one tenant communicator (the reference
    client_arbiter has no classes — every client is equal; production
    multi-tenancy needs the serving/training/scavenger split)."""

    GUARANTEED = 0   # latency-bound serving traffic: highest weight
    BURST = 1        # interactive/batch traffic with headroom to spare
    BEST_EFFORT = 2  # bulk scavenger traffic: absorbs all backpressure


#: default DRR weight per class (overridable per tenant)
CLASS_WEIGHTS = {
    TenantClass.GUARANTEED: 8,
    TenantClass.BURST: 4,
    TenantClass.BEST_EFFORT: 1,
}

MAX_TENANT_WEIGHT = 64


def env_arbiter(environ=None) -> bool:
    """The ``ACCL_ARBITER`` opt-in (read at ACCL-handle construction):
    arms the acting half — DRR admission queueing and throttles."""
    return (environ or os.environ).get(ARBITER_ENV, "0") not in ("0", "")


def env_ledger(environ=None) -> bool:
    """The ``ACCL_ARBITER_LEDGER`` opt-in (read at ACCL-handle
    construction on KV-capable tiers): arms the cross-process tenant
    registry exchange."""
    return (environ or os.environ).get(LEDGER_ENV, "0") not in ("0", "")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def arbiter_for(anchor) -> Optional["QosArbiter"]:
    """The :class:`QosArbiter` shared by every rank handle anchored on
    ``anchor`` (the engine's ``contract_anchor()`` — the same anchor
    discipline as the contract board and the demotion ledger); None on
    one-process-per-rank tiers, where each rank process runs its own
    arbiter over an identical per-comm call stream."""
    return anchored(anchor, "_accl_qos_arbiter", QosArbiter)


def tenant_config_field(fn) -> str:
    """``"class"`` / ``"weight"`` / ``"window_share"`` /
    ``"ring_slots"`` / ``"rate"`` from a ``SET_TENANT_*``
    :class:`~accl_tpu.constants.ConfigFunction` — the engine-mirror
    field name, derived in ONE place."""
    return ConfigFunction(fn).name[len("SET_TENANT_"):].lower()


def tenant_config_valid(fn, value) -> bool:
    """THE validator every engine tier applies to a ``SET_TENANT_*``
    write — one shared range table, so a tenant config accepted on one
    tier can never be CONFIG_ERROR on another (the portability the
    config surface promises).  Ranges derive from the authoritative
    constants, not hardcoded maxima."""
    fn = ConfigFunction(fn)
    if fn == ConfigFunction.SET_TENANT_CLASS:
        return 0 <= value <= max(TenantClass)
    if fn == ConfigFunction.SET_TENANT_WEIGHT:
        return 1 <= value <= MAX_TENANT_WEIGHT
    if fn == ConfigFunction.SET_TENANT_WINDOW_SHARE:
        return 1 <= value <= MAX_INFLIGHT_WINDOW
    if fn == ConfigFunction.SET_TENANT_RING_SLOTS:
        return 1 <= value <= CMDRING_MAX_DEPTH
    if fn == ConfigFunction.SET_TENANT_RATE:
        return value >= 0
    return False


def coerce_class(value) -> TenantClass:
    """A :class:`TenantClass` from an enum / int / name string."""
    if isinstance(value, TenantClass):
        return value
    if isinstance(value, str):
        try:
            return TenantClass[value.upper()]
        except KeyError:
            raise ValueError(
                f"unknown tenant class {value!r}; valid: "
                f"{[c.name.lower() for c in TenantClass]}"
            ) from None
    return TenantClass(int(value))


def hist_p99_us(hist: dict) -> Optional[float]:
    """p99 upper bound in us from a log2-us bucket histogram
    (``{"count": n, "log2_us": {bucket: n}}`` — the telemetry plane's
    shape): the upper edge of the first bucket whose cumulative count
    reaches the 99th percentile.  None on an empty histogram.  The
    monitor plane's ``/tenants`` route and the bench's adversarial-load
    gate both read tail latency through this ONE estimator."""
    count = int(hist.get("count") or 0)
    if count <= 0:
        return None
    need = count - count // 100  # ceil(0.99 * count) for count < 100
    cum = 0
    for b, n in sorted(
        ((int(k), int(v)) for k, v in (hist.get("log2_us") or {}).items())
    ):
        cum += n
        if cum >= need:
            return float(2 ** (b + 1))
    return None


def _log2_bucket(us: int) -> int:
    return max(0, int(us).bit_length() - 1)


class TokenBucket:
    """Bytes/s cap with burst headroom, monotonic-clock timed.

    ``throttle_ns(cost)`` consumes ``cost`` tokens and returns how long
    the caller must wait for the bucket to have covered them — tokens go
    negative (the debt model), so the delay is exact for back-to-back
    callers without a reservation queue.  The clock is injectable for
    deterministic tests.
    """

    def __init__(self, rate_bytes_s: float, burst_bytes: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate_bytes_s)
        self.burst = float(
            burst_bytes if burst_bytes is not None else max(self.rate, 1.0)
        )
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = self.burst
        self._last = clock()

    def throttle_ns(self, cost: int) -> int:
        """Consume ``cost`` bytes; ns the caller owes the cap (0 when
        the burst allowance covers it)."""
        if self.rate <= 0:
            return 0
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rate
            )
            self._last = now
            self._tokens -= float(cost)
            if self._tokens >= 0:
                return 0
            return int(-self._tokens / self.rate * 1e9)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "rate_bytes_s": self.rate,
                "burst_bytes": self.burst,
                "tokens": round(self._tokens, 1),
            }


class _Ticket:
    __slots__ = ("cost", "granted")

    def __init__(self, cost: int):
        self.cost = cost
        self.granted = False


class Tenant:
    """One registered tenant communicator's arbiter-side state.

    Admission bookkeeping is per OWNER (one owner = one rank handle):
    the in-flight bound is the tenant's *per-rank* window share, and a
    collective occupies one admission on every rank — bounding ranks
    independently is what keeps one rank's intake thread from grabbing
    the whole tenant allowance and starving its peers' halves of the
    same collectives (which can only complete when every rank admits).
    DRR credit stays tenant-wide: the tenant is the unit of fairness.

    All mutation happens under the owning arbiter's lock; ``snapshot``
    is served through the arbiter too.
    """

    __slots__ = (
        "comm_id", "name", "cls", "weight", "world", "window_share",
        "ring_slots", "bucket", "deficit", "queues", "owner_rr",
        "outstanding", "_inflight", "outstanding_peak", "admitted",
        "completed", "cost_granted", "grant_wait_ns",
        "throttle_ns_total", "over_admissions", "queued_peak", "hist",
        "template", "auto_rate",
    )

    def __init__(self, comm_id: int, name: str, cls: TenantClass,
                 weight: int, world: int):
        self.comm_id = int(comm_id)
        self.name = name
        self.cls = cls
        self.weight = int(weight)
        self.world = max(1, int(world))
        self.window_share = DEFAULT_INFLIGHT_WINDOW
        self.ring_slots: Optional[int] = None
        self.bucket: Optional[TokenBucket] = None
        # True when the bucket was derived by the cross-process ledger
        # (a fabric share, re-derived on every exchange); an explicit
        # set_quota rate clears it and is never overwritten by shares
        self.auto_rate = False
        self.deficit = 0
        # per-owner (rank handle) waiting tickets + in-flight counts;
        # _inflight mirrors sum(outstanding.values()) so the hot path
        # never sums the dict
        self.queues: Dict[int, deque] = {}
        self.owner_rr: List[int] = []  # owner scan order (first-seen)
        self.outstanding: Dict[int, int] = {}
        self._inflight = 0
        self.outstanding_peak = 0
        self.admitted = 0
        self.completed = 0
        self.cost_granted = 0
        self.grant_wait_ns = 0
        self.throttle_ns_total = 0
        self.over_admissions = 0
        self.queued_peak = 0
        # per-tenant completion-latency histogram, telemetry-shaped:
        # [count, sum_ns, {log2_us: n}] — the monitor plane serves it
        # live and hist_p99_us reads the tail off it
        self.hist: list = [0, 0, {}]
        # pre-built decision-record template (enum .name lookups and
        # key construction off the admission hot path)
        self.template: dict = {}
        self.retemplate()

    def retemplate(self) -> None:
        self.template = {
            "seq": 0,
            "tenant": self.name,
            "class": self.cls.name,
            "throttle_ns": 0,
            "latched": False,
        }

    def queue_for(self, owner: int) -> deque:
        q = self.queues.get(owner)
        if q is None:
            q = self.queues[owner] = deque()
            self.owner_rr.append(owner)
        return q

    def queued(self) -> int:
        return sum(len(q) for q in self.queues.values())

    def in_flight(self) -> int:
        return self._inflight

    def grantable_owner(self) -> Optional[int]:
        """The first owner (scan order) with a waiting head under its
        per-rank limit; None when every queued owner is pinned."""
        for owner in self.owner_rr:
            q = self.queues.get(owner)
            if q and self.outstanding.get(owner, 0) < self.window_share:
                return owner
        return None

    def snapshot(self) -> dict:
        count, sum_ns, buckets = self.hist
        hist = {
            "count": count,
            "sum_ns": sum_ns,
            "mean_us": round(sum_ns / count / 1e3, 3) if count else 0,
            "log2_us": {str(k): v for k, v in sorted(buckets.items())},
        }
        return {
            "comm": self.comm_id,
            "name": self.name,
            "class": self.cls.name,
            "weight": self.weight,
            "world": self.world,
            "window_share": self.window_share,
            "ring_slots": self.ring_slots,
            "rate": self.bucket.snapshot() if self.bucket else None,
            "auto_rate": self.auto_rate,
            "outstanding": self.in_flight(),
            "outstanding_peak": self.outstanding_peak,
            "outstanding_limit": self.window_share,
            "queued": self.queued(),
            "queued_peak": self.queued_peak,
            "admitted": self.admitted,
            "completed": self.completed,
            "cost_granted_bytes": self.cost_granted,
            "grant_wait_ns_total": self.grant_wait_ns,
            "throttle_ns_total": self.throttle_ns_total,
            "over_admissions": self.over_admissions,
            "latency": dict(hist, p99_us=hist_p99_us(hist)),
        }


class TenantLedger:
    """Cross-process tenant-weight registry state for one arbiter.

    Each process posts its local ``{tenant name: weight}`` map into the
    dist tier's KV plane (the same plane the contract-digest ledger
    rides) and sweeps every peer's posting back.  The arbiter then
    re-derives per-tenant token-bucket rates as *fabric shares*:

        rate = fabric_bytes_s * weight / (local_total + foreign_total)

    so a GUARANTEED tenant in one process squeezes a BEST_EFFORT tenant
    in another even though the two arbiters share no lock — only the KV
    plane.  Derived rates are marked ``auto_rate`` and are re-derived on
    every exchange; explicit ``set_quota`` rates are never overwritten.
    """

    __slots__ = ("process_key", "fabric_bytes_s", "state", "foreign",
                 "exchanges", "posted", "errors")

    def __init__(self, process_key: str,
                 fabric_bytes_s: Optional[float] = None):
        self.process_key = str(process_key)
        self.fabric_bytes_s = float(
            fabric_bytes_s if fabric_bytes_s is not None
            else _env_float(LEDGER_FABRIC_ENV, DEFAULT_LEDGER_FABRIC_BYTES_S)
        )
        # exchange-protocol scratch (slot claim + last posted doc) owned
        # by contract.kv_tenant_exchange
        self.state: dict = {}
        # last swept view: {process_key: {"weights": {...}, "total": n}}
        self.foreign: dict = {}
        self.exchanges = 0
        self.posted = 0
        self.errors = 0

    def foreign_weight(self) -> int:
        """Sum of every foreign process's tenant weights (the
        denominator share the local tenants compete against)."""
        return sum(int(doc.get("total", 0))
                   for doc in self.foreign.values())

    def snapshot(self) -> dict:
        return {
            "process": self.process_key,
            "fabric_bytes_s": self.fabric_bytes_s,
            "peers": len(self.foreign),
            "foreign_weight": self.foreign_weight(),
            "exchanges": self.exchanges,
            "posted": self.posted,
            "errors": self.errors,
        }


class QosArbiter:
    """Deficit-weighted round-robin admission in front of engine
    dispatch, shared by every rank handle on one process anchor.

    One lock + condition covers the whole machine (registry, queues,
    deficits, the decision latch) — admission is a handful of integer
    ops per call, and the single lock keeps the grant order globally
    consistent (the fairness the tests counter-assert).
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self.armed = False
        self._clock = clock
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._tenants: Dict[int, Tenant] = {}
        self._order: List[int] = []  # registration order (RR scan)
        self._rr = 0
        self.quantum = max(
            1024, int(_env_float(QUANTUM_ENV, DEFAULT_QUANTUM))
        )
        self.max_wait_s = max(
            0.1, _env_float(MAX_WAIT_ENV, DEFAULT_MAX_WAIT_S)
        )
        # per-(comm, seq) latched decisions (DemotionLedger discipline;
        # engaged only for token-bucket tenants — see _admitted)
        self._decisions: Dict[tuple, dict] = {}
        self._decision_order: deque = deque()
        # queued tickets across every tenant: the hot path's contention
        # probe — zero means admit/release may skip the DRR pump
        self._waiting = 0
        self.rounds = 0
        self.grant_timeouts = 0
        self.passthrough = 0
        # cross-process tenant registry (attached when ACCL_ARBITER_LEDGER
        # is set and the engine exposes a KV plane); None = local-only
        self.ledger: Optional[TenantLedger] = None

    # -- registry ------------------------------------------------------------
    def register(self, comm_id: int, name: Optional[str] = None,
                 cls=TenantClass.BEST_EFFORT, weight: Optional[int] = None,
                 world: int = 1) -> Tenant:
        """Register (or re-class) the tenant behind ``comm_id``.
        Collective by contract: every rank of the communicator registers
        it with the same class/weight at the same call-sequence point —
        the same discipline every other config write carries."""
        cls = coerce_class(cls)
        w = int(weight) if weight is not None else CLASS_WEIGHTS[cls]
        w = max(1, min(w, MAX_TENANT_WEIGHT))
        with self._cv:
            t = self._tenants.get(int(comm_id))
            if t is None:
                t = Tenant(comm_id, name or f"comm-{comm_id}", cls, w,
                           world)
                self._tenants[t.comm_id] = t
                self._order.append(t.comm_id)
            else:
                t.cls = cls
                t.weight = w
                if name:
                    t.name = name
                if world > 1:
                    t.world = int(world)
                t.retemplate()
            self._cv.notify_all()
            return t

    def set_quota(self, comm_id: int, window_share: Optional[int] = None,
                  ring_slots: Optional[int] = None,
                  bytes_per_s: Optional[float] = None) -> Optional[Tenant]:
        """Quota writes for a registered tenant; None for unknown ids
        (quotas without a registered tenant have nothing to govern)."""
        with self._cv:
            t = self._tenants.get(int(comm_id))
            if t is None:
                return None
            if window_share is not None:
                t.window_share = max(
                    1, min(int(window_share), MAX_INFLIGHT_WINDOW)
                )
            if ring_slots is not None:
                t.ring_slots = max(1, int(ring_slots))
            if bytes_per_s is not None:
                t.bucket = (
                    TokenBucket(float(bytes_per_s), clock=self._clock)
                    if bytes_per_s > 0 else None
                )
                # an explicit operator rate is authoritative: the
                # cross-process ledger must never overwrite it with a
                # derived fabric share
                t.auto_rate = False
            self._cv.notify_all()
            return t

    def tenant(self, comm_id: int) -> Optional[Tenant]:
        with self._lock:
            return self._tenants.get(int(comm_id))

    # -- admission (the DRR queue) -------------------------------------------
    @spmd_uniform
    def admit(self, comm_id: int, seq: int, cost: int,
              timeout_s: Optional[float] = None,
              pace: bool = True, owner: int = 0) -> Optional[dict]:
        """Admit call index ``seq`` of communicator ``comm_id`` costing
        ``cost`` bytes.  Blocks (bounded) while the tenant is out of DRR
        credit or at its outstanding limit; returns the latched decision
        record — identical on every rank of the comm by construction —
        or None when the arbiter is disarmed / the comm unregistered
        (pass-through, counted).

        ``pace=False`` charges without queueing (DRR credit, token
        bucket, counters — no outstanding slot, no grant wait): the
        facade uses it for calls queued into an open batch, whose
        dispatch unit is the flushed window — a queued call cannot
        complete before its batch flushes, so holding an admission slot
        for it would wedge any batch deeper than the tenant's limit.
        Batched traffic is quota'd where its contention lives instead:
        the command ring's per-tenant slot budget."""
        with self._cv:
            t = self._tenants.get(int(comm_id))
            if not self.armed or t is None:
                self.passthrough += 1
                return None
            cost = max(1, int(cost))
            o = int(owner)
            if not pace:
                t.deficit = max(0, t.deficit - cost)  # charged, unqueued
                decision, waited = self._admitted(t, comm_id, seq, cost, 0)
            elif (
                self._waiting == 0
                and t.outstanding.get(o, 0) < t.window_share
            ):
                # uncontended fast path: nothing queued anywhere and
                # this owner has window headroom — grant inline, no
                # ticket, no DRR pump, no wait timers (the whole
                # machine only engages under contention; the warm-path
                # budget depends on it)
                t.outstanding[o] = t.outstanding.get(o, 0) + 1
                t._inflight += 1
                t.outstanding_peak = max(
                    t.outstanding_peak, t._inflight
                )
                decision, waited = self._admitted(t, comm_id, seq, cost, 0)
            else:
                t0 = time.perf_counter_ns()
                ticket = _Ticket(cost)
                t.queue_for(o).append(ticket)
                self._waiting += 1
                t.queued_peak = max(t.queued_peak, t.queued())
                self._pump()
                if not ticket.granted:
                    bound = min(
                        self.max_wait_s,
                        timeout_s if timeout_s is not None
                        else self.max_wait_s,
                    )
                    deadline = self._clock() + max(0.05, bound)
                    while not ticket.granted:
                        rem = deadline - self._clock()
                        if rem <= 0:
                            break
                        self._cv.wait(min(rem, 0.5))
                    if not ticket.granted:
                        # bounded wait expired: over-admit (counted) —
                        # the arbiter must never wedge intake; the
                        # facade's deadlock deadlines stay the last word
                        try:
                            t.queue_for(o).remove(ticket)
                        except ValueError:  # granted in the race window
                            pass
                        else:
                            self._waiting -= 1
                            t.outstanding[o] = (
                                t.outstanding.get(o, 0) + 1
                            )
                            t._inflight += 1
                            t.outstanding_peak = max(
                                t.outstanding_peak, t._inflight
                            )
                            t.over_admissions += 1
                            self.grant_timeouts += 1
                            ticket.granted = True
                decision, waited = self._admitted(t, comm_id, seq, cost, t0)
        throttle_ns = decision["throttle_ns"]
        if throttle_ns > 0:
            # bytes/s cap: the latched debt, paid outside the lock so a
            # throttled tenant never blocks its peers' admissions;
            # bounded by the same admission ceiling
            time.sleep(min(throttle_ns / 1e9, self.max_wait_s))
        # `paced` is the CALLER's accounting truth (did this admission
        # take an outstanding slot, i.e. must its completion release
        # one) — per handle, deliberately not the latched value.  A
        # ledger-shared record is copied; the unlatched fast-path dict
        # is fresh and stamped in place.
        if decision.get("latched", True):
            return dict(decision, wait_ns=int(waited), paced=bool(pace))
        decision["wait_ns"] = int(waited)
        decision["paced"] = bool(pace)
        return decision

    def _admitted(self, t: Tenant, comm_id: int, seq: int, cost: int,
                  t0: int) -> tuple:
        """Account one admission + fetch-or-latch its decision record
        (arbiter lock held).  The token bucket is consumed ONCE per
        logical call — the first rank to a call index computes the
        throttle, every later rank replays it.  Unthrottled tenants
        carry nothing stateful in the record (class and name are
        registration constants, identical on every rank), so the
        ledger only engages when a bucket makes the decision
        path-dependent — the warm path skips the dict churn.  ``t0``
        of 0 means the grant was inline (no wait, no timer taken).
        ``seq < 0`` means NO LATCH: plain p2p is rank-asymmetric by
        design (the contract plane exempts it for the same reason), so
        its admissions never consume the shared per-(comm, call index)
        space — a p2p decision charges this handle's side of the
        bucket directly, and collective call indices stay aligned
        across ranks however asymmetric the p2p pattern is."""
        waited = time.perf_counter_ns() - t0 if t0 else 0
        t.admitted += 1
        t.cost_granted += cost
        t.grant_wait_ns += waited
        if t.bucket is None:
            # fresh (unshared) dict off the template: admit() may stamp
            # wait_ns/paced into it directly instead of paying a copy
            decision = dict(t.template)
            decision["seq"] = int(seq)
            return decision, waited
        if seq < 0:  # p2p: local charge, no shared-ledger entry
            decision = dict(t.template)
            decision["seq"] = -1
            decision["throttle_ns"] = int(t.bucket.throttle_ns(cost))
            t.throttle_ns_total += decision["throttle_ns"]
            return decision, waited
        key = (int(comm_id), int(seq))
        decision = self._decisions.get(key)
        if decision is None:
            decision = {
                "seq": int(seq),
                "tenant": t.name,
                "class": t.cls.name,
                "throttle_ns": int(t.bucket.throttle_ns(cost)),
            }
            self._decisions[key] = decision
            self._decision_order.append(key)
            while len(self._decision_order) > _DECISION_CAP:
                self._decisions.pop(
                    self._decision_order.popleft(), None
                )
        t.throttle_ns_total += decision["throttle_ns"]
        return decision, waited

    def release(self, comm_id: int, owner: int = 0) -> None:
        """One admitted call completed on ``owner``'s handle: free its
        outstanding slot and grant whatever the freed capacity now
        affords."""
        with self._cv:
            t = self._tenants.get(int(comm_id))
            if t is None:
                return
            t.completed += 1
            o = int(owner)
            if t.outstanding.get(o, 0) > 0:
                t.outstanding[o] -= 1
                t._inflight -= 1
            if self._waiting:
                self._pump()

    def complete(self, comm_id: int, duration_ns: int,
                 owner: int = 0, release: bool = True) -> None:
        """The completion fast lane (the facade's Request
        done-callback): release + latency observation under ONE lock
        acquisition — the separate calls each pay a lock and measured
        ~2x this on the warm path."""
        with self._cv:
            t = self._tenants.get(int(comm_id))
            if t is None:
                return
            # completion counts unconditionally — a batched
            # (charge-only) call really did complete; only the SLOT
            # release is conditional on having taken one
            t.completed += 1
            if release:
                o = int(owner)
                if t.outstanding.get(o, 0) > 0:
                    t.outstanding[o] -= 1
                    t._inflight -= 1
                if self._waiting:
                    self._pump()
            h = t.hist
            h[0] += 1
            h[1] += int(duration_ns)
            b = _log2_bucket(int(duration_ns) // 1000)
            h[2][b] = h[2].get(b, 0) + 1

    def _pump(self) -> None:
        """Grant waiting tickets in deficit-weighted round-robin order
        (lock held).  Within a tenant, owners (rank handles) are
        scanned in first-seen order, each bounded at the tenant's
        per-rank window share — one rank's backlog never pins a slot a
        peer rank needs to complete the same collective.  Terminates:
        every grant consumes a ticket, and a round only advances while
        some queued owner is under its limit — pinned owners wait for
        :meth:`release`, which pumps again."""
        while True:
            n = len(self._order)
            granted = False
            for i in range(n):
                cid = self._order[(self._rr + i) % n]
                t = self._tenants[cid]
                owner = t.grantable_owner()
                if owner is None:
                    continue
                head = t.queues[owner][0]
                if t.deficit < head.cost:
                    continue
                t.deficit -= head.cost
                t.queues[owner].popleft()
                self._waiting -= 1
                if not t.queued():
                    # classic DRR: an emptied queue banks nothing
                    t.deficit = 0
                head.granted = True
                t.outstanding[owner] = t.outstanding.get(owner, 0) + 1
                t._inflight += 1
                t.outstanding_peak = max(
                    t.outstanding_peak, t._inflight
                )
                self._rr = (self._rr + i + 1) % n
                granted = True
                break
            if granted:
                self._cv.notify_all()
                continue
            # nothing affordable: advance rounds for the tenants still
            # eligible (a queued owner under its limit) — by exactly
            # enough rounds that the cheapest head becomes affordable,
            # so a lone big payload costs O(1) bookkeeping, not
            # O(cost/quantum)
            eligible = []
            for t in self._tenants.values():
                owner = t.grantable_owner()
                if owner is not None:
                    eligible.append((t, t.queues[owner][0].cost))
            if not eligible:
                return
            need = min(
                max(
                    1,
                    -(-(cost - t.deficit) // (t.weight * self.quantum)),
                )
                for t, cost in eligible
            )
            self.rounds += need
            for t, cost in eligible:
                t.deficit = min(
                    t.deficit + need * t.weight * self.quantum,
                    _DEFICIT_CAP_ROUNDS * t.weight * self.quantum + cost,
                )

    # -- cross-process tenant registry ---------------------------------------
    def attach_ledger(self, ledger: TenantLedger) -> TenantLedger:
        """Arm the cross-process registry: subsequent
        ``ledger_exchange`` calls post local weights and re-derive
        fabric-share rates against the swept foreign total."""
        with self._cv:
            self.ledger = ledger
            return ledger

    def local_weights(self) -> Dict[str, int]:
        """``{tenant name: weight}`` for every registered tenant — the
        doc this process posts to the KV plane."""
        with self._lock:
            return {
                self._tenants[cid].name: int(self._tenants[cid].weight)
                for cid in self._order
            }

    def ledger_exchange(self, kv, is_notfound=None) -> Optional[dict]:
        """Post local tenant weights through ``kv`` and re-derive
        fabric-share token-bucket rates from the swept peer view.
        Returns the exchange counters, or None when no ledger is
        attached (local-only arbiter)."""
        led = self.ledger
        if led is None:
            return None
        from . import contract as _contract
        weights = self.local_weights()
        foreign, out = _contract.kv_tenant_exchange(
            kv, led.process_key, weights, led.state,
            is_notfound=is_notfound,
        )
        led.foreign = foreign
        led.exchanges += 1
        led.posted += int(out.get("posted", 0))
        led.errors += int(out.get("errors", 0))
        self._apply_ledger_shares()
        return out

    def _apply_ledger_shares(self) -> None:
        """Re-derive auto token-bucket rates as fabric shares.  Only
        buckets the ledger itself installed (``auto_rate``) or tenants
        with no bucket at all are touched — explicit ``set_quota``
        rates stay authoritative.  With no foreign peers the auto caps
        are lifted entirely (nothing to share the fabric with)."""
        led = self.ledger
        if led is None:
            return
        with self._cv:
            foreign_total = led.foreign_weight()
            local_total = sum(
                int(t.weight) for t in self._tenants.values()
            )
            for t in self._tenants.values():
                if t.bucket is not None and not t.auto_rate:
                    continue  # explicit operator rate
                if foreign_total <= 0:
                    # sole process on the fabric: an auto cap would
                    # only throttle against nobody
                    t.bucket = None
                    t.auto_rate = False
                    continue
                total = local_total + foreign_total
                if total <= 0:
                    continue
                rate = led.fabric_bytes_s * (int(t.weight) / total)
                if rate > 0:
                    t.bucket = TokenBucket(rate, clock=self._clock)
                    t.auto_rate = True
            self._cv.notify_all()

    # -- recovery / telemetry ------------------------------------------------
    def reset_ledger(self) -> None:
        """soft_reset recovery: drop latched decisions and DRR credit —
        the facade's per-comm call-index counters restart at 0, and a
        stale latched decision for those indices would replay pre-reset
        throttles.  Registrations and counters survive (quotas are
        config state, like the tuning registers)."""
        with self._cv:
            self._decisions.clear()
            self._decision_order.clear()
            for t in self._tenants.values():
                t.deficit = 0
            self._cv.notify_all()

    def window_share_of(self, comm_id: int) -> Optional[int]:
        """The tenant's per-rank in-flight window share (None when
        unregistered) — the overlap plane reads its per-key depth
        override through this accessor."""
        with self._lock:
            t = self._tenants.get(int(comm_id))
            return t.window_share if t is not None else None

    def ring_slots_of(self, comm_id: int) -> Optional[int]:
        """The tenant's per-refill-window ring slot budget (None when
        unregistered or unbudgeted)."""
        with self._lock:
            t = self._tenants.get(int(comm_id))
            return t.ring_slots if t is not None else None

    def snapshot(self) -> dict:
        """The merged-telemetry view (``telemetry_snapshot()["tenants"]``
        and the monitor plane's ``/tenants`` route serve this live)."""
        with self._lock:
            return {
                "enabled": self.armed,
                "quantum": self.quantum,
                "rounds": self.rounds,
                "grant_timeouts": self.grant_timeouts,
                "passthrough": self.passthrough,
                "ledger": (
                    self.ledger.snapshot()
                    if self.ledger is not None else None
                ),
                "tenants": {
                    str(cid): self._tenants[cid].snapshot()
                    for cid in self._order
                },
            }
