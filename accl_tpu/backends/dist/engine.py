"""The multi-process (jax.distributed) collective engine.

One :class:`DistEngine` per OS process; the process owns the rank equal to
``jax.process_index()`` and that rank's device HBM.  Collectives are SPMD:
every member process calls the facade op in the same order (exactly the
contract mpirun imposes on the reference's per-rank hosts), each
contributes its local shard via ``jax.make_array_from_single_device_arrays``
(zero host copies for device-resident buffers), and all run the identical
jitted program over the global mesh.  Matched send/recv pairs run a
two-device collective-permute program in just the two owning processes.

Differences from the single-process gang (backends/xla):
* no rendezvous slot machinery — program order IS the match (SPMD);
* the barrier is a real cross-process device collective, not gang
  assembly;
* remote stream ports ride the distributed runtime's key-value service
  (one-sided, sequence-ordered — see the "remote stream ports" section
  below): a control-plane hop sized for kernel handoffs, not bulk data.
"""

from __future__ import annotations

import functools
import time
import traceback
from typing import Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ...analysis.markers import spmd_uniform
from ...buffer import DeviceBuffer, dev_zeros as _dev_zeros, make_buffer
from ...communicator import Communicator, Rank
from ...constants import (
    CompressionFlags,
    ConfigFunction,
    DEFAULT_TIMEOUT_S,
    ErrorCode,
    MAX_EAGER_SIZE_LIMIT,
    Operation,
    ReduceFunction,
    StreamFlags,
    dtype_to_numpy,
)
from ...ops import driver as opdriver
from ...request import Request
from ..base import BaseEngine, CallOptions, InteractionCounter, StreamPortMixin
from ..xla.engine import (
    IN_W,
    OUT_W,
    apply_tuning,
    _cast_program,
    _p2p_hop_program,
    _write_host_result,
    run_allreduce_with_tuning,
    run_rooted_with_tuning,
)


#: collectives whose completion advances the contract verifier's digest
#: (the facade's _CONTRACT_OPS) — only these can complete a verification
#: window, so only they trigger the KV digest-piggyback exchange
_KV_VERIFIED_OPS = frozenset((
    Operation.BCAST, Operation.SCATTER, Operation.GATHER,
    Operation.ALLGATHER, Operation.REDUCE, Operation.ALLREDUCE,
    Operation.REDUCE_SCATTER, Operation.ALLTOALL, Operation.BARRIER,
))


def _bucket_width(n: int) -> int:
    """Power-of-two wire bucket (floor 8) for a per-chunk element count.

    Every XLA program this engine dispatches is specialized on its
    operand shapes: without bucketing, a workload sweeping arbitrary
    counts compiles a FRESH collective program per distinct size (the
    round-4 soak measured ~3 ops/s on the dist tier for exactly this
    reason — nearly every op was a cold compile).  Padding each chunk
    to the next power of two caps the program population at ~log2(max
    count) per collective and turns the steady state into cached-
    dispatch latency — the same static-shapes discipline XLA demands of
    TPU programs generally.  Zero-padding is neutral for every op here:
    reductions trim the pad before any result is read, and data-movement
    ops move the pad alongside and trim it at the edge."""
    if n <= 8:
        return 8
    return 1 << (n - 1).bit_length()


@functools.lru_cache(maxsize=1024)
def _pad_chunks_program(chunks: int, n: int, nb: int, wire_name, device):
    """Device-side re-layout of a (>= chunks*n,) operand into the
    (1, chunks*nb) padded wire row (big device-resident operands; small
    ones pad on the host, see _operand_shard)."""
    from jax.sharding import SingleDeviceSharding

    def f(a):
        m = a[: chunks * n].reshape(chunks, n)
        if wire_name is not None:
            # the shared in-program wire lane (cast lanes + the scaled
            # int8 lane), mirroring the gang tier's decode-loop helper
            from ...ops import wire as devwire

            m = devwire.wire_lane_roundtrip(m, jnp.dtype(wire_name))
        if nb != n:
            m = jnp.pad(m, ((0, 0), (0, nb - n)))
        return m.reshape(1, chunks * nb)

    return jax.jit(f, out_shardings=SingleDeviceSharding(device))


@functools.lru_cache(maxsize=1024)
def _unpad_chunks_program(chunks: int, n: int, nb: int, device,
                          npdt=None):
    """Inverse edge: (1, chunks*nb) padded wire row -> (chunks*n,).
    ``npdt`` fuses the decompress/cast lane into the SAME program (one
    result-side device interaction instead of the old unpad+cast pair —
    the single-interaction dispatch discipline applied to this tier's
    result leg)."""
    from jax.sharding import SingleDeviceSharding

    def f(a):
        a = a.reshape(chunks, nb)[:, :n].reshape(-1)
        if npdt is not None and a.dtype != npdt:
            a = a.astype(npdt)
        return a

    return jax.jit(f, out_shardings=SingleDeviceSharding(device))


class DistEngine(StreamPortMixin, BaseEngine):
    """This process's rank engine over the multi-controller runtime."""

    def __init__(self):
        if jax.process_count() < 2:
            raise RuntimeError(
                "DistEngine needs an initialized jax.distributed runtime "
                "with >= 2 processes (call dist_group_member)"
            )
        self.process_id = jax.process_index()
        locals_ = jax.local_devices()
        # one rank per process: the facade rank maps to this process's
        # first device (multi-device hosts shard within the process via
        # the model-parallel mesh APIs, not the MPI-like facade)
        self.device = locals_[0]
        self.timeout_s = DEFAULT_TIMEOUT_S
        self.max_eager_size = 32 * 1024
        self.max_rendezvous_size = MAX_EAGER_SIZE_LIMIT
        self.retry_limit = 0
        self.retry_backoff_s = 0.05
        self.tuning = {"allreduce_algorithm": "xla", "ring_segments": 1}
        self.interactions = InteractionCounter()
        # overlap plane: this tier's in-flight unit is the serialized
        # executor's backlog — start() applies backpressure once more
        # than `inflight_window` calls are queued ahead of the executor
        # (SET_INFLIGHT_WINDOW / ACCL_INFLIGHT_WINDOW), and
        # drain_inflight() rides a NOP through the queue as the barrier.
        from ...overlap import default_window_depth

        self.inflight_window = default_window_depth()
        # QoS arbiter plane: engine-side mirror of SET_TENANT_* writes
        self.tenants: Dict[int, dict] = {}
        self._init_streams()
        # per-port consumed counter for remotely-posted stream chunks
        import threading as _threading

        self._stream_seq: Dict[int, int] = {}
        self._stream_seq_lock = _threading.Lock()
        # learned not-found signature for KV try-get (see _is_notfound)
        self._nf_sig: Optional[tuple] = None
        self._nf_probed = False
        self._nf_probe_tries = 0
        # compat KV adapter cache (legacy jaxlib clients lack the
        # try-get/increment surface; see compat.kv_client)
        self._kv_raw = None
        self._kv_wrapped = None
        # contract plane: per-comm KV digest-piggyback cursors +
        # lifetime counters (see _kv_contract_exchange)
        self._vfy_kv_state: Dict[int, dict] = {}
        self._vfy_kv_counters: Dict[str, int] = {
            "posted": 0, "claims": 0, "errors": 0,
        }
        self._meshes: Dict[tuple, object] = {}
        # one serialized executor thread (the FPGAQueue role): calls run
        # in submission order — the property SPMD needs — while start()
        # returns immediately so facade timeouts can fire even if a
        # mismatched cross-process program wedges the executor (the
        # reference's wedged-CCLO failure mode, recovered by re-init)
        from ...request import CommandQueue

        self._queue = CommandQueue()
        self._shut = False
        import threading

        self._executor = threading.Thread(
            target=self._run, name="accl-dist-engine", daemon=True
        )
        self._executor.start()
        # global rank -> that process's first device (a process may hold
        # several local devices, e.g. a forced multi-device CPU host or a
        # TPU host with 4 chips; the MPI-like facade rank uses the first)
        self._rank_device: Dict[int, object] = {}
        for d in jax.devices():
            self._rank_device.setdefault(d.process_index, d)

    def _device_of(self, session: int):
        dev = self._rank_device.get(session)
        if dev is None:
            raise ValueError(f"no device for process {session}")
        return dev

    # -- buffers -------------------------------------------------------------
    def create_buffer(self, count: int, dtype, host_only: bool = False,
                      data=None):
        return make_buffer(
            self.device, count, dtype, host_only=host_only, data=data
        )

    # -- mesh plumbing -------------------------------------------------------
    def _comm_mesh(self, comm: Communicator):
        """Mesh over the communicator members' devices (global rank ->
        process -> that process's device), cached per membership."""
        sessions = tuple(r.session for r in comm.ranks)
        if sessions in self._meshes:
            return self._meshes[sessions]
        from jax.sharding import Mesh

        mesh = Mesh(
            [self._device_of(s) for s in sessions], (opdriver.AXIS,)
        )
        self._meshes[sessions] = mesh
        return mesh

    # -- call entry ----------------------------------------------------------
    def start(self, options: CallOptions) -> Request:
        req = Request(op_name=options.op.name)
        if options.stream & StreamFlags.OP0_STREAM:
            # ANY streaming-operand op must not occupy the serialized
            # executor while waiting for the local kernel push (which may
            # come from the submitting thread after run_async — head-of-
            # line blocking would wedge the rank).  It runs on its own
            # thread; the caller must keep the cross-process op ORDER
            # consistent, the contract MPI nonblocking collectives impose.
            import threading

            threading.Thread(
                target=self._execute, args=(options, req),
                name="accl-dist-op", daemon=True,
            ).start()
        else:
            # overlap backpressure: an async caller more than
            # `inflight_window` calls ahead of the executor waits here —
            # BOUNDED by the engine timeout so a wedged executor can
            # never also wedge the submitting thread (facade deadlines
            # must still fire, the design note on the executor above)
            self._queue.wait_depth_below(
                self.inflight_window, timeout=self.timeout_s
            )
            try:
                self._queue.push((options, req))
            except RuntimeError:  # engine shut down
                req.mark_executing()
                req.complete(ErrorCode.INVALID_OPERATION)
        return req

    @spmd_uniform
    def start_batch(self, items) -> None:
        """A flushed facade batch becomes ONE queue item, so the executor
        sees the identical batch boundary in every member process (the
        SPMD contract extended to batches).  Unlike the single-process
        gang — which sees EVERY rank's buffers centrally and can make one
        fusion decision for the whole slot — this tier cannot decide
        fusion SPMD-consistently: the decision would hinge on process-
        LOCAL buffer aliasing (e.g. a non-root rank legitimately passes a
        DummyBuffer where the root passes a real one), and divergent
        fused-vs-sequential choices desynchronize the processes' program
        streams and wedge the mesh.  So a dist batch executes its items
        strictly in order; the win here is the facade-side contract
        (deferred dispatch + one flush point), not program fusion."""
        try:
            self._queue.push((
                [o for o, _ in items], [r for _, r in items]
            ))
        except RuntimeError:  # engine shut down
            for _, req in items:
                req.mark_executing()
                req.complete(ErrorCode.INVALID_OPERATION)

    def device_interactions(self) -> int:
        return self.interactions.read()

    # -- contract plane (accl_tpu.contract) ----------------------------------
    # One process per rank: there is no shared in-process board to meet
    # on (contract_anchor() stays the BaseEngine default, None), so
    # this tier verifies via the facade intake screen plus the executor
    # screen in _execute — AND the rolling-digest piggyback on the
    # distributed KV plane below (the PR 7 deferral, landed): after
    # each executed collective the verifier's latest completed window
    # digest is posted under accl/vfy/<comm>/<gen>/<window>/<rank> and
    # peers' posted digests are compared via observe_claim, so
    # cross-host divergence fails fast exactly like in-process.

    def _kv_contract_exchange(self, comm) -> None:
        """Post/compare the verifier's rolling digest over the KV plane
        (executor thread; bounded — try-get, never blocking-get).
        Failures are counted, never raised: an unreachable KV degrades
        verification to the intake screen, not the collective."""
        v = self.contract_verifier
        if v is None or comm is None:
            return
        from ...contract import kv_digest_exchange

        state = self._vfy_kv_state.setdefault(comm.id, {})
        try:
            kv = self._kv()
        except Exception:
            self._vfy_kv_counters["errors"] += 1
            return
        out = kv_digest_exchange(
            kv, v, comm.id, comm.local_rank, comm.size,
            state=state, is_notfound=self._is_notfound,
        )
        for k, n in out.items():
            self._vfy_kv_counters[k] = self._vfy_kv_counters.get(k, 0) + n

    def telemetry_report(self) -> dict:
        """Dist-tier counters for the telemetry snapshot: executor queue
        backlog, remote stream-port sequence positions, cached meshes."""
        with self._stream_seq_lock:
            stream_seq = dict(self._stream_seq)
        return {
            "device_interactions": self.interactions.read(),
            "executor_queue_depth": len(self._queue),
            "inflight_window": self.inflight_window,
            "remote_stream_seq": stream_seq,
            "cached_meshes": len(self._meshes),
            "faults": None,
            # contract plane: the KV digest-piggyback exchange counters
            # (windows posted / peer claims compared / KV errors)
            "contract_kv": dict(self._vfy_kv_counters),
            # monitor plane: per-rank baselines only — the cross-
            # process skew exchange rides ROADMAP item 2's topology
            # work
            "skew_exchange": "local",
        }

    def drain_inflight(self, timeout=None) -> bool:
        """Overlap drain point: a NOP barrier through the serialized
        executor — when it completes, every call queued before it has
        executed (the SPMD program stream is empty)."""
        from ...overlap import drain_deadline_s

        req = Request(op_name="NOP")
        try:
            self._queue.push((CallOptions(op=Operation.NOP), req))
        except RuntimeError:  # engine shut down: nothing left to drain
            return True
        # the shared drain policy: queued calls get their own engine
        # deadlines first — a tighter bound here would make flush()
        # spuriously report deadlock over a healthy backlog
        return req.wait(
            timeout if timeout is not None
            else drain_deadline_s(self.timeout_s)
        )

    def _run(self) -> None:
        while not self._shut:
            item = self._queue.pop(timeout=0.5)
            if item is None:
                continue  # timeout/spurious wake; re-check shutdown
            if isinstance(item[0], list):
                self._execute_batch(*item)
            else:
                self._execute(*item)
        # drain: abandoned queued requests complete with an error instead
        # of leaving waiters blocked forever
        while True:
            item = self._queue.pop(timeout=0)
            if item is None:
                return
            reqs = item[1] if isinstance(item[1], list) else [item[1]]
            for req in reqs:
                req.mark_executing()
                req.complete(ErrorCode.INVALID_OPERATION)

    def _execute(self, options: CallOptions, req: Request) -> None:
        req.mark_executing()
        cv = self.contract_verifier
        if (
            cv is not None and cv.has_verdict and options.comm is not None
        ):
            verdict = cv.check(options.comm.id)
            if verdict is not None:
                # contract plane: the verifier proved this process's call
                # sequence diverged from its peers — calls already queued
                # behind the detection point fail fast instead of wedging
                # the serialized executor on a cross-process program that
                # can never assemble
                from ...contract import verdict_context

                req.complete(
                    ErrorCode.CONTRACT_VIOLATION, 0,
                    context=verdict_context(verdict, options.op.name),
                )
                return
        t0 = time.perf_counter_ns()
        try:
            code = self._dispatch(options, req)
        except Exception:
            traceback.print_exc()
            code = ErrorCode.INVALID_OPERATION
        req.complete(code, time.perf_counter_ns() - t0)
        if (
            cv is not None and code == ErrorCode.OK
            and options.comm is not None
            and options.op in _KV_VERIFIED_OPS
        ):
            # digest piggyback on the KV plane: post/compare the latest
            # completed verification window (cheap cursor check when
            # nothing new completed)
            self._kv_contract_exchange(options.comm)

    # -- batched execution ---------------------------------------------------
    def _execute_batch(self, options_list, reqs) -> None:
        """Execute one flushed batch strictly in order (see start_batch:
        cross-process fusion decisions cannot be made SPMD-uniformly on
        this tier, so the batch boundary is preserved but items run
        through the ordinary per-call path)."""
        for options, req in zip(options_list, reqs):
            self._execute(options, req)

    def _dispatch(self, options: CallOptions,
                  req: Optional[Request] = None) -> ErrorCode:
        op = options.op
        if op == Operation.CONFIG:
            return self._apply_config(options)
        if op == Operation.NOP:
            return ErrorCode.OK
        if op in (Operation.COPY, Operation.COMBINE):
            return self._local_op(options)
        if op == Operation.SEND:
            return self._send(options)
        if op == Operation.RECV:
            return self._recv(options)
        if op == Operation.BARRIER:
            # a REAL cross-process barrier: a tiny psum over the
            # communicator mesh — my output shard cannot materialize until
            # every member process has contributed, so blocking on it IS
            # the barrier
            mesh = self._comm_mesh(options.comm)
            shard = _dev_zeros((1, 8), np.float32, self.device)
            self.interactions.bump(2)  # the zeros shard + barrier psum
            out = opdriver.run_allreduce(
                self._assemble(options.comm, mesh, shard, 8), mesh
            )
            self._local_shard(out).block_until_ready()
            return ErrorCode.OK
        if op in IN_W:
            return self._collective(options, req)
        return ErrorCode.COLLECTIVE_NOT_IMPLEMENTED

    # -- collectives -----------------------------------------------------------
    def _assemble(self, comm: Communicator, mesh, local_shard, width: int):
        """Global (size, width) array from this process's shard; peers
        contribute theirs in their own processes."""
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.make_array_from_single_device_arrays(
            (comm.size, width),
            NamedSharding(mesh, PartitionSpec(opdriver.AXIS)),
            [local_shard],
        )

    def _local_shard(self, global_arr):
        (shard,) = [
            s for s in global_arr.addressable_shards
            if s.device == self.device
        ]
        return shard.data

    def _operand_shard(self, options: CallOptions, chunks: int, n: int,
                       nb: int):
        """This rank's (1, chunks*nb) committed wire shard from op0:
        ``chunks`` chunks of ``n`` elements, each padded to the ``nb``
        bucket (see :func:`_bucket_width`).  Small operands stage on the
        host (free numpy pad, no per-count program); big device-resident
        operands re-layout on device."""
        buf = options.op0
        npdt = dtype_to_numpy(options.arithcfg.uncompressed)
        compressed = bool(
            options.compression & CompressionFlags.ETH_COMPRESSED
        )
        wire_name = (
            np.dtype(dtype_to_numpy(options.arithcfg.compressed)).name
            if compressed and options.op != Operation.ALLREDUCE
            else None
        )
        in_w = chunks * n
        if options.stream & StreamFlags.OP0_STREAM:
            row = self._pop_stream_payload(options, in_w)
            if row is None:
                return None
            row = np.asarray(row).astype(npdt)[:in_w]
        elif buf is None or buf.is_dummy:
            self.interactions.bump()
            return _dev_zeros((1, chunks * nb), npdt, self.device)
        elif isinstance(buf, DeviceBuffer) and buf.device == self.device:
            # eager/rendezvous is decided per CHUNK — the wire message
            # unit, matching the reference's per-message eager rule (a
            # scatter of world eager-sized chunks is eager protocol).
            # A TuningPlan's per-size-bucket eager threshold overlays the
            # global register (every member process loads the same plan,
            # so the choice stays SPMD-uniform).
            if n * np.dtype(npdt).itemsize > options.eager_limit(
                self.max_eager_size
            ):
                # RENDEZVOUS domain: zero-host-copy (transfer-guard-
                # tested) — re-layout on device.  The pad program
                # retraces per exact count, but the expensive collective
                # program compiles per BUCKET only.
                self.interactions.bump()
                return _pad_chunks_program(
                    chunks, n, nb, wire_name, self.device
                )(buf.device_array())
            # EAGER domain: stage through the host, the reference's own
            # protocol for small payloads (eager sends land in rx bounce
            # buffers and are memcpy'd out — zero-copy is a rendezvous-
            # path property, ref rxbuf_offload).  Numpy pad/trim costs
            # microseconds and compiles NOTHING per count — the property
            # that lets a soak sweep arbitrary sizes at cached-dispatch
            # speed.
            self.interactions.bump()  # eager D2H read
            row = np.asarray(buf.device_view()[:in_w]).astype(npdt)
        else:
            if isinstance(buf, DeviceBuffer):
                self.interactions.bump()
            row = np.asarray(buf.device_view()[:in_w]).astype(npdt)
        # already host-side: chunk, wire-round, pad in numpy (free), one
        # committed put of the bucket-shaped row
        m = row.reshape(chunks, n)
        if wire_name is not None:
            # the shared host codec (scaled int8 lane + SR seeds
            # included), per chunk — mirrors the emulator's chunk lanes
            from ... import wire as wirecodec

            seed = wirecodec.options_rank_seed(options)
            m = np.stack([
                wirecodec.roundtrip(
                    c, options.arithcfg.compressed, seed
                ).astype(npdt)
                for c in m
            ])
        if nb != n:
            m = np.concatenate(
                [m, np.zeros((chunks, nb - n), npdt)], axis=1
            )
        self.interactions.bump()  # the committed put
        return jax.device_put(m.reshape(1, chunks * nb), self.device)

    def _collective(self, options: CallOptions,
                    req: Optional[Request] = None) -> ErrorCode:
        comm = options.comm
        op = options.op
        size = comm.size
        n = options.count
        if n <= 0:
            return ErrorCode.INVALID_COUNT
        nb = _bucket_width(n)
        in_chunks = size if IN_W[op] == "P" else 1
        out_chunks = size if OUT_W[op] == "P" else 1
        mesh = self._comm_mesh(comm)
        fn = options.reduce_function
        if op in (
            Operation.REDUCE, Operation.ALLREDUCE, Operation.REDUCE_SCATTER
        ) and not options.arithcfg.supports(fn):
            return ErrorCode.ARITH_ERROR
        shard = self._operand_shard(options, in_chunks, n, nb)
        if shard is None:
            return ErrorCode.DMA_TIMEOUT
        global_arr = self._assemble(comm, mesh, shard, in_chunks * nb)
        compressed = bool(
            options.compression & CompressionFlags.ETH_COMPRESSED
        )

        # per-size-bucket TuningPlan overlay (CallOptions.tuning) over the
        # global registers — identical in every member process when all
        # load the same plan, so the SPMD program streams stay uniform
        tuning = options.effective_tuning(self.tuning)

        self.interactions.bump()  # the collective program dispatch
        if op == Operation.ALLREDUCE:
            wire = options.arithcfg.compressed if compressed else None
            out = run_allreduce_with_tuning(
                global_arr, mesh, fn, wire, tuning
            )
        elif op in (Operation.REDUCE, Operation.BCAST, Operation.SCATTER,
                    Operation.GATHER):
            out = run_rooted_with_tuning(
                op, global_arr, mesh, options, tuning
            )
        elif op == Operation.ALLGATHER:
            out = opdriver.run_allgather(global_arr, mesh)
        elif op == Operation.REDUCE_SCATTER:
            out = opdriver.run_reduce_scatter(global_arr, mesh, fn)
        elif op == Operation.ALLTOALL:
            out = opdriver.run_alltoall(global_arr, mesh)
        else:  # pragma: no cover - guarded by IN_W
            return ErrorCode.COLLECTIVE_NOT_IMPLEMENTED

        return self._place_result(options, out, n, nb, out_chunks, req)

    def _place_result(self, options: CallOptions, out, n: int, nb: int,
                      out_chunks: int, req: Optional[Request]) -> ErrorCode:
        """Adopt this process's output shard into the result buffer.
        The rendezvous-domain unpad+cast (one FUSED device program, see
        ``_unpad_chunks_program``) is parked LAZILY on the buffer/request
        — materialized at wait()/first data access — so a fire-and-forget
        chain pays no result-side device interaction at dispatch time."""
        comm = options.comm
        op = options.op
        out_w = n * out_chunks
        # result placement: only ranks the op addresses read their shard
        writes = True
        if op == Operation.REDUCE:
            writes = comm.local_rank == options.root_dst
        elif op == Operation.GATHER:
            writes = comm.local_rank == options.root_src
        arr = self._local_shard(out)  # (1, out_chunks*nb) padded wire row
        if not writes:
            return ErrorCode.OK
        res = options.res
        if options.stream & StreamFlags.RES_STREAM:
            host = np.asarray(arr).reshape(out_chunks, nb)[:, :n]
            self._push_stream_result(options, host.reshape(-1))
            return ErrorCode.OK
        if res is None or res.is_dummy:
            return ErrorCode.OK
        if (
            isinstance(res, DeviceBuffer) and res.device == self.device
            and n * np.dtype(arr.dtype).itemsize
            > options.eager_limit(self.max_eager_size)
        ):
            # rendezvous domain: chunk-trim + decompress ON DEVICE
            # (zero-host-copy), one fused program, deferred to the reader
            npdt = dtype_to_numpy(res.dtype)

            def adopt(arr=arr, res=res, npdt=npdt, out_w=out_w,
                      out_chunks=out_chunks, n=n, nb=nb,
                      ic=self.interactions):
                trimmed = _unpad_chunks_program(
                    out_chunks, n, nb, self.device, npdt
                )(arr)
                ic.bump()
                if res.store(trimmed, out_w):
                    ic.bump()

            res.defer_store(adopt)
            if req is not None:
                req.defer_result(res.resolve_pending, handle=arr)
        elif isinstance(res, DeviceBuffer) and res.device == self.device:
            # eager domain: host trim, one committed put (see
            # _operand_shard's eager note)
            host = np.asarray(arr).reshape(out_chunks, nb)[:, :n]
            npdt = dtype_to_numpy(res.dtype)
            self.interactions.bump()  # D2H read + H2D put of a tiny row
            if res.store(
                jax.device_put(
                    host.reshape(-1).astype(npdt), self.device
                ),
                out_w,
            ):
                self.interactions.bump()
        else:
            host = np.asarray(arr).reshape(out_chunks, nb)[:, :n]
            _write_host_result(
                res, host.reshape(-1), out_w, self.interactions
            )
        return ErrorCode.OK

    # -- p2p -------------------------------------------------------------------
    def _p2p_devices(self, options: CallOptions, remote_is_dst: bool):
        comm = options.comm
        peer = options.root_dst if remote_is_dst else options.root_src
        return self._device_of(comm.ranks[peer].session)

    def _send(self, options: CallOptions) -> ErrorCode:
        if options.stream & StreamFlags.RES_STREAM:
            return self._remote_stream_put(options)
        n = options.count
        nb = _bucket_width(n)
        shard = self._operand_shard(options, 1, n, nb)
        if shard is None:
            return ErrorCode.DMA_TIMEOUT
        if options.compression & CompressionFlags.ETH_COMPRESSED:
            # compress lane on the sending chip: the wire carries the
            # narrow dtype (the receiver's zeros shard matches it)
            self.interactions.bump()
            shard = _cast_program(
                dtype_to_numpy(options.arithcfg.compressed), self.device
            )(shard)
        dst_dev = self._p2p_devices(options, remote_is_dst=True)
        if dst_dev == self.device:
            return ErrorCode.INVALID_RANK  # self-send needs no processes
        return self._p2p_run(shard, self.device, dst_dev, n, nb)

    def _recv(self, options: CallOptions) -> ErrorCode:
        n = options.count
        nb = _bucket_width(n)
        npdt = dtype_to_numpy(
            options.arithcfg.compressed
            if options.compression & CompressionFlags.ETH_COMPRESSED
            else options.arithcfg.uncompressed
        )
        src_dev = self._p2p_devices(options, remote_is_dst=False)
        if src_dev == self.device:
            return ErrorCode.INVALID_RANK
        self.interactions.bump()
        shard = _dev_zeros((1, nb), npdt, self.device)
        code = self._p2p_run(
            shard, src_dev, self.device, n, nb, recv_into=options
        )
        return code

    def _p2p_run(self, local_shard, src_dev, dst_dev, n, nb,
                 recv_into: Optional[CallOptions] = None) -> ErrorCode:
        """Both owning processes execute the same 2-device ppermute
        program over the (2, nb) BUCKETED wire row (so the hop program
        compiles per bucket, not per exact count); the receiver adopts
        its shard and trims the pad."""
        from jax.sharding import NamedSharding, PartitionSpec

        mesh, prog = _p2p_hop_program(src_dev, dst_dev)
        global_in = jax.make_array_from_single_device_arrays(
            (2, nb),
            NamedSharding(mesh, PartitionSpec("p2p")),
            [local_shard],
        )
        self.interactions.bump()  # the hop program
        out = prog(global_in)
        arr = self._local_shard(out)
        if recv_into is None:
            return ErrorCode.OK
        options = recv_into
        if options.stream & StreamFlags.RES_STREAM:
            self._push_stream_result(
                options, np.asarray(arr).reshape(-1)[:n]
            )
            return ErrorCode.OK
        res = options.res
        if res is None or res.is_dummy:
            return ErrorCode.OK
        if (
            isinstance(res, DeviceBuffer) and res.device == self.device
            and n * np.dtype(arr.dtype).itemsize
            > options.eager_limit(self.max_eager_size)
        ):
            # fused unpad + decompress: ONE result-side program
            npdt = dtype_to_numpy(res.dtype)
            self.interactions.bump()
            arr = _unpad_chunks_program(1, n, nb, self.device, npdt)(arr)
            if res.store(arr, n):
                self.interactions.bump()
        elif isinstance(res, DeviceBuffer) and res.device == self.device:
            npdt = dtype_to_numpy(res.dtype)
            host = np.asarray(arr).reshape(-1)[:n].astype(npdt)
            self.interactions.bump()
            if res.store(jax.device_put(host, self.device), n):
                self.interactions.bump()
        else:
            _write_host_result(
                res, np.asarray(arr).reshape(-1)[:n], n, self.interactions
            )
        return ErrorCode.OK

    # -- remote stream ports over the distributed KV service -------------------
    # stream_put to another process's port is ONE-SIDED in the reference
    # (data lands on the remote CCLO's ext-kernel stream with no receiver
    # call, tag<247 routing accl.cpp:181-183).  SPMD device programs can't
    # express that (the receiver would have to run a matched program), so
    # the dist tier rides the distributed runtime's key-value service —
    # the same control plane that bootstrapped the gang: the sender
    # atomically takes the destination port's next sequence number and
    # posts the wire bytes under it; the receiver's stream_pop drains in
    # sequence order.  A control-plane hop sized for kernel handoffs (the
    # reference's stream port is a FIFO of 512-bit words, not a bulk
    # path); bulk data belongs to the collectives.

    @staticmethod
    def _stream_key(dst: int, sid: int, seq: int) -> str:
        return f"accl/strm/{dst}/{sid}/{seq}"

    @staticmethod
    def _stream_ctr(dst: int, sid: int) -> str:
        return f"accl/strmctr/{dst}/{sid}"

    def _kv(self):
        from jax._src import distributed

        client = distributed.global_state.client
        if client is None:  # pragma: no cover - initialize() guarantees it
            raise RuntimeError("distributed KV service unavailable")
        # modern KV surface over whatever jaxlib provides: legacy clients
        # (no try-get/increment) are wrapped once by the compat adapter
        if self._kv_raw is not client:
            from ...compat import kv_client

            self._kv_raw = client
            self._kv_wrapped = kv_client(client)
        return self._kv_wrapped

    def arbiter_kv(self):
        """The KV plane handed to the QoS arbiter's cross-process tenant
        ledger (same adapter the contract-digest ledger rides); raises
        when the distributed KV service is unavailable."""
        return self._kv()

    def _remote_stream_put(self, options: CallOptions) -> ErrorCode:
        n = options.count
        cfg = options.arithcfg
        if options.stream & StreamFlags.OP0_STREAM:
            payload = self._pop_stream_payload(options, n)
            if payload is None:
                return ErrorCode.DMA_TIMEOUT
            data = np.asarray(payload)
        else:
            buf = options.op0
            if buf is None or buf.is_dummy:
                return ErrorCode.INVALID_OPERATION
            data = np.asarray(buf.device_view()[:n])
        data = data.astype(dtype_to_numpy(cfg.uncompressed))
        if options.compression & CompressionFlags.ETH_COMPRESSED:
            # wire carries the narrow dtype, same as the gang tier
            data = data.astype(dtype_to_numpy(cfg.compressed))
        dst_proc = options.comm.ranks[options.root_dst].session
        if dst_proc == self.process_id:
            self.stream_push(options.stream_id, data.tobytes())
            return ErrorCode.OK
        try:
            kv = self._kv()
            seq = kv.key_value_increment(
                self._stream_ctr(dst_proc, options.stream_id), 1
            )
            kv.key_value_set_bytes(
                self._stream_key(dst_proc, options.stream_id, seq),
                data.tobytes(),
            )
        except Exception:
            traceback.print_exc()
            return ErrorCode.TRANSPORT_ERROR
        return ErrorCode.OK

    def _is_notfound(self, e: Exception) -> bool:
        """Is this try-get exception 'key absent' (normal while polling)
        rather than a real KV/transport failure?

        jaxlib renders XlaRuntimeError as a flat string, so the only
        portable discrimination is the message — but a hardcoded
        "NOT_FOUND" substring breaks silently if a jaxlib upgrade changes
        the rendering (every empty poll would then raise out of the
        polling loop).  So the signature is LEARNED once per engine: ask
        the KV for a key that cannot exist and record (type, message
        fragments around the key); a later exception matches if it is the
        same type and carries the same fragments.  The substring check
        stays as a belt-and-braces fallback for KV services that render
        differently between the probe and real keys."""
        if not self._nf_probed:
            probe_key = (
                f"accl/__nf_probe__/{self.process_id}/{id(self)}"
            )
            try:
                self._kv().key_value_try_get_bytes(probe_key)
                # this KV returns (not raises) on missing keys: nothing
                # to learn, and nothing the fallback can add
                self._nf_sig = None
                self._nf_probed = True
            except Exception as probe_e:
                msg = str(probe_e)
                parts = tuple(p for p in msg.split(probe_key) if p)
                # only trust a signature that can actually DISCRIMINATE:
                # it must name the key and carry non-trivial text around
                # it — a bare-key rendering ("'<key>'") would make every
                # same-typed exception match vacuously
                trivial = (
                    sum(len(p.strip("'\"` :.,()[]{}")) for p in parts) < 4
                )
                # ...and it must READ like not-found: a transport error
                # raised while fetching the probe key also names the key
                # ("UNAVAILABLE: failed to fetch <key>: connection
                # refused"), and learning THAT shape would silently fold
                # every later persistent KV failure into 'nothing
                # posted'.  Every known coordination-service rendering
                # of key-absent carries one of these words; a probe
                # without any is treated as a transport blip.
                looks_notfound = any(
                    mk in msg.lower()
                    for mk in (
                        "not_found", "not found", "notfound", "no such",
                        "missing", "does not exist", "absent",
                    )
                )
                if probe_key in msg and not trivial and looks_notfound:
                    self._nf_sig = (type(probe_e), parts)
                    self._nf_probed = True
                elif probe_key in msg and trivial:
                    # rendering is bare-key: cannot discriminate, and
                    # re-probing would never improve — substring
                    # fallback only
                    self._nf_sig = None
                    self._nf_probed = True
                else:
                    # the KV itself was unreachable or errored (init
                    # blip): re-arm so a later healthy poll can still
                    # learn, but cap the retries — each one is an extra
                    # KV roundtrip on the ~20 Hz polling path
                    self._nf_sig = None
                    self._nf_probe_tries += 1
                    self._nf_probed = self._nf_probe_tries >= 8
        if self._nf_sig is not None:
            typ, parts = self._nf_sig
            msg = str(e)
            if isinstance(e, typ) and all(p in msg for p in parts):
                return True
        return "NOT_FOUND" in str(e)

    def _drain_remote_stream(self, stream_id: int) -> bool:
        """Pull this port's next remotely-posted chunk (if any) into the
        local port; returns True when one landed.  The sequence counter
        is advanced under its lock so concurrent poppers of one port
        cannot both fetch (and double-deliver) the same chunk."""
        with self._stream_seq_lock:
            nxt = self._stream_seq.get(stream_id, 0) + 1
            key = self._stream_key(self.process_id, stream_id, nxt)
            try:
                data = self._kv().key_value_try_get_bytes(key)
            except Exception as e:
                if self._is_notfound(e):
                    return False  # nothing posted yet
                # a persistent KV/transport failure must not be silently
                # folded into "nothing posted" — the caller would only
                # see a generic stream TimeoutError with no cause
                traceback.print_exc()
                raise
            self._stream_seq[stream_id] = nxt
            # delete before releasing the seq lock: a crash between get
            # and delete cannot leak the KV entry to a concurrent popper
            try:
                self._kv().key_value_delete(key)
            except Exception:  # pragma: no cover - cleanup only
                pass
        self.stream_push(stream_id, data)
        return True

    def stream_pop(self, stream_id: int, timeout: Optional[float] = None) -> bytes:
        """Local port first (condition-variable fast path, woken
        immediately by a local push); while empty, poll the KV service
        non-blockingly for chunks another process stream_put into this
        port (sequence order, ~20 probes/s)."""
        budget = self.timeout_s if timeout is None else timeout
        deadline = time.monotonic() + budget
        while True:
            with self._stream_cv:
                q = self._streams.get(stream_id)
                if not q:
                    # a local push lands here instantly; the short wait
                    # only bounds the remote-probe cadence
                    self._stream_cv.wait(0.05)
                    q = self._streams.get(stream_id)
                if q:
                    return q.pop(0)
            if self._drain_remote_stream(stream_id):
                continue
            if time.monotonic() > deadline:
                raise TimeoutError(f"stream {stream_id} empty")

    # -- local ops / streams ---------------------------------------------------
    def _local_op(self, options: CallOptions) -> ErrorCode:
        n = options.count
        if options.stream & StreamFlags.OP0_STREAM:
            payload = self._pop_stream_payload(options, n)
            if payload is None:
                return ErrorCode.DMA_TIMEOUT
            acc = payload.astype(
                dtype_to_numpy(options.arithcfg.uncompressed)
            )
        else:
            acc = np.asarray(options.op0.device_view()[:n])
        if options.op == Operation.COMBINE:
            other = np.asarray(options.op1.device_view()[:n])
            if options.reduce_function == ReduceFunction.SUM:
                acc = acc + other
            elif options.reduce_function == ReduceFunction.MAX:
                acc = np.maximum(acc, other)
            else:
                return ErrorCode.ARITH_ERROR
        if options.stream & StreamFlags.RES_STREAM:
            self._push_stream_result(options, acc)
            return ErrorCode.OK
        _write_host_result(options.res, acc, n)
        return ErrorCode.OK

    # -- config ----------------------------------------------------------------
    def _apply_config(self, options: CallOptions) -> ErrorCode:
        fn = ConfigFunction(options.cfg_function)
        val = options.cfg_value
        if fn == ConfigFunction.RESET:
            pass
        elif fn == ConfigFunction.ENABLE_TRANSPORT:
            pass
        elif fn == ConfigFunction.SET_TIMEOUT:
            if val <= 0:
                return ErrorCode.CONFIG_ERROR
            self.timeout_s = float(val)
        elif fn == ConfigFunction.SET_MAX_EAGER_SIZE:
            if not 0 < val <= MAX_EAGER_SIZE_LIMIT:
                return ErrorCode.CONFIG_ERROR
            self.max_eager_size = int(val)
        elif fn == ConfigFunction.SET_MAX_RENDEZVOUS_SIZE:
            if val <= 0:
                return ErrorCode.CONFIG_ERROR
            self.max_rendezvous_size = int(val)
        elif fn == ConfigFunction.SET_RETRY_LIMIT:
            # SPMD fabric: no host retransmit exists, but the knob is
            # accepted so set_retry_policy stays portable across tiers
            if val < 0:
                return ErrorCode.CONFIG_ERROR
            self.retry_limit = int(val)
        elif fn == ConfigFunction.SET_RETRY_BACKOFF:
            if val <= 0:
                return ErrorCode.CONFIG_ERROR
            self.retry_backoff_s = float(val)
        elif fn == ConfigFunction.SET_INFLIGHT_WINDOW:
            from ...constants import MAX_INFLIGHT_WINDOW

            if not 1 <= val <= MAX_INFLIGHT_WINDOW:
                return ErrorCode.CONFIG_ERROR
            # the config itself rode the queue, so everything launched
            # under the old bound has already executed (ordered drain)
            self.inflight_window = int(val)
        elif fn in (
            ConfigFunction.SET_TENANT_CLASS,
            ConfigFunction.SET_TENANT_WEIGHT,
            ConfigFunction.SET_TENANT_WINDOW_SHARE,
            ConfigFunction.SET_TENANT_RING_SLOTS,
            ConfigFunction.SET_TENANT_RATE,
        ):
            # QoS arbiter plane: this tier serializes everything through
            # one executor — enforcement lives in the per-process facade
            # arbiter; the ONE shared validator keeps the write
            # portable across tiers
            from ...arbiter import tenant_config_field, tenant_config_valid

            if not tenant_config_valid(fn, val):
                return ErrorCode.CONFIG_ERROR
            self.tenants.setdefault(
                int(options.cfg_key), {}
            )[tenant_config_field(fn)] = val
        elif fn == ConfigFunction.SET_TUNING:
            return self._apply_tuning(options)
        else:
            return ErrorCode.CONFIG_ERROR
        return ErrorCode.OK

    def _apply_tuning(self, options: CallOptions) -> ErrorCode:
        return apply_tuning(self.tuning, options)

    def shutdown(self) -> None:
        # close FIRST so a racing start() either lands before (drained) or
        # gets the closed-queue error — never a forever-queued request
        self._queue.close()
        self._shut = True
        # executor exits at its next 0.5s poll and drains the queue; a
        # wedged in-flight program (mismatched cross-process call) cannot
        # be interrupted — the daemon thread dies with the process, the
        # reference's wedged-CCLO failure mode
        self._executor.join(timeout=2.0)


def dist_group_member(
    rank: int,
    world: int,
    coordinator: str = "127.0.0.1:47600",
    **accl_kwargs,
):
    """Initialize this process as rank ``rank`` of a ``world``-process
    distributed group and return its ACCL handle (the mpirun-per-rank
    bring-up of ref fixture.hpp:124-132 over jax.distributed).

    On CPU hosts the cross-process collectives ride gloo (the test tier);
    on TPU pods jax.distributed wires ICI/DCN natively.
    """
    import os

    # honor an explicit platform request via config as well as env: some
    # site PJRT hooks only respect the config path, and probing the
    # backend here would initialize it before jax.distributed
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        jax.config.update("jax_platforms", platforms)
    if "tpu" not in os.environ.get("JAX_PLATFORMS", "").lower():
        try:
            # CPU backend needs an explicit cross-process collectives impl
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # pragma: no cover - older jax without the option
            pass
    jax.distributed.initialize(
        coordinator, num_processes=world, process_id=rank
    )
    from ...core import ACCL

    ranks = [Rank(address=f"dist:{i}", session=i) for i in range(world)]
    return ACCL(DistEngine(), ranks, rank, **accl_kwargs)
