"""Multi-process device tier: one OS process per rank over jax.distributed.

The reference's device deployment is mpirun-per-rank host processes, each
driving its own FPGA over the shared fabric
(``test/host/xrt/include/fixture.hpp:124-132``,
``accl_network_utils.cpp``).  This backend is the TPU analog: each process
owns its chip(s) through a multi-controller ``jax.distributed`` runtime,
and every collective executes as the same jitted shard_map program in all
participating processes — ICI/DCN (or gloo on the CPU test tier) carries
the data, with no single-controller gang in the way.
"""

from .engine import DistEngine, dist_group_member  # noqa: F401
