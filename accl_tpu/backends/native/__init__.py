"""Native backend: the C++ collective engine driven via ctypes.

The reference implements its control plane and host runtime in native code
(C firmware ``ccl_offload_control.c`` + C++ driver ``driver/xrt``); this
backend is our equivalent — the full eager/rendezvous protocol engine and
every collective algorithm live in C++ (``native/src/engine/``), built into
``libaccl_engine.so``.  Python supplies only the facade: `NativeEngine`
adapts `CallOptions` records onto the C ABI, exactly as the reference's thin
``hostctrl`` kernel forwards 15 scalar args to the CCLO.

Two transports, mirroring the emulator backend's tiers:

* INPROC — all rank engines in one process (CI tier)
* SOCKET — one process per rank over TCP (the per-rank-process tier)
"""

from .engine import (  # noqa: F401
    NativeEngine,
    engine_library_available,
    native_group,
    native_socket_member,
)
