"""ctypes binding for the native C++ collective engine (libaccl_engine.so).

Role split (mirrors the reference): Python is the host driver facade; the
C++ library owns scheduling, protocol state machines (eager segmentation with
per-peer sequence numbers, rendezvous address handshake), RX buffer matching,
reductions/casts, and both transports.  See ``native/src/engine/`` for the
firmware-role citations.
"""

from __future__ import annotations

import ctypes
import itertools
import threading
from typing import List, Optional, Sequence

from ...buffer import BaseBuffer
from ...communicator import Communicator, Rank
from ...constants import (
    DEFAULT_RX_BUFFER_COUNT,
    DEFAULT_RX_BUFFER_SIZE,
    ErrorCode,
)
from ...request import Request
from ..base import BaseEngine, CallOptions
from ... import native as _native_dataplane

_group_ids = itertools.count(0)

_LIB = None
_LOAD_ATTEMPTED = False


class _CallArgs(ctypes.Structure):
    """Field-for-field mirror of accl::CallArgs (native/src/engine/accl_engine.h)."""

    _fields_ = [
        ("op", ctypes.c_int32),
        ("comm_id", ctypes.c_uint32),
        ("count", ctypes.c_int64),
        ("root_src", ctypes.c_int32),
        ("root_dst", ctypes.c_int32),
        ("tag", ctypes.c_uint32),
        ("rfunc", ctypes.c_int32),
        ("acc_dtype", ctypes.c_int32),
        ("cmp_dtype", ctypes.c_int32),
        ("supports_rfunc", ctypes.c_int32),
        ("compression", ctypes.c_uint32),
        ("stream_flags", ctypes.c_uint32),
        ("stream_id", ctypes.c_int32),
        ("cfg_function", ctypes.c_int32),
        ("cfg_value", ctypes.c_double),
        ("op0", ctypes.c_void_p),
        ("op1", ctypes.c_void_p),
        ("res", ctypes.c_void_p),
        ("op0_dtype", ctypes.c_int32),
        ("op1_dtype", ctypes.c_int32),
        ("res_dtype", ctypes.c_int32),
        ("cfg_key", ctypes.c_int32),
    ]


def _bind(lib) -> None:
    c = ctypes
    lib.accl_ng_engine_new.restype = c.c_int
    lib.accl_ng_engine_new.argtypes = [c.c_char_p, c.c_int, c.c_int, c.c_int]
    lib.accl_ng_engine_shutdown.restype = None
    lib.accl_ng_engine_shutdown.argtypes = [c.c_int]
    lib.accl_ng_add_comm.restype = c.c_int
    lib.accl_ng_add_comm.argtypes = [
        c.c_int, c.c_uint32, c.c_int, c.c_int,
        c.POINTER(c.c_char_p), c.POINTER(c.c_uint32),
    ]
    lib.accl_ng_start.restype = c.c_uint64
    lib.accl_ng_start.argtypes = [c.c_int, c.POINTER(_CallArgs)]
    lib.accl_ng_wait.restype = c.c_int
    lib.accl_ng_wait.argtypes = [c.c_int, c.c_uint64, c.c_double]
    lib.accl_ng_test.restype = c.c_int
    lib.accl_ng_test.argtypes = [c.c_int, c.c_uint64]
    lib.accl_ng_retcode.restype = c.c_uint32
    lib.accl_ng_retcode.argtypes = [c.c_int, c.c_uint64]
    lib.accl_ng_duration_ns.restype = c.c_int64
    lib.accl_ng_duration_ns.argtypes = [c.c_int, c.c_uint64]
    lib.accl_ng_free_request.restype = None
    lib.accl_ng_free_request.argtypes = [c.c_int, c.c_uint64]
    lib.accl_ng_stream_push.restype = None
    lib.accl_ng_stream_push.argtypes = [c.c_int, c.c_int, c.c_void_p, c.c_int64]
    lib.accl_ng_stream_pop.restype = c.c_int64
    lib.accl_ng_stream_pop.argtypes = [
        c.c_int, c.c_int, c.c_void_p, c.c_int64, c.c_double,
    ]
    lib.accl_ng_rx_occupancy.restype = c.c_int
    lib.accl_ng_rx_occupancy.argtypes = [c.c_int]
    lib.accl_ng_rx_capacity.restype = c.c_int
    lib.accl_ng_rx_capacity.argtypes = [c.c_int]


def _load():
    global _LIB, _LOAD_ATTEMPTED
    if _LOAD_ATTEMPTED:
        return _LIB
    _LOAD_ATTEMPTED = True
    so = _native_dataplane._NATIVE_DIR / "build" / "libaccl_engine.so"
    if not so.exists():
        _native_dataplane._try_build()
    if not so.exists():
        return None
    try:
        lib = ctypes.CDLL(str(so))
        _bind(lib)
    except (OSError, AttributeError):
        return None
    _LIB = lib
    return _LIB


def engine_library_available() -> bool:
    return _load() is not None


class NativeRequest(Request):
    """Request completed inside the C++ engine; wait/test bridge the C ABI."""

    def __init__(self, engine: "NativeEngine", native_id: int, op_name: str,
                 keepalive):
        super().__init__(op_name=op_name)
        self._engine = engine
        self._native_id = native_id
        self._keepalive = keepalive  # numpy views the engine writes into
        self._fin_lock = threading.Lock()

    def _finalize(self) -> None:
        with self._fin_lock:
            if self._done.is_set():
                return
            lib, h = self._engine._lib, self._engine._handle
            ret = ErrorCode(lib.accl_ng_retcode(h, self._native_id))
            dur = lib.accl_ng_duration_ns(h, self._native_id)
            lib.accl_ng_free_request(h, self._native_id)
            self._keepalive = None
            self.complete(ret, dur)

    def test(self) -> bool:
        if self._done.is_set():
            return True
        if self._engine._lib.accl_ng_test(
            self._engine._handle, self._native_id
        ):
            self._finalize()
            return True
        return False

    def wait(self, timeout: Optional[float] = None) -> bool:
        if self._done.is_set():
            return True
        t = -1.0 if timeout is None else float(timeout)
        if self._engine._lib.accl_ng_wait(
            self._engine._handle, self._native_id, t
        ):
            self._finalize()
            return True
        return False


class NativeEngine(BaseEngine):
    """One rank's handle onto the C++ engine."""

    TRANSPORT_INPROC = 0
    TRANSPORT_SOCKET = 1

    def __init__(
        self,
        address: str,
        transport: int = TRANSPORT_INPROC,
        rx_buffer_count: int = DEFAULT_RX_BUFFER_COUNT,
        rx_buffer_size: int = DEFAULT_RX_BUFFER_SIZE,
    ):
        lib = _load()
        if lib is None:
            raise RuntimeError(
                "libaccl_engine.so unavailable (native toolchain missing?)"
            )
        self._lib = lib
        self.address = address
        self._handle = lib.accl_ng_engine_new(
            address.encode(), transport, rx_buffer_count, rx_buffer_size
        )
        if self._handle < 0:
            raise RuntimeError(f"native engine failed to open {address!r}")
        self._registered_comms: set = set()
        self._shut = False
        from ...overlap import default_window_depth

        self.inflight_window = default_window_depth()
        # QoS arbiter plane: host-side mirror of SET_TENANT_* writes
        # (the C ABI predates the tenant vocabulary)
        self.tenants: dict = {}
        # host-side mirror of the C engine's register table, seeded from
        # the shared defaults: every SET_TUNING write that rides the ABI
        # is mirrored here (write-through), registers the ABI predates
        # (pipeline_threshold) live here outright — the facade's
        # _engine_tuning and register-visibility tests read this dict
        from ...constants import TUNING_DEFAULTS

        self.tuning: dict = dict(TUNING_DEFAULTS)

    # -- plumbing ------------------------------------------------------------
    def _ensure_comm(self, comm: Communicator) -> None:
        if comm.id in self._registered_comms:
            return
        n = comm.size
        addrs = (ctypes.c_char_p * n)(
            *[r.address.encode() for r in comm.ranks]
        )
        segs = (ctypes.c_uint32 * n)(
            *[r.max_segment_size for r in comm.ranks]
        )
        rc = self._lib.accl_ng_add_comm(
            self._handle, comm.id, comm.local_rank, n, addrs, segs
        )
        if rc != 0:
            raise RuntimeError("add_comm failed")
        self._registered_comms.add(comm.id)

    @staticmethod
    def _operand(buf: Optional[BaseBuffer]):
        """(pointer, dtype code, keepalive view) for one operand."""
        if buf is None or buf.is_dummy:
            return 0, 0, None
        view = buf.device_view()
        return view.ctypes.data, int(buf.dtype), view

    def start(self, options: CallOptions) -> Request:
        from ...constants import (
            ConfigFunction,
            MAX_INFLIGHT_WINDOW,
            Operation,
            TuningKey,
        )

        if (
            options.op == Operation.CONFIG
            and int(options.cfg_function)
            == int(ConfigFunction.SET_INFLIGHT_WINDOW)
        ):
            # overlap-plane parity knob, handled host-side: the C engine
            # predates the window vocabulary and its scheduler already
            # completes requests asynchronously (no launch-path blocking
            # to decouple) — accept + store so set_inflight_window is
            # portable across all four tiers
            req = Request(op_name=options.op.name)
            req.mark_executing()
            if 1 <= options.cfg_value <= MAX_INFLIGHT_WINDOW:
                self.inflight_window = int(options.cfg_value)
                req.complete(ErrorCode.OK)
            else:
                req.complete(ErrorCode.CONFIG_ERROR)
            return req
        if options.op == Operation.CONFIG and int(
            options.cfg_function
        ) in (
            int(ConfigFunction.SET_TENANT_CLASS),
            int(ConfigFunction.SET_TENANT_WEIGHT),
            int(ConfigFunction.SET_TENANT_WINDOW_SHARE),
            int(ConfigFunction.SET_TENANT_RING_SLOTS),
            int(ConfigFunction.SET_TENANT_RATE),
        ):
            # QoS arbiter plane, handled host-side: the C ABI predates
            # the tenant vocabulary and enforcement lives in the facade
            # arbiter anyway — accept + mirror, through the ONE shared
            # validator, so set_tenant_class/quota stay portable across
            # all four tiers
            from ...arbiter import tenant_config_field, tenant_config_valid

            fn = ConfigFunction(int(options.cfg_function))
            val = options.cfg_value
            req = Request(op_name=options.op.name)
            req.mark_executing()
            if tenant_config_valid(fn, val):
                self.tenants.setdefault(
                    int(options.cfg_key), {}
                )[tenant_config_field(fn)] = val
                req.complete(ErrorCode.OK)
            else:
                req.complete(ErrorCode.CONFIG_ERROR)
            return req
        if (
            options.op == Operation.CONFIG
            and int(options.cfg_function) == int(ConfigFunction.SET_TUNING)
            and int(options.cfg_key)
            == int(TuningKey.PIPELINE_THRESHOLD)
        ):
            # overlap-plane register, handled host-side: the C ABI's
            # register table predates it, and the facade-level segmented
            # split reads it from this host dict anyway
            req = Request(op_name=options.op.name)
            req.mark_executing()
            if options.cfg_value >= 0:
                self.tuning["pipeline_threshold"] = int(options.cfg_value)
                req.complete(ErrorCode.OK)
            else:
                req.complete(ErrorCode.CONFIG_ERROR)
            return req
        if (
            options.op == Operation.CONFIG
            and int(options.cfg_function) == int(ConfigFunction.SET_TUNING)
            and int(options.cfg_key) in (
                int(TuningKey.WIRE_DTYPE),
                int(TuningKey.WIRE_DTYPE_ICI),
                int(TuningKey.WIRE_DTYPE_DCN),
            )
        ):
            # quantized-wire verdict registers (generic + per link
            # class), handled host-side like pipeline_threshold: the
            # ABI predates them and the facade's _plan_for reads this
            # host mirror anyway — same validation as every other tier
            # (0 or a registered wire lane)
            from ... import wire as wirecodec
            from ...constants import TUNING_KEY_NAMES

            req = Request(op_name=options.op.name)
            req.mark_executing()
            val = int(options.cfg_value)
            if val == 0 or wirecodec.is_wire_dtype(val):
                name = TUNING_KEY_NAMES[TuningKey(int(options.cfg_key))]
                self.tuning[name] = val
                req.complete(ErrorCode.OK)
            else:
                req.complete(ErrorCode.CONFIG_ERROR)
            return req
        if (
            options.op == Operation.CONFIG
            and int(options.cfg_function) == int(ConfigFunction.SET_TUNING)
            and int(options.cfg_key) == int(TuningKey.HIERARCHICAL)
        ):
            # topology-plane register, handled host-side: the facade's
            # hierarchical dispatch reads the host mirror; the C
            # dataplane only ever sees the decomposed sub-collectives
            req = Request(op_name=options.op.name)
            req.mark_executing()
            if int(options.cfg_value) in (0, 1):
                self.tuning["hierarchical"] = int(options.cfg_value)
                req.complete(ErrorCode.OK)
            else:
                req.complete(ErrorCode.CONFIG_ERROR)
            return req
        if (
            options.op == Operation.CONFIG
            and int(options.cfg_function) == int(ConfigFunction.SET_TUNING)
            and int(options.cfg_key) in (
                int(TuningKey.CMDRING_RUN_WINDOWS),
                int(TuningKey.CMDRING_LINGER_US),
            )
        ):
            # persistent-sequencer posture registers, handled host-side
            # like pipeline_threshold: the C ABI's register table
            # predates them, and the ring posture overlay reads the
            # host mirror anyway — same clamps as every other tier
            from ...constants import CMDRING_MAX_RUN_WINDOWS

            req = Request(op_name=options.op.name)
            req.mark_executing()
            val = int(options.cfg_value)
            if int(options.cfg_key) == int(TuningKey.CMDRING_RUN_WINDOWS):
                ok = 0 <= val <= CMDRING_MAX_RUN_WINDOWS
                name = "cmdring_run_windows"
            else:
                ok = 0 <= val <= 1_000_000  # >1s would pin the stream
                name = "cmdring_linger_us"
            if ok:
                self.tuning[name] = val
                req.complete(ErrorCode.OK)
            else:
                req.complete(ErrorCode.CONFIG_ERROR)
            return req
        mv = self.membership
        if (
            mv is not None and mv.self_evicted
            and options.comm is not None
            and options.op not in (
                Operation.CONFIG, Operation.NOP, Operation.COPY,
                Operation.COMBINE,
            )
        ):
            # membership plane: a rank voted out of the group fails its
            # comm ops fast at intake with the agreement evidence — the
            # C dataplane cannot consult the Python view mid-call, so
            # the screen sits here, like the facade's intake screen
            req = Request(op_name=options.op.name)
            req.mark_executing()
            req.complete(ErrorCode.RANK_EVICTED, 0, context={
                "op": options.op.name,
                "comm": options.comm.id,
                "membership": mv.evidence(),
                "elapsed_s": 0.0,
            })
            return req
        # quantized wire plane, host-side mirror: the C ABI's cast
        # lanes (hp_compression role) cover the f16/bf16/fp8 wire
        # dtypes; the SCALED int8 lane (per-segment absmax + SR) is
        # mirrored here through the shared host codec — the operand is
        # pre-rounded through the wire exactly as the other tiers
        # round it, and the C engine runs the call uncompressed, so
        # every tier computes the same quantized sum.  (Wire BYTES on
        # this tier stay full-width — the honest-bytes lane needs ABI
        # growth; the numeric protocol is what must agree.)
        options = self._mirror_scaled_wire(options)
        args = _CallArgs()
        args.op = int(options.op)
        args.cfg_function = int(options.cfg_function)
        args.cfg_value = float(options.cfg_value)
        args.cfg_key = int(options.cfg_key)
        args.count = int(options.count)
        args.root_src = int(options.root_src)
        args.root_dst = int(options.root_dst)
        args.tag = int(options.tag) & 0xFFFFFFFF
        args.rfunc = int(options.reduce_function)
        args.compression = int(options.compression)
        args.stream_flags = int(options.stream)
        args.stream_id = int(options.stream_id)
        if options.comm is not None:
            self._ensure_comm(options.comm)
            args.comm_id = options.comm.id
        cfg = options.arithcfg
        if cfg is not None:
            args.acc_dtype = int(cfg.uncompressed)
            args.cmp_dtype = int(cfg.compressed)
            args.supports_rfunc = int(cfg.supports(options.reduce_function))
        else:
            args.acc_dtype = args.cmp_dtype = 2  # FLOAT32
            args.supports_rfunc = 1
        keep = []
        args.op0, args.op0_dtype, k0 = self._operand(options.op0)
        args.op1, args.op1_dtype, k1 = self._operand(options.op1)
        args.res, args.res_dtype, k2 = self._operand(options.res)
        keep = [k for k in (k0, k1, k2) if k is not None]
        native_id = self._lib.accl_ng_start(self._handle, ctypes.byref(args))
        req = NativeRequest(self, native_id, options.op.name, keep)
        req.mark_executing()
        if (
            options.op == Operation.CONFIG
            and int(options.cfg_function) == int(ConfigFunction.SET_TUNING)
        ):
            # write-through mirror: keep the host-readable register dict
            # in step with the C engine — but only once the engine
            # ACCEPTED the write (a rejected value must never leak into
            # the mirror the facade's pipelining verdict reads).  The
            # algorithm registers are skipped: every other tier's table
            # holds their NAME strings, and mirroring the wire's int
            # would flip-flop the dict's value type across tiers.
            from ...constants import ALGORITHM_TUNING_KEYS, TUNING_KEY_NAMES

            try:
                tkey = TuningKey(int(options.cfg_key))
                name = (
                    None if tkey in ALGORITHM_TUNING_KEYS
                    else TUNING_KEY_NAMES.get(tkey)
                )
            except ValueError:
                name = None
            if name is not None:
                val = int(options.cfg_value)

                def _mirror(name=name, val=val, req=req):
                    if req.get_retcode() == ErrorCode.OK:
                        self.tuning[name] = val

                req.add_done_callback(_mirror)
        return req

    def _mirror_scaled_wire(self, options: CallOptions) -> CallOptions:
        """Scaled-wire (int8) calls re-shaped for the C ABI: round the
        operand through the shared host codec (blockwise absmax + this
        call's rank-mixed SR seed — the identical arithmetic every
        other tier runs) into a staging buffer, then dispatch the call
        UNCOMPRESSED.  Cast-lane and uncompressed calls pass through
        untouched."""
        from ...constants import CompressionFlags, Operation
        from ... import wire as wirecodec

        cfg = options.arithcfg
        if (
            cfg is None
            or not options.compression & CompressionFlags.ETH_COMPRESSED
            or not wirecodec.is_scaled(cfg.compressed)
            or options.op == Operation.CONFIG
            or options.op0 is None
            or options.op0.is_dummy
        ):
            return options
        import dataclasses

        import numpy as np

        from ...arithconfig import ArithConfig
        from ...buffer import EmuBuffer

        seed = wirecodec.options_rank_seed(options)
        # operand WIDTH follows the op: the P-wide ops' op0 spans
        # size*count elements (staging only `count` would hand the C
        # engine a truncated buffer it reads past)
        in_w = options.count
        if options.comm is not None and options.op in (
            Operation.REDUCE_SCATTER, Operation.ALLTOALL,
            Operation.SCATTER,
        ):
            in_w *= options.comm.size
        x = np.asarray(options.op0.device_view()[:in_w])
        rounded = wirecodec.roundtrip(
            x, cfg.compressed, seed
        ).astype(x.dtype)
        staged = EmuBuffer.from_array(np.ascontiguousarray(rounded))
        staged.sync_to_device()
        return dataclasses.replace(
            options,
            op0=staged,
            arithcfg=ArithConfig(
                cfg.uncompressed, cfg.uncompressed, cfg.reduce_functions
            ),
            compression=options.compression
            & ~CompressionFlags.ETH_COMPRESSED,
        )

    def shutdown(self) -> None:
        if not self._shut:
            self._shut = True
            self._lib.accl_ng_engine_shutdown(self._handle)

    # -- device stream ports -------------------------------------------------
    def stream_push(self, stream_id: int, data: bytes) -> None:
        self._lib.accl_ng_stream_push(
            self._handle, stream_id, data, len(data)
        )

    def stream_pop(self, stream_id: int, timeout: Optional[float] = None) -> bytes:
        t = 30.0 if timeout is None else float(timeout)
        cap = 1 << 16
        while True:
            out = ctypes.create_string_buffer(cap)
            n = self._lib.accl_ng_stream_pop(
                self._handle, stream_id, out, cap, t
            )
            if n < 0:
                raise TimeoutError(f"stream {stream_id} pop timed out")
            if n <= cap:
                return out.raw[:n]
            cap = int(n)  # chunk bigger than buffer: retry with exact size

    # -- contract plane (accl_tpu.contract) ----------------------------------
    def contract_anchor(self):
        """None (no board): in-proc native groups share one
        process-wide CDLL, but anchoring the digest board there would
        let two *sequential* groups cross-compare stale windows under
        colliding comm ids.  The native tier verifies via the facade
        intake screen; its C dataplane cannot consult a Python verifier
        mid-call (set_contract_verifier keeps the BaseEngine store-only
        behavior)."""
        return None

    # -- debug (ref ACCL::dump_eager_rx_buffers) -----------------------------
    def dump_rx_buffers(self) -> str:
        used = self._lib.accl_ng_rx_occupancy(self._handle)
        total = self._lib.accl_ng_rx_capacity(self._handle)
        return "\n".join(
            f"rxbuf[{i}] {'FILLED' if i < used else 'IDLE'}"
            for i in range(total)
        )

    def telemetry_report(self) -> dict:
        """Native-tier counters for the telemetry snapshot: the C++
        engine's rx-pool occupancy over the C ABI (per-call facts ride
        the shared Request flight-recorder hook like every tier)."""
        return {
            "device_interactions": None,
            "rx_pool": {
                "used": int(self._lib.accl_ng_rx_occupancy(self._handle)),
                "total": int(self._lib.accl_ng_rx_capacity(self._handle)),
            },
            "faults": None,
            # monitor plane: per-rank baselines only (no board — the
            # contract_anchor rationale above applies to the skew judge
            # identically: sequential groups would cross-compare)
            "skew_exchange": "local",
        }


# ---------------------------------------------------------------------------
# group constructors (mirror core.emulated_group / socket_group_member)
# ---------------------------------------------------------------------------


def native_group(
    n: int,
    rx_buffer_count: int = DEFAULT_RX_BUFFER_COUNT,
    rx_buffer_size: int = DEFAULT_RX_BUFFER_SIZE,
    **accl_kwargs,
) -> List:
    """N ranks in one process over the C++ in-proc transport."""
    from ...core import ACCL

    # unique address namespace per group so groups never collide in the
    # process-wide native registry
    gid = next(_group_ids)
    ranks = [
        Rank(
            address=f"native:{gid}:{i}",
            session=i,
            max_segment_size=rx_buffer_size,
        )
        for i in range(n)
    ]
    engines = [
        NativeEngine(
            f"native:{gid}:{i}",
            NativeEngine.TRANSPORT_INPROC,
            rx_buffer_count=rx_buffer_count,
            rx_buffer_size=rx_buffer_size,
        )
        for i in range(n)
    ]
    return [ACCL(engines[i], ranks, i, **accl_kwargs) for i in range(n)]


def native_socket_member(
    rank: int,
    addresses: Sequence[str],
    rx_buffer_count: int = DEFAULT_RX_BUFFER_COUNT,
    rx_buffer_size: int = DEFAULT_RX_BUFFER_SIZE,
    **accl_kwargs,
):
    """This process's member of a multi-process native group over TCP (one
    process per rank, the reference's per-rank emulator-process layout)."""
    from ...core import ACCL

    ranks = [
        Rank(address=a, session=i, max_segment_size=rx_buffer_size)
        for i, a in enumerate(addresses)
    ]
    engine = NativeEngine(
        addresses[rank],
        NativeEngine.TRANSPORT_SOCKET,
        rx_buffer_count=rx_buffer_count,
        rx_buffer_size=rx_buffer_size,
    )
    return ACCL(engine, ranks, rank, **accl_kwargs)
