"""Backend interface: what the ACCL facade needs from a collective engine.

Role model: the abstract device ``CCLO`` (``driver/xrt/include/accl/
cclo.hpp:35-202``) with its ``Options`` record and
``call/start/wait/test`` surface.  A backend owns the scheduling and data
movement for one rank (emulator) or for a whole mesh (XLA tier).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..analysis.markers import spmd_uniform
from ..arithconfig import ArithConfig
from ..buffer import BaseBuffer
from ..communicator import Communicator
from ..constants import (
    CompressionFlags,
    HostFlags,
    Operation,
    ReduceFunction,
    StreamFlags,
)


@dataclasses.dataclass
class CallOptions:
    """One engine call, fully resolved (ref ``CCLO::Options``)."""

    op: Operation
    comm: Optional[Communicator] = None
    count: int = 0  # element count in *uncompressed* dtype
    root_src: int = 0  # root / source rank (op-dependent)
    root_dst: int = 0  # destination rank for send/recv
    tag: int = 0
    reduce_function: ReduceFunction = ReduceFunction.SUM
    arithcfg: Optional[ArithConfig] = None
    compression: CompressionFlags = CompressionFlags.NO_COMPRESSION
    stream: StreamFlags = StreamFlags.NO_STREAM
    host: HostFlags = HostFlags.NO_HOST
    op0: Optional[BaseBuffer] = None
    op1: Optional[BaseBuffer] = None
    res: Optional[BaseBuffer] = None
    stream_id: int = 0  # destination stream port for stream_put
    # Operation.CONFIG only:
    cfg_function: int = 0
    cfg_value: float = 0.0
    cfg_key: int = 0  # tuning register selector for SET_TUNING
    # cached-dispatch state (accl_tpu.plans): the facade's CollectivePlan
    # for this call (engines park prepared state in plan.engine), and the
    # per-size-bucket tuning-register overlay from a loaded TuningPlan —
    # engines overlay it onto their global registers at execution time
    # via effective_tuning()/eager_limit() below
    plan: Optional[object] = None
    tuning: Optional[dict] = None
    # quantized wire plane (accl_tpu.wire): the stochastic-rounding
    # seed for this call's wire lane.  0 = deterministic rounding (the
    # f16/bf16 lanes); nonzero for the fp8/int8 lanes, derived
    # SPMD-uniformly by the facade (wire.call_seed) and mixed per rank
    # at the point of encoding (wire.rank_seed) so ranks draw
    # independent streams from one shared slot/seed value
    wire_seed: int = 0
    # fused compute slot (constants.FusedCompute value): which compute
    # epilogue rides this call's command-ring slot.  0 = plain
    # collective; nonzero calls pack their compute operands into the
    # operand row (cmdring.ring_widths fused geometry) and NEVER run
    # the plain base op off-ring — ineligible fused calls decompose on
    # host with a counted fallback.  fuse_param is the epilogue scalar
    # (alpha / lr / scale), carried Q16.16 in the slot's fparam word.
    fuse: int = 0
    fuse_param: float = 0.0

    @spmd_uniform
    def eager_limit(self, default: int) -> int:
        """The eager-vs-rendezvous threshold steering THIS call: the
        per-size-bucket TuningPlan overlay's value when present, else
        the engine's global register.  The single definition every tier
        reads — divergent copies would skew protocol choice across
        ranks and break SPMD uniformity."""
        if self.tuning is not None:
            return self.tuning.get("max_eager_size", default)
        return default

    @spmd_uniform
    def effective_tuning(self, table: dict) -> dict:
        """The engine tuning table overlaid with this call's per-bucket
        registers (identical across ranks when every member loaded the
        same plan — the SPMD-uniformity contract)."""
        if not self.tuning:
            return table
        eff = dict(table)
        eff.update(self.tuning)
        return eff


class InteractionCounter:
    """Counts *device interactions*: program dispatches and host<->device
    transfers an engine issues on the data path.  The reference's hostctrl
    discipline is ONE command per collective (hostctrl.cpp:22-63); on a
    tunneled host every extra interaction bills a full RTT, so the engines
    keep an honest running count — exposed via
    ``ACCL.capabilities()["device_interactions"]`` and asserted by
    tests/test_dispatch_overhead.py (one collective == one bump on the
    gang fast path).

    Buffer *creation* (``create_buffer`` staging) is deliberately not
    counted: the contract covers the collective between creation and
    sync, matching the zero-host-copy transfer-guard tests.

    Bumps come from every rank thread of a gang (and from deferred
    adoption running on waiter threads), so the increment is locked —
    ``+=`` alone is load/add/store and can lose counts across threads,
    which would break the tests' strict-equality assertions.
    """

    __slots__ = ("count", "_lock")

    def __init__(self):
        import threading

        self.count = 0
        self._lock = threading.Lock()

    def bump(self, n: int = 1) -> None:
        with self._lock:
            self.count += n

    def read(self) -> int:
        return self.count


class BaseEngine:
    """One rank's collective engine."""

    def start(self, options: CallOptions):
        """Enqueue a call; returns a Request immediately."""
        raise NotImplementedError

    def start_batch(self, items) -> None:
        """Dispatch a flushed command-queue batch: ``items`` is a list of
        ``(CallOptions, Request)`` pairs whose Requests were created by
        the facade at queue time (so ``run_async`` callers already hold
        them).  Engines that can fuse a batch into one device interaction
        override this (XLA gang / dist); the default just serializes,
        bridging each inner engine request onto the caller's."""
        for options, req in items:
            inner = self.start(options)
            inner.add_done_callback(
                lambda i=inner, r=req: r.complete(
                    i.get_retcode(), i.get_duration_ns(),
                    context=i.error_context,
                )
            )

    def device_interactions(self):
        """Engine-lifetime device-interaction count, or ``None`` on tiers
        with no device (emulator/native: the dataplane is host memory)."""
        return None

    def drain_inflight(self, timeout=None) -> bool:
        """Overlap plane: block until every launched-but-incomplete call
        of this engine has completed (the facade's ``flush()``/config/
        ``soft_reset`` drain points).  Tiers without an in-flight window
        (emulator/native: requests complete from their own schedulers)
        are a no-op.  Returns False only on timeout."""
        return True

    def contract_anchor(self):
        """The object the contract plane's in-process digest exchange
        (``accl_tpu.contract.board_for``) anchors on.  Engines whose
        rank handles share a process-wide object override this with it
        (InProc fabric, XLA gang context); the default — ``None`` — on
        one-engine-per-process tiers skips board posting entirely (a
        single-poster board can never convict; copying the evidence
        ring into it every window would be pure overhead against the
        <=5% budget) and leaves verification to the wire piggyback /
        facade intake checks."""
        return None

    #: the facade-armed ContractVerifier (None = verification off)
    contract_verifier = None

    #: the facade's straggler SkewTracker (monitor plane; None = off)
    skew_tracker = None

    #: the facade's MembershipView (accl_tpu.membership; None = off)
    membership = None

    #: facade hook fired on every peer-health state transition
    #: (``(peer, old_state, new_state)``): feeds the transition
    #: counters/event ring and, when elastic membership is armed, the
    #: dead-verdict eviction proposal.  Must be cheap and never raise.
    on_health_transition = None

    def set_membership(self, view) -> None:
        """Arm (or with ``None`` disarm) the membership plane on this
        engine.  Default: store the handle — the facade's intake/
        failure paths do the acting; fabric tiers override to observe
        MEMBER agreement frames at delivery and to fail in-flight work
        against confirmed evictions fast."""
        self.membership = view

    def on_membership_cutover(self, plan: dict, addresses: tuple = (),
                              comm_ids: tuple = ()) -> None:
        """Engine-side shrink hook: tear down / re-arm per-comm session
        state over the survivors (ring sessions + mailboxes on the XLA
        tier; rx/ledger/retransmit purge + health-strike hygiene on the
        emulator).  ``addresses`` are the evicted peers' transport
        addresses; ``comm_ids`` the communicators that shrank.
        Default: no per-comm session state to re-arm."""

    def on_membership_restore(self) -> None:
        """Engine-side restore hook (soft_reset re-admission): the
        reset itself already flushed engine state on every tier."""

    def set_skew_tracker(self, tracker) -> None:
        """Arm (or with ``None`` disarm) the monitor plane's cross-rank
        skew exchange on this engine.  Default: store the handle — on
        board-anchored tiers (InProc emulator, XLA gang) the shared
        judge does the exchanging and the engine has nothing to wire;
        fabric tiers override to observe peers' piggybacked window
        claims at delivery (the contract plane's stamp cadence,
        reused)."""
        self.skew_tracker = tracker

    #: the facade's POSTMORTEM frame handler (None = postmortem off)
    postmortem_handler = None

    def set_postmortem(self, handler) -> None:
        """Arm (or with ``None`` disarm) the postmortem plane's wire
        solicitation on this engine.  Default: store the handle —
        board-anchored tiers solicit in process over the anchored
        registry; fabric tiers override to route POSTMORTEM frames to
        the handler at delivery."""
        self.postmortem_handler = handler

    def trace_events(self) -> list:
        """Engine-owned Chrome/Perfetto trace events merged into the
        facade's export: ring-resident slot spans on the gang tier
        (one span per slot, parented under its refill window and
        flow-linked to the issuing call); [] on tiers with no engine-
        resident execution to introspect."""
        return []

    def skew_exchange_mode(self) -> str:
        """How this tier's straggler samples cross ranks: ``"board"``
        (shared in-process judge via ``contract_anchor()``), ``"wire"``
        (per-message piggyback), or ``"local"`` (single-rank baselines
        only — the dist tier's cross-process exchange rides ROADMAP
        item 2's topology work, like the contract plane's KV
        piggyback)."""
        return "board" if self.contract_anchor() is not None else "local"

    def set_contract_verifier(self, verifier) -> None:
        """Arm (or with ``None`` disarm) engine-side contract checks.
        Default: store the handle — the facade's intake screen is the
        only check on such tiers (native: the C dataplane cannot consult
        a Python verifier mid-call).  Engines with their own schedulers
        or delivery paths override to fail in-flight work fast too."""
        self.contract_verifier = verifier

    def health_report(self, comm) -> dict:
        """Per-peer health map for ``comm``, keyed by comm-relative rank
        (``capabilities()["health"]``).  Engines with timeout/retry
        accounting (emulator) or a gang watchdog (XLA) override this; the
        default reports every peer healthy."""
        return {
            i: {"state": "ok", "timeouts": 0, "failures": 0, "last_event": ""}
            for i in range(comm.size)
            if i != comm.local_rank
        }

    def telemetry_report(self) -> dict:
        """Engine-side counters for ``ACCL.telemetry_snapshot()``: the
        tier-specific live-resource depths and event counters (rx pool,
        retransmit window, fault injector, gang slots, stream ports).
        Each tier overrides with its own facts; the shape is flat
        scalars/small dicts so the Prometheus exporter can fold the
        numbers out as gauges.  Must be cheap and side-effect-free —
        dashboards poll it."""
        return {
            "device_interactions": self.device_interactions(),
            "faults": None,
            "skew_exchange": self.skew_exchange_mode(),
        }

    def create_buffer(self, count: int, dtype, host_only: bool = False,
                      data=None):
        """Backend-appropriate buffer (ref: ACCL::create_buffer dispatching
        to XRTBuffer/SimBuffer per device).  Default: emulator-tier host
        pair; device tiers override with HBM-resident buffers.

        ``data`` (a 1-D numpy array) seeds the buffer: the host side ALIASES
        it (mutating the caller's array mutates host memory, the reference's
        wrap-existing-pointer buffer constructor) and the device side is
        synced on return."""
        from ..buffer import EmuBuffer

        if data is not None:
            buf = EmuBuffer.from_array(data, host_only=host_only)
            buf.sync_to_device()
            return buf
        return EmuBuffer(count, dtype, host_only=host_only)

    def shutdown(self) -> None:
        raise NotImplementedError

    # -- device stream ports (stream_put / streaming operands) --------------
    def stream_push(self, stream_id: int, data: bytes) -> None:
        raise NotImplementedError

    def stream_pop(self, stream_id: int, timeout: Optional[float] = None) -> bytes:
        raise NotImplementedError


class StreamPortMixin:
    """Local device stream ports (the external-kernel AXIS interface) and
    the streaming-operand/result payload helpers, shared by the device-tier
    engines.  Hosts must call :meth:`_init_streams` and provide
    ``self.timeout_s``."""

    def _init_streams(self) -> None:
        import threading

        self._streams: dict = {}
        self._stream_cv = threading.Condition()

    def stream_push(self, stream_id: int, data: bytes) -> None:
        with self._stream_cv:
            self._streams.setdefault(stream_id, []).append(data)
            self._stream_cv.notify_all()

    def stream_pop(self, stream_id: int, timeout: Optional[float] = None) -> bytes:
        with self._stream_cv:
            ok = self._stream_cv.wait_for(
                lambda: self._streams.get(stream_id), timeout
            )
            if not ok:
                raise TimeoutError(f"stream {stream_id} empty")
            return self._streams[stream_id].pop(0)

    def _pop_stream_payload(self, options: CallOptions, count=None):
        """Blocking pop of a full streaming operand from this rank's
        stream port; None on timeout (the engine's DMA deadline)."""
        import time

        import numpy as np

        from ..constants import dtype_to_numpy

        cfg = options.arithcfg
        src_dt = (
            cfg.compressed
            if options.compression & CompressionFlags.OP0_COMPRESSED
            else cfg.uncompressed
        )
        npdt = dtype_to_numpy(src_dt)
        n = options.count if count is None else int(count)
        need = n * npdt.itemsize
        raw = b""
        deadline = time.monotonic() + self.timeout_s
        try:
            while len(raw) < need:
                raw += self.stream_pop(
                    options.stream_id,
                    timeout=max(0.01, deadline - time.monotonic()),
                )
        except TimeoutError:
            return None
        return np.frombuffer(raw[:need], npdt).copy()

    def _push_stream_result(self, options: CallOptions, data) -> None:
        """Result row to this rank's stream port, in the wire dtype the
        compression flags request (the RES_STREAM lane)."""
        import numpy as np

        from ..constants import dtype_to_numpy

        cfg = options.arithcfg
        res_dt = (
            cfg.compressed
            if options.compression & CompressionFlags.RES_COMPRESSED
            else cfg.uncompressed
        )
        npdt = dtype_to_numpy(res_dt)
        self.stream_push(
            options.stream_id,
            np.asarray(data)[: options.count].astype(npdt).tobytes(),
        )
