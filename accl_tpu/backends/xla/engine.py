"""XLA device backend: the ACCL facade over a real device mesh.

The reference's device tier drives one offload engine per FPGA over the
100G fabric; the TPU equivalent is SPMD — *one* XLA program executes the
collective across every chip at once.  This backend bridges the MPI-like
per-rank call model onto that: rank handles submit their operands into a
shared :class:`XLAGangContext`; when every rank of a communicator has posted
the matching call, the gang runs one jitted ``shard_map`` program over the
mesh (built from ``accl_tpu.ops``) and distributes the per-rank results.

This is the semantic bridge SURVEY.md §7 calls the hard part ("eager/
rendezvous semantics vs XLA's static world"): tag-matched point-to-point
pairs rendezvous *at the gang*, and the data then moves with a
collective-permute on ICI.

Mapping notes (ref -> here):
* communicator        -> sub-``Mesh`` over the first ``comm.size`` devices
                         (ref: comm tables in exchange memory)
* eager/rendezvous    -> collapsed: gang rendezvous + XLA scheduling
                         (ref: protocol select at c:587/667/808)
* compression flags   -> wire-dtype cast stages around the collective
                         (ref: hp_compression lanes)
* per-call perf ctr   -> wall-clock ns around the XLA program
"""

from __future__ import annotations

import functools
import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax

from ...compat import install as _compat_install

_compat_install()  # legacy-jax shims (shard_map kwargs, lax.axis_size)
import jax.numpy as jnp

from ...communicator import Communicator
from ...constants import (
    CompressionFlags,
    ConfigFunction,
    DEFAULT_TIMEOUT_S,
    ErrorCode,
    MAX_EAGER_SIZE_LIMIT,
    Operation,
    ReduceFunction,
    StreamFlags,
    dtype_to_numpy,
)
from ...buffer import (
    DeviceBuffer,
    DummyBuffer,
    EmuBuffer,
    dev_zeros as _dev_zeros,
    make_buffer,
)
from ...overlap import InflightWindow, drain_deadline_s
from ...request import Request
from ..base import BaseEngine, CallOptions, InteractionCounter, StreamPortMixin
from ...ops import driver as opdriver
from .cmdring import GangCommandRing

#: sentinel returned by the gang execution paths when a call's completion
#: was handed to the in-flight window (the overlap plane): the caller
#: must NOT complete the requests — the window's drainer will, from the
#: device done-probe, in launch order.
IN_FLIGHT = object()


def _np_stack_op0(
    calls: List[CallOptions], counts: List[int], ic=None
) -> np.ndarray:
    """Stack per-rank operands (rank-major) into one (size, n) array."""
    rows = []
    width = max(counts) if counts else 0
    for call, n in zip(calls, counts):
        if call.op0 is not None and not call.op0.is_dummy:
            if ic is not None and isinstance(call.op0, DeviceBuffer):
                ic.bump()  # D2H read of the operand (fallback staging)
            row = np.asarray(call.op0.device_view()[:n])
            if row.size < width:
                row = np.pad(row, (0, width - row.size))
        else:
            row = np.zeros(width, dtype_to_numpy(call.arithcfg.uncompressed))
        rows.append(row)
    return np.stack(rows)


def _write_host_result(buf, row, n: int, ic=None) -> None:
    """Place a host-computed result row into any buffer type (the fallback
    path's writer; the zero-copy path uses DeviceBuffer.store directly)."""
    if isinstance(buf, DeviceBuffer):
        npdt = dtype_to_numpy(buf.dtype)
        arr = jax.device_put(np.asarray(row)[:n].astype(npdt), buf.device)
        dispatched = buf.store(arr, n)
        if ic is not None:
            ic.bump(1 + int(dispatched))  # the H2D put (+ writeback)
    else:
        dst = buf.device_view()[:n]
        np.copyto(dst, np.asarray(row)[:n].astype(dst.dtype))


# The shard prep/trim steps run as tiny cached jitted programs rather than
# eager ops: eager slicing dispatches its index scalars host->device, which
# would break the zero-host-copy guarantee (and trip transfer guards).
@functools.lru_cache(maxsize=1024)
def _prep_program(width: int, wire_name: Optional[str], device,
                  flat: bool = False):
    """Slice/round a rank's operand into a shard: ``flat`` keeps the
    (width,) 1-D layout (the engine's flat globals), otherwise the stacked
    (1, width) row.  Flat exact-size uncompressed operands never get here —
    they plug in raw with no program at all."""
    from jax.sharding import SingleDeviceSharding

    def f(a):
        a = a[:width]
        if wire_name is not None:
            a = a.astype(jnp.dtype(wire_name)).astype(a.dtype)
        return a if flat else a.reshape(1, width)

    return jax.jit(f, out_shardings=SingleDeviceSharding(device))


@functools.lru_cache(maxsize=1024)
def _trim_program(width: int, device):
    from jax.sharding import SingleDeviceSharding

    return jax.jit(
        lambda a: a.reshape(-1)[:width],
        out_shardings=SingleDeviceSharding(device),
    )


@functools.lru_cache(maxsize=1024)
def _cast_program(npdt, device):
    from jax.sharding import SingleDeviceSharding

    return jax.jit(
        lambda a: a.astype(npdt),
        out_shardings=SingleDeviceSharding(device),
    )


@functools.lru_cache(maxsize=512)
def _p2p_hop_program(src_dev, dst_dev):
    """The device-fabric hop for a matched send/recv pair: a jitted
    collective-permute over a two-device mesh [src, dst] — on real TPU
    slices the payload moves over ICI, the analog of the reference's
    packetizer->wire->depacketizer path (ccl_offload_control.c:573-710).
    Returns (mesh, program)."""
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec

    try:
        from jax import shard_map
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map  # type: ignore

    mesh = Mesh([src_dev, dst_dev], ("p2p",))
    spec = PartitionSpec("p2p")
    prog = jax.jit(
        shard_map(
            lambda x: lax.ppermute(x, "p2p", [(0, 1)]),
            mesh=mesh,
            in_specs=(spec,),
            out_specs=spec,
            check_vma=False,
        )
    )
    return mesh, prog


def _p2p_device_deliver(payload, res: DeviceBuffer, count: int,
                        ic=None) -> None:
    """Move a device-resident p2p payload to the receiver's chip with a
    collective-permute and adopt it into the result buffer — no host in
    the data path.  ``ic`` counts each program dispatch (the p2p leg is
    honestly multi-interaction; the single-interaction contract covers
    the gang collectives, not the rendezvous hop)."""
    from jax.sharding import NamedSharding, PartitionSpec

    bump = ic.bump if ic is not None else (lambda n=1: None)
    if payload.ndim != 1 or payload.shape[0] < count:
        raise ValueError(
            f"p2p payload of shape {payload.shape} into count {count}"
        )
    (src_dev,) = payload.devices()
    dst_dev = res.device
    res_npdt = dtype_to_numpy(res.dtype)
    if src_dev == dst_dev:
        # self-send: a device-local copy (jit output, distinct array)
        arr = _trim_program(count, dst_dev)(payload)
        bump()
    else:
        mesh, prog = _p2p_hop_program(src_dev, dst_dev)
        shards = [
            _prep_program(count, None, src_dev)(payload),
            _dev_zeros((1, count), payload.dtype, dst_dev),
        ]
        global_in = jax.make_array_from_single_device_arrays(
            (2, count),
            NamedSharding(mesh, PartitionSpec("p2p")),
            shards,
        )
        out = prog(global_in)
        arr = next(
            s.data for s in out.addressable_shards if s.device == dst_dev
        )
        arr = _trim_program(count, dst_dev)(arr)
        bump(4)  # prep + zeros + hop program + trim
    if arr.dtype != res_npdt:
        # wire-compressed payload: decompress lane on the receiving chip
        arr = _cast_program(res_npdt, dst_dev)(arr)
        bump()
    if res.store(arr, count):
        bump()



# per-op operand/result widths in units of ``count`` ('P' = size*count)
IN_W = {
    Operation.ALLREDUCE: 1, Operation.REDUCE: 1, Operation.BCAST: 1,
    Operation.ALLGATHER: 1, Operation.GATHER: 1,
    Operation.REDUCE_SCATTER: "P", Operation.SCATTER: "P",
    Operation.ALLTOALL: "P",
}
OUT_W = {
    Operation.ALLREDUCE: 1, Operation.REDUCE: 1, Operation.BCAST: 1,
    Operation.SCATTER: 1, Operation.REDUCE_SCATTER: 1,
    Operation.ALLGATHER: "P", Operation.GATHER: "P",
    Operation.ALLTOALL: "P",
}


def run_rooted_with_tuning(op, global_arr, mesh, lead, tuning, donate=False,
                           prep=None):
    """Rooted collective with algorithm selection from the tuning
    registers: XLA lowering, or the rooted Pallas ring-relay kernels (the
    algorithm-faithful mode of the reference's rooted trees).  Shared by
    the single-process gang and the multi-process dist engine.  ``prep``
    fuses operand staging into the program (opdriver._with_prep)."""
    nseg = int(tuning.get("ring_segments", 1))
    fn = lead.reduce_function
    if op == Operation.REDUCE:
        if tuning.get("reduce_algorithm", "xla") == "pallas_ring":
            return opdriver.run_pallas_reduce(
                global_arr, mesh, lead.root_dst, fn, nseg, prep=prep
            )
        return opdriver.run_reduce(
            global_arr, mesh, lead.root_dst, fn, prep=prep
        )
    if op == Operation.BCAST:
        if tuning.get("bcast_algorithm", "xla") == "pallas_ring":
            return opdriver.run_pallas_bcast(
                global_arr, mesh, lead.root_src, nseg, prep=prep
            )
        return opdriver.run_bcast(
            global_arr, mesh, lead.root_src, donate=donate, prep=prep
        )
    if op == Operation.SCATTER:
        if tuning.get("scatter_algorithm", "xla") == "pallas_ring":
            return opdriver.run_pallas_scatter(
                global_arr, mesh, lead.root_src, nseg, prep=prep
            )
        return opdriver.run_scatter(
            global_arr, mesh, lead.root_src, prep=prep
        )
    if op == Operation.GATHER:
        if tuning.get("gather_algorithm", "xla") == "pallas_ring":
            return opdriver.run_pallas_gather(
                global_arr, mesh, lead.root_src, nseg, prep=prep
            )
        return opdriver.run_gather(
            global_arr, mesh, lead.root_src, prep=prep
        )
    raise ValueError(op)  # pragma: no cover


def apply_tuning(tuning: dict, options) -> ErrorCode:
    """Validate + apply one SET_TUNING register write into a device-tier
    tuning table (shared by the gang and dist engines; identical checks
    to the emulator/native tiers)."""
    from ...constants import (
        ALGORITHM_TUNING_KEYS,
        AllreduceAlgorithm,
        ROOTED_ALGORITHMS,
        TUNING_KEY_NAMES,
        TuningKey,
    )

    try:
        key = TuningKey(int(options.cfg_key))
    except ValueError:
        return ErrorCode.CONFIG_ERROR
    val = options.cfg_value
    if val < 0:
        return ErrorCode.CONFIG_ERROR
    if key in ALGORITHM_TUNING_KEYS:
        try:
            algo = AllreduceAlgorithm(int(val))
        except ValueError:
            return ErrorCode.CONFIG_ERROR
        if (
            key != TuningKey.ALLREDUCE_ALGORITHM
            and algo not in ROOTED_ALGORITHMS
        ):
            return ErrorCode.CONFIG_ERROR
        tuning[TUNING_KEY_NAMES[key]] = algo.name.lower()
    elif key == TuningKey.RING_SEGMENTS:
        if int(val) < 1:
            return ErrorCode.CONFIG_ERROR
        tuning["ring_segments"] = int(val)
    elif key in (
        TuningKey.WIRE_DTYPE,
        TuningKey.WIRE_DTYPE_ICI,
        TuningKey.WIRE_DTYPE_DCN,
    ):
        # quantized wire plane: the per-bucket compression verdict must
        # name a REGISTERED wire lane (or 0 = off) — a typo'd DataType
        # must fail the config write, not surface as an arith-lookup
        # error N calls later.  The per-link-class variants validate
        # identically (0 additionally means "defer to the generic")
        from ...wire import is_wire_dtype

        if int(val) != 0 and not is_wire_dtype(int(val)):
            return ErrorCode.CONFIG_ERROR
        tuning[TUNING_KEY_NAMES[key]] = int(val)
    elif key == TuningKey.HIERARCHICAL:
        if int(val) > 1:
            return ErrorCode.CONFIG_ERROR
        tuning["hierarchical"] = int(val)
    elif key == TuningKey.CMDRING_RUN_WINDOWS:
        # persistent-sequencer posture registers: 0 = env default;
        # the run-windows budget is clamped exactly like the env knob
        # (an unbounded run would pin the device stream indefinitely)
        from ...constants import CMDRING_MAX_RUN_WINDOWS

        if int(val) > CMDRING_MAX_RUN_WINDOWS:
            return ErrorCode.CONFIG_ERROR
        tuning["cmdring_run_windows"] = int(val)
    elif key == TuningKey.CMDRING_LINGER_US:
        if int(val) > 1_000_000:  # >1s would pin the device stream
            return ErrorCode.CONFIG_ERROR
        tuning["cmdring_linger_us"] = int(val)
    else:
        if key == TuningKey.GATHER_FLAT_TREE_MAX_FANIN and val < 1:
            return ErrorCode.CONFIG_ERROR
        tuning[TUNING_KEY_NAMES[key]] = int(val)
    return ErrorCode.OK


def run_allreduce_with_tuning(global_arr, mesh, fn, wire_dtype, tuning,
                              prep=None):
    """Allreduce with algorithm + segmentation + wire compression from the
    tuning registers; ``prep`` fuses the operand width slice into the
    program (the wire lane already runs in-program on every algorithm)."""
    algo = tuning.get("allreduce_algorithm", "xla")
    nseg = int(tuning.get("ring_segments", 1))
    bidir = algo == "pallas_ring_bidir"
    if wire_dtype is not None:
        wire_name = dtype_to_numpy(wire_dtype).name
        if algo in ("pallas_ring", "pallas_ring_bidir"):
            # compression lanes run inside the kernel
            return opdriver.run_pallas_allreduce(
                global_arr, mesh, fn, nseg, wire_dtype=wire_name,
                bidirectional=bidir, prep=prep,
            )
        return opdriver.run_compressed_allreduce(
            global_arr, mesh, fn, wire_dtype=wire_name, prep=prep
        )
    if algo == "ring":
        return opdriver.run_ring_allreduce(global_arr, mesh, fn, nseg,
                                           prep=prep)
    if algo in ("pallas_ring", "pallas_ring_bidir"):
        return opdriver.run_pallas_allreduce(
            global_arr, mesh, fn, nseg, bidirectional=bidir, prep=prep
        )
    return opdriver.run_allreduce(global_arr, mesh, fn, prep=prep)


def effective_tuning(tuning: dict, lead: CallOptions) -> dict:
    """The register set steering one call — the per-size selection at
    dispatch that generalizes the reference's flat-tree ``*_MAX_COUNT``
    thresholds (one definition for every tier: CallOptions)."""
    return lead.effective_tuning(tuning)


def resolve_lowering(op, lead: CallOptions, tuning: dict, wire_npdt):
    """(driver op name, extra) for the prepared-program handle a plan
    caches — the same selection run_allreduce_with_tuning /
    run_rooted_with_tuning make per call, resolved ONCE at plan-prepare
    time.  BCAST is excluded (its donating form mutates operand arrays,
    which the prepared fast path must not cache around)."""
    nseg = int(tuning.get("ring_segments", 1))
    wire_name = np.dtype(wire_npdt).name if wire_npdt is not None else None
    if op == Operation.ALLREDUCE:
        algo = tuning.get("allreduce_algorithm", "xla")
        bidir = algo == "pallas_ring_bidir"
        if algo in ("pallas_ring", "pallas_ring_bidir"):
            return "pallas_allreduce", (nseg, wire_name, bidir)
        if wire_name is not None:
            return "compressed_allreduce", wire_name
        if algo == "ring":
            return "ring_allreduce", nseg
        return "allreduce", None
    if op == Operation.REDUCE:
        if tuning.get("reduce_algorithm", "xla") == "pallas_ring":
            return "pallas_reduce", (lead.root_dst, nseg)
        return "reduce", lead.root_dst
    if op == Operation.SCATTER:
        if tuning.get("scatter_algorithm", "xla") == "pallas_ring":
            return "pallas_scatter", (lead.root_src, nseg)
        return "scatter", lead.root_src
    if op == Operation.GATHER:
        if tuning.get("gather_algorithm", "xla") == "pallas_ring":
            return "pallas_gather", (lead.root_src, nseg)
        return "gather", lead.root_src
    if op == Operation.ALLGATHER:
        return "allgather", None
    if op == Operation.REDUCE_SCATTER:
        return "reduce_scatter", None
    if op == Operation.ALLTOALL:
        return "alltoall", None
    raise ValueError(op)  # pragma: no cover - callers gate on _FAST_OPS


#: ops eligible for the prepared-program fast path (pure-functional
#: lowerings; BCAST stays on the full path — donation semantics)
_FAST_OPS = frozenset((
    Operation.ALLREDUCE, Operation.REDUCE, Operation.SCATTER,
    Operation.GATHER, Operation.ALLGATHER, Operation.REDUCE_SCATTER,
    Operation.ALLTOALL,
))


class _GangSlot:
    def __init__(self, world: int, timeout_s: float, comm=None):
        self.calls: Dict[int, Tuple[CallOptions, Request]] = {}
        self.world = world
        self.deadline = time.monotonic() + timeout_s
        self.watchdog: Optional[threading.Timer] = None
        self.comm = comm  # for absent-rank health attribution on timeout


class XLAGangContext:
    """Shared per-process rendezvous point for all rank handles on a mesh."""

    def __init__(self, mesh=None):
        self.mesh = mesh  # full mesh; sub-meshes derived per communicator
        self._lock = threading.Lock()
        self._slots: Dict[tuple, _GangSlot] = {}
        self._seq: Dict[Tuple[int, int], int] = {}  # (comm_id, rank) -> call #
        self._submeshes: Dict[int, object] = {}
        self.timeout_s = DEFAULT_TIMEOUT_S
        # assembled-global reuse: repeated calls on the same operand
        # buffers rebuild an identical sharded view, so cache it keyed by
        # shard identity (strong refs keep ids stable; identity re-checked
        # on hit).  Donating ops bypass this (donation would invalidate
        # the cached view).
        self._asm_cache: Dict[tuple, tuple] = {}
        # algorithm-selection tuning registers (the reference's runtime
        # flat-vs-tree threshold registers, accl.cpp:1198-1208):
        #   allreduce_algorithm: "xla" (XLA's scheduler picks),
        #   "ring" (explicit ppermute pipeline), "pallas_ring" (the
        #   Pallas remote-DMA kernel)
        self.tuning = {"allreduce_algorithm": "xla", "ring_segments": 1}
        # monotone register-write counter: prepared per-plan state
        # (templates / program handles parked in CollectivePlan.engine)
        # records the epoch it was built at and dies on mismatch — a
        # SET_TUNING can never leave a stale prepared program serving
        self.tuning_epoch = 0
        # device-interaction accounting (single-interaction dispatch):
        # shared across the gang's rank handles — one collective on the
        # fast path bumps it exactly once, whatever the world size
        self.interactions = InteractionCounter()
        # overlap plane: launched device programs park here and their
        # requests complete from the drainer's done-probe instead of on
        # the launch path — up to `window.depth` collectives per
        # communicator in flight at once (SET_INFLIGHT_WINDOW /
        # ACCL_INFLIGHT_WINDOW).  Drain points: Request.wait (per call),
        # facade flush(), barrier, config writes, soft_reset.
        self.window = InflightWindow()
        # per-GLOBAL-rank (Rank.session) health, fed by the slot watchdog:
        # a rank absent from a timed-out gang slot is "suspect"; two
        # strikes make it "dead" and collectives addressing it fail fast
        # instead of waiting out the watchdog again.  soft_reset clears it.
        self.health: Dict[int, dict] = {}
        # command-ring plane (the TPU CCLO analog): warm batched windows
        # of eligible collectives refill a device-resident slot ring and
        # execute under ONE sequencer dispatch — the host stops issuing
        # collectives and starts refilling a queue.  ACCL_CMDRING=0
        # disables; =eager also routes single warm calls through it.
        self.cmdring = GangCommandRing(self)
        # the shared tag-matched p2p channel (set by the first rank
        # handle): the fallback route for batched SEND/RECV positions
        # that did not pair into a ring slot
        self.p2p = None

    _DEAD_AFTER_TIMEOUTS = 2

    def add_health_listener(self, fn) -> None:
        """Register a health-transition listener ``fn(session, old,
        new)`` — the membership plane's hook onto the slot-watchdog
        accounting (one per rank handle; each facade records the edge
        and, under elastic membership, proposes eviction on ``dead``)."""
        listeners = getattr(self, "_health_listeners", None)
        if listeners is None:
            listeners = self._health_listeners = []
        if fn not in listeners:
            listeners.append(fn)

    def remove_health_listener(self, fn) -> None:
        """Deregister (engine deinit): the gang outlives individual
        rank handles, and a dead handle's listener must not keep
        firing — or pin the handle — for the gang's lifetime."""
        listeners = getattr(self, "_health_listeners", None)
        if listeners is not None and fn in listeners:
            listeners.remove(fn)

    def _health_note_absent(self, session: int) -> None:
        h = self.health.setdefault(
            session,
            {"state": "ok", "timeouts": 0, "failures": 0, "last_event": ""},
        )
        old = h["state"]
        h["timeouts"] += 1
        h["last_event"] = "gang_timeout"
        h["state"] = (
            "dead" if h["timeouts"] >= self._DEAD_AFTER_TIMEOUTS else "suspect"
        )
        if h["state"] != old:
            for fn in getattr(self, "_health_listeners", ()):
                try:
                    fn(session, old, h["state"])
                except Exception:  # a listener must never fail the gang
                    pass

    def dead_rank_in(self, comm: Communicator) -> Optional[int]:
        """Comm-relative rank of a member already marked dead (excluding
        the local rank), or None."""
        if not self.health:
            return None
        for i, r in enumerate(comm.ranks):
            if i == comm.local_rank:
                continue
            h = self.health.get(r.session)
            if h is not None and h["state"] == "dead":
                return i
        return None

    # -- communicator -> mesh -----------------------------------------------
    def submesh(self, comm: Communicator):
        """Sub-mesh over the communicator's member devices — rank i of the
        communicator executes on the device of its *global* rank identity
        (``Rank.session``), so a subcommunicator of ranks {4..7} runs on
        devices 4-7, not 0-3.  None when the host has fewer devices than the
        membership needs — execution falls back to host numpy, the
        single-controller analog of the reference's emulator tier."""
        sessions = tuple(r.session for r in comm.ranks)
        if sessions in self._submeshes:
            return self._submeshes[sessions]
        devs = jax.devices()
        if max(sessions) < len(devs):
            from jax.sharding import Mesh

            mesh = Mesh([devs[s] for s in sessions], (opdriver.AXIS,))
        else:
            mesh = None
        self._submeshes[sessions] = mesh
        return mesh

    # -- gang assembly -------------------------------------------------------
    def submit(self, comm: Communicator, options: CallOptions, request: Request):
        self._submit_entry(comm, (options, request))

    def submit_batch(
        self,
        comm: Communicator,
        options_list: List[CallOptions],
        requests: List[Request],
    ):
        """A whole flushed command-queue batch as ONE gang event: every
        rank of the communicator must flush a batch of the same length at
        the same point of its call sequence (the batched extension of the
        gang's SPMD ordering contract).  A fully matched batch executes
        as one fused jitted program — one device interaction for N
        queued collectives."""
        self._submit_entry(comm, (list(options_list), list(requests)))

    def _submit_entry(self, comm: Communicator, entry: tuple):
        if comm.size == 1:
            # single-member gang (the chip tier's world=1 shape): the
            # submit IS the assembled slot — no seq/slot bookkeeping, no
            # watchdog, no dead peers to screen (there are none)
            slot = _GangSlot(1, 0.0, comm)
            slot.calls[0] = entry
            self._execute(comm, slot)
            return
        with self._lock:
            dead = self.dead_rank_in(comm)
            if dead is not None:
                # fail fast: a member of this communicator is already
                # marked dead by the watchdog accounting — assembling a
                # slot would only burn the full deadline again.  No seq is
                # consumed; recovery is the collective soft_reset.
                h = dict(self.health.get(comm.ranks[dead].session, {}))
        if dead is not None:
            ctx = {
                "comm": comm.id,
                "peer": dead,
                "attempts": h.get("timeouts", 0),
                "elapsed_s": 0.0,
            }
            reqs = entry[1] if isinstance(entry[1], list) else [entry[1]]
            opts = entry[0] if isinstance(entry[0], list) else [entry[0]]
            for o, req in zip(opts, reqs):
                req.complete(
                    ErrorCode.RECEIVE_TIMEOUT,
                    context=dict(ctx, op=o.op.name),
                )
            return
        with self._lock:
            seq_key = (comm.id, comm.local_rank)
            seq = self._seq.get(seq_key, 0)
            self._seq[seq_key] = seq + 1
            slot_key = (comm.id, seq)
            slot = self._slots.get(slot_key)
            arm = False
            if slot is None:
                slot = _GangSlot(comm.size, self.timeout_s, comm=comm)
                self._slots[slot_key] = slot
                arm = True  # exactly one watchdog per slot
            slot.calls[comm.local_rank] = entry
            ready = len(slot.calls) == slot.world
            if ready:
                del self._slots[slot_key]
                if slot.watchdog is not None:
                    slot.watchdog.cancel()
        if ready:
            self._execute(comm, slot)
        elif arm:
            self._arm_watchdog(slot_key, slot)

    @staticmethod
    def _slot_requests(slot: "_GangSlot"):
        """Every request parked in a slot (batch entries hold lists)."""
        for _, req in slot.calls.values():
            if isinstance(req, list):
                yield from req
            else:
                yield req

    def soft_reset(self) -> None:
        """ref ``ACCL`` soft-reset recovery (accl.cpp:57-89): abandon all
        stale gang state so a world that lost a collective (e.g. one rank
        timed out while a peer never submitted) can realign.

        Collective by contract, like the reference's: every rank handle
        issues CONFIG/RESET with no new collectives in flight; each call
        idempotently clears the shared tables, so after the last rank's
        reset all per-communicator sequence counters restart at 0 and the
        next collective matches at a fresh slot.  Any still-parked call is
        completed with RECEIVE_TIMEOUT (its gang never assembled)."""
        # overlap plane: a FULL drain first — every launched program's
        # requests complete normally before any state is abandoned (the
        # soft_reset drain-point contract, asserted by chip_soak).
        # BOUNDED: soft_reset is the recovery path, so a wedged device
        # call must not also wedge recovery — past the bound the reset
        # proceeds and the stragglers complete (or fail) from the
        # drainer whenever their done-probe returns
        self.window.drain(drain_deadline_s(self.timeout_s))
        with self._lock:
            slots = list(self._slots.values())
            self._slots.clear()
            self._seq.clear()
            self._asm_cache.clear()
            self.health.clear()  # degradation state is part of the reset
            self.tuning_epoch += 1  # prepared plan state dies with the reset
        # command ring: park the sequencer and realign every session's
        # seqn/head at 0 (after the full window drain above — no slot
        # can still be in flight when the ring state is abandoned)
        self.cmdring.reset()
        for slot in slots:
            if slot.watchdog is not None:
                slot.watchdog.cancel()
            for req in self._slot_requests(slot):
                if not req.done():
                    req.complete(ErrorCode.RECEIVE_TIMEOUT)

    def contract_fail(self, verdict: dict) -> None:
        """Contract plane: a cross-rank divergence verdict landed for
        ``verdict["comm"]`` — complete every PARKED slot on that
        communicator with CONTRACT_VIOLATION immediately.  The detecting
        rank fails pre-dispatch at facade intake; its peers' calls are
        already parked in half-assembled slots and would otherwise
        starve until the watchdog (the hang this plane removes).
        Idempotent: every rank's verifier listener calls this once."""
        from ...contract import verdict_context

        comm_id = verdict.get("comm")
        with self._lock:
            keys = [k for k in self._slots if k[0] == comm_id]
            slots = [self._slots.pop(k) for k in keys]
        for slot in slots:
            if slot.watchdog is not None:
                slot.watchdog.cancel()
            for req in self._slot_requests(slot):
                if not req.done():
                    req.complete(
                        ErrorCode.CONTRACT_VIOLATION,
                        context=verdict_context(verdict, req.op_name),
                    )

    def dump_state(self) -> List[str]:
        """Pending-rendezvous lines for the debug dump: every parked gang
        slot (a collective some rank posted that never assembled) is a
        live resource exactly like an occupied reference rx buffer."""
        lines: List[str] = []
        with self._lock:
            for (comm_id, seq), slot in self._slots.items():
                posted = sorted(slot.calls)
                lines.append(
                    f"rxbuf gang-slot comm={comm_id} seq={seq} PENDING "
                    f"posted_ranks={posted} world={slot.world}"
                )
        return lines

    def _arm_watchdog(self, slot_key, slot: _GangSlot) -> None:
        def fire():
            with self._lock:
                live = self._slots.get(slot_key) is slot
                if live:
                    del self._slots[slot_key]
                    # health accounting: every member that never posted to
                    # this starved slot takes a strike (graceful
                    # degradation — two strikes mark it dead and later
                    # collectives fail fast)
                    absent = []
                    if slot.comm is not None:
                        for r in range(slot.world):
                            if r not in slot.calls:
                                absent.append(r)
                                self._health_note_absent(
                                    slot.comm.ranks[r].session
                                )
            if live:
                ctx = {
                    "comm": slot_key[0],
                    "peer": absent if len(absent) != 1 else absent[0],
                    "elapsed_s": round(self.timeout_s, 3),
                }
                for req in self._slot_requests(slot):
                    req.complete(
                        ErrorCode.RECEIVE_TIMEOUT,
                        context=dict(ctx, op=req.op_name),
                    )

        t = threading.Timer(max(0.01, slot.deadline - time.monotonic()), fire)
        t.daemon = True
        slot.watchdog = t
        t.start()

    # -- execution -----------------------------------------------------------
    @staticmethod
    def _sig(c: CallOptions) -> tuple:
        return (
            c.op, c.count, c.reduce_function, c.root_src, c.root_dst,
            c.compression, c.fuse, c.fuse_param,
        )

    def _execute(self, comm: Communicator, slot: _GangSlot) -> None:
        entries = [slot.calls[r] for r in range(slot.world)]
        batched = [isinstance(e[0], list) for e in entries]
        if any(batched) and not all(batched):
            # one rank flushed a batch where another posted a single call:
            # the gang sequence is torn — fail the whole slot
            for req in self._slot_requests(slot):
                req.complete(ErrorCode.INVALID_OPERATION)
            return
        if all(batched) and entries:
            self._execute_batch(comm, entries)
            return
        self._execute_calls(
            comm, [e[0] for e in entries], [e[1] for e in entries]
        )

    def _execute_calls(
        self,
        comm: Communicator,
        calls: List[CallOptions],
        reqs: List[Request],
    ) -> None:
        t0 = time.perf_counter_ns()
        lead = calls[0]
        try:
            if all(
                c.op in (Operation.SEND, Operation.RECV) for c in calls
            ):
                # a batched p2p position that did not ride the ring
                # (fallback / ring disabled mid-flight): a
                # complementary pair delivers directly, anything else
                # re-routes through the shared channel (which then owns
                # completion — None)
                code = self._execute_p2p_pair(comm, calls, reqs)
                if code is None:
                    return
            elif any(
                c.op in (Operation.SEND, Operation.RECV) for c in calls
            ):
                # a position mixing p2p with a collective is a torn
                # gang (SPMD divergence): fail fast — the channel must
                # not be fed a collective call dressed as a recv
                code = ErrorCode.INVALID_OPERATION
            elif any(self._sig(c) != self._sig(lead) for c in calls[1:]):
                code = ErrorCode.INVALID_OPERATION  # mismatched gang calls
            elif lead.fuse:
                # a fused call that missed the ring: its operand is
                # PACKED for the slot (grads ‖ param tail, kv ‖ q), so
                # the plain base op would compute the wrong thing —
                # decompose with the host reference semantics instead
                # (counted on the ring's fallback table)
                with jax.profiler.TraceAnnotation(
                    f"accl::fused{int(lead.fuse)}_decomposed"
                ):
                    code = self._execute_fused_decomposed(comm, calls)
            else:
                # named range in the xprof timeline (the per-call span the
                # reference's perf counter provides, SURVEY §5 tracing)
                with jax.profiler.TraceAnnotation(
                    f"accl::{lead.op.name.lower()}"
                ):
                    code = self._run_op(comm, calls, lead, reqs, t0)
        except Exception:
            import traceback

            traceback.print_exc()
            code = ErrorCode.INVALID_OPERATION
        if code is IN_FLIGHT:
            # overlap plane: completion was handed to the in-flight
            # window — the drainer completes these requests from the
            # device done-probe, in launch order
            return
        # per-communicator ordering fence: an inline completion (host-path
        # collectives, gang-mismatch failures) must not overtake earlier
        # launched-but-incomplete device calls of this communicator — the
        # window's launch-order contract.  Bounded like every drain point:
        # a wedged earlier call must not also wedge this completion
        self.window.drain_key(comm.id, drain_deadline_s(self.timeout_s))
        dt = time.perf_counter_ns() - t0
        for req in reqs:
            req.complete(code, dt)

    def _execute_p2p_pair(self, comm: Communicator,
                          calls: List[CallOptions],
                          reqs: List[Request]) -> Optional[ErrorCode]:
        """A batched p2p position that did not ride the ring.  A
        complementary SEND/RECV pair delivers directly — the slot IS
        the rendezvous (both sides posted at the same batch position):
        the device fabric hop for device-resident ends, a host write
        otherwise.  Any other shape (the classic cross-exchange where
        both ranks batch ``[send, recv]`` and positions pair ACROSS
        slots, or pairs with mismatched tags) routes each call through
        the shared p2p channel exactly as an unbatched call would —
        tag matching across positions keeps working.  Returns None
        when the calls were handed to the channel (it owns their
        completion)."""
        from ...cmdring import complementary_pair

        # THE pair definition, shared with the ring planner (_plan_p2p):
        # a dtype-mismatched or compressed position is not a match on
        # either path — it rides the channel, whose cast-on-deliver /
        # wire-cast semantics the unbatched path already has
        pair = complementary_pair(calls)
        if pair is not None:
            src, dst = pair
            snd, rcv = calls[src], calls[dst]
            if rcv.res is not None and not rcv.res.is_dummy:
                n = snd.count
                ic = self.interactions
                res = rcv.res
                op0 = snd.op0
                if isinstance(op0, DeviceBuffer) and isinstance(
                    res, DeviceBuffer
                ):
                    payload = _trim_program(n, op0.device)(
                        op0.device_array()
                    )
                    ic.bump()  # the payload-copy program
                    _p2p_device_deliver(payload, res, n, ic)
                else:
                    row = np.asarray(op0.device_view()[:n])
                    _write_host_result(res, row, n, ic)
                return ErrorCode.OK
        if self.p2p is None:  # pragma: no cover - engines always set it
            return ErrorCode.INVALID_OPERATION
        for r, (call, req) in enumerate(zip(calls, reqs)):
            self._route_p2p_channel(comm, r, call, req)
        return None

    def _execute_fused_decomposed(
        self, comm: Communicator, calls: List[CallOptions]
    ) -> ErrorCode:
        """Host-reference execution of a fused call that fell off the
        ring.  The operand is packed for the slot, so the plain base op
        has no correct off-ring spelling; the decomposition computes
        the fused semantics itself — the shared width/epilogue
        definitions from :mod:`accl_tpu.cmdring`, in numpy — and counts
        the miss as a ``fused_decomposed`` ring fallback.  Correctness
        over speed: the warm path is the ring slot."""
        from ...cmdring import ring_widths
        from ...constants import FusedCompute, ReduceFunction

        lead = calls[0]
        size = len(calls)
        try:
            fuse = FusedCompute(int(lead.fuse))
        except ValueError:
            return ErrorCode.INVALID_OPERATION
        n = int(lead.count)
        if n <= 0 or fuse == FusedCompute.NONE:
            return ErrorCode.INVALID_OPERATION
        in_w, _ = ring_widths(lead.op, n, size, fuse=fuse)
        rows = []
        for c in calls:
            if c.op0 is None or c.op0.is_dummy:
                return ErrorCode.INVALID_OPERATION
            view = np.asarray(c.op0.device_view())
            if view.shape[0] < in_w:
                return ErrorCode.INVALID_OPERATION
            # copy: the result write below may alias the operand
            rows.append(view[:in_w].copy())
        self.cmdring.note_fallback("fused_decomposed")
        fp = float(lead.fuse_param)
        outs = []
        if fuse == FusedCompute.ATTN_HOP:
            from ...ops.pallas.ring import hop_source

            hop = int(lead.root_src)
            for r in range(size):
                src = hop_source(r, hop, size)
                outs.append(fp * (rows[r][n:2 * n] * rows[src][:n]))
        else:
            stack = np.stack([row[: n * size] for row in rows])
            if lead.reduce_function == ReduceFunction.MAX:
                reduced = stack.max(axis=0)
            else:
                reduced = stack.sum(axis=0)
            for r in range(size):
                chunk = reduced[r * n:(r + 1) * n]
                if fuse == FusedCompute.MATMUL_RS:
                    outs.append(fp * chunk)
                else:  # APPLY: param tail minus the scaled reduced chunk
                    outs.append(
                        rows[r][size * n:(size + 1) * n] - fp * chunk
                    )
        for r, c in enumerate(calls):
            if c.res is not None and not c.res.is_dummy:
                _write_host_result(c.res, outs[r], n, self.interactions)
        return ErrorCode.OK

    def _route_p2p_channel(self, comm: Communicator, rank: int,
                           call: CallOptions, req: Request) -> None:
        """Post one gang-assembled SEND/RECV onto the shared tag-matched
        channel (the unbatched path's machinery, minus streams — stream
        p2p is never gang-eligible)."""
        ic = self.interactions
        me_world = comm.ranks[rank].session
        if call.op == Operation.SEND:
            cfg = call.arithcfg
            if isinstance(call.op0, DeviceBuffer):
                payload = _trim_program(call.count, call.op0.device)(
                    call.op0.device_array()
                )
                ic.bump()  # the payload-copy program
                if call.compression & CompressionFlags.ETH_COMPRESSED:
                    # compress lane on the sending chip (the unbatched
                    # path's wire-cast discipline, _start_send)
                    payload = _cast_program(
                        dtype_to_numpy(cfg.compressed), call.op0.device
                    )(payload)
                    ic.bump()
            else:
                payload = np.asarray(
                    call.op0.device_view()[: call.count]
                ).copy()
                if call.compression & CompressionFlags.ETH_COMPRESSED:
                    payload = payload.astype(
                        dtype_to_numpy(cfg.compressed)
                    )
            dst_world = comm.ranks[call.root_dst].session
            key = (comm.id, call.tag, me_world, dst_world)
            self.p2p.post_send(key, payload, req,
                               timeout_s=self.timeout_s)
            return
        src_world = comm.ranks[call.root_src].session
        key = (comm.id, call.tag, src_world, me_world)

        def sink(payload, call=call, ic=ic):
            if isinstance(payload, jax.Array) and isinstance(
                call.res, DeviceBuffer
            ):
                _p2p_device_deliver(payload, call.res, call.count, ic)
                return
            if isinstance(payload, jax.Array):
                payload = np.asarray(payload)
            _write_host_result(call.res, payload, call.count, ic)

        self.p2p.post_recv(key, sink, req, timeout_s=self.timeout_s)

    # -- batched execution ---------------------------------------------------
    _BATCH_TUNING_KEYS = (
        "allreduce_algorithm", "reduce_algorithm", "bcast_algorithm",
        "scatter_algorithm", "gather_algorithm",
    )

    def _execute_batch(self, comm: Communicator, entries: List[tuple]) -> None:
        """Execute a fully matched batch slot: ``entries[r]`` is rank r's
        ``(options_list, requests_list)``.  The whole batch runs as ONE
        fused jitted program when every position qualifies for the
        zero-host-copy device path; otherwise each position executes in
        order through the ordinary per-call machinery (still correct,
        just not single-interaction)."""
        lens = {len(e[0]) for e in entries}
        if lens != {len(entries[0][0])}:
            for _, batch_reqs in entries:
                for req in batch_reqs:
                    req.complete(ErrorCode.INVALID_OPERATION)
            return
        npos = len(entries[0][0])
        try:
            # command-ring fast path first: a warm window of eligible
            # collectives becomes slot refills + ONE sequencer dispatch
            # (planning is side-effect-free; True means the ring owns
            # request completion).  Ineligible batches fall through to
            # the fused program, then the sequential path.
            handled = self.cmdring.run_batch(comm, entries, npos)
        except Exception:
            import traceback

            traceback.print_exc()
            handled = False
        if handled:
            return
        try:
            # planning is side-effect-free: a False return means "not
            # fusable", safe to fall back; once dispatch has begun,
            # _run_batch_fused owns request completion (True) so the
            # sequential path can never double-execute a position
            handled = self._run_batch_fused(comm, entries, npos)
        except Exception:
            import traceback

            traceback.print_exc()
            handled = False
        if handled:
            return
        for i in range(npos):
            self._execute_calls(
                comm,
                [e[0][i] for e in entries],
                [e[1][i] for e in entries],
            )

    def _run_batch_fused(
        self, comm: Communicator, entries: List[tuple], npos: int
    ) -> bool:
        """Try to run the whole batch as one fused device program (one
        device interaction for N collectives).  Returns False — having
        dispatched nothing — when any position disqualifies: non-default
        tuning algorithms (the fused program composes the plain XLA
        lowerings), host/mixed operands, streams, or a gang signature
        mismatch at any position (that position must surface its error
        through the sequential path)."""
        mesh = self.submesh(comm)
        if mesh is None or npos == 0:
            return False
        if any(
            self.tuning.get(k, "xla") != "xla" for k in self._BATCH_TUNING_KEYS
        ):
            return False
        # per-call TuningPlan overlays selecting a non-XLA lowering also
        # disqualify fusion (the fused program composes plain XLA bodies)
        for options_list, _ in entries:
            for c in options_list:
                if c.tuning and any(
                    c.tuning.get(k, "xla") != "xla"
                    for k in self._BATCH_TUNING_KEYS
                ):
                    return False
        plans = []
        written: set = set()  # result-buffer roots of earlier positions
        for i in range(npos):
            calls = [e[0][i] for e in entries]
            lead = calls[0]
            if any(self._sig(c) != self._sig(lead) for c in calls[1:]):
                return False
            if lead.fuse:
                # fused positions never run the plain lowerings (the
                # packed operand layout differs) — the sequential path
                # decomposes them with the host reference
                return False
            # (_plan_device_call also enforces the BCAST op0-is-res form)
            plan = self._plan_device_call(comm, calls, lead, mesh)
            if plan is None:
                return False
            # data-dependency guard: all positions' operands are
            # assembled BEFORE the single fused dispatch, so a position
            # reading a buffer an earlier position writes would see the
            # PRE-batch bytes — only the sequential path orders such
            # chains; reject fusion (the in-place op0-is-res form of one
            # position is fine: its own read/write is inside one op)
            for call in calls:
                buf = call.op0
                if (
                    buf is not None
                    and not buf.is_dummy
                    and id(buf._root()) in written
                ):
                    return False
            for r in plan["writers"]:
                res = calls[r].res
                if res is not None and not res.is_dummy:
                    written.add(id(res._root()))
            plans.append((calls, lead, plan))

        t0 = time.perf_counter_ns()
        try:
            return self._dispatch_batch_fused(comm, entries, plans, mesh, t0)
        except Exception:
            # dispatch/adoption failed mid-batch: requests already
            # completed stay completed; the rest fail — NEVER fall back
            # to sequential re-execution (a collective must not run twice)
            import traceback

            traceback.print_exc()
            dt = time.perf_counter_ns() - t0
            for _, batch_reqs in entries:
                for req in batch_reqs:
                    if not req.done():  # side-effect-free engine probe
                        req.complete(ErrorCode.INVALID_OPERATION, dt)
            return True

    def _dispatch_batch_fused(
        self, comm: Communicator, entries, plans, mesh, t0
    ) -> bool:
        globals_ = []
        specs = []
        for calls, lead, plan in plans:
            global_arr, prep, _ = self._assemble_flat(calls, plan, mesh)
            globals_.append(global_arr)
            op = plan["op"]
            fn = lead.reduce_function
            wire_name = (
                np.dtype(plan["wire_npdt"]).name
                if plan["wire_npdt"] is not None
                else None
            )
            if op == Operation.ALLREDUCE:
                if wire_name is not None:
                    specs.append(
                        ("compressed_allreduce", fn, wire_name, prep, True)
                    )
                else:
                    specs.append(("allreduce", fn, None, prep, True))
            elif op == Operation.REDUCE:
                specs.append(("reduce", fn, lead.root_dst, prep, True))
            elif op == Operation.BCAST:
                # non-donating inside a batch: the operand may back other
                # positions' shards of the same fused program
                specs.append(("bcast", fn, lead.root_src, prep, True))
            elif op == Operation.SCATTER:
                specs.append(("scatter", fn, lead.root_src, prep, True))
            elif op == Operation.GATHER:
                specs.append(("gather", fn, lead.root_src, prep, True))
            elif op == Operation.ALLGATHER:
                specs.append(("allgather", fn, None, prep, True))
            elif op == Operation.REDUCE_SCATTER:
                specs.append(("reduce_scatter", fn, None, prep, True))
            elif op == Operation.ALLTOALL:
                specs.append(("alltoall", fn, None, prep, True))
            else:  # pragma: no cover - _plan_device_call gates on IN_W
                return False

        self.interactions.bump()  # ONE dispatch for the whole batch
        with jax.profiler.TraceAnnotation(f"accl::batch[{len(plans)}]"):
            outs = opdriver.run_batch(globals_, mesh, specs)
        all_reqs: List[Request] = []
        for i, (calls, lead, plan) in enumerate(plans):
            reqs = [e[1][i] for e in entries]
            self._adopt_out_shards(outs[i], calls, plan, reqs)
            all_reqs.extend(reqs)
        # the fused batch rides the in-flight window as ONE entry: all
        # positions came out of one program, so they become ready (and
        # complete) together, from the drainer's done-probe
        self._park_inflight(comm, outs, all_reqs, t0)
        return True

    def _run_op(
        self,
        comm: Communicator,
        calls: List[CallOptions],
        lead: CallOptions,
        reqs: Optional[List[Request]] = None,
        t0: Optional[int] = None,
    ) -> ErrorCode:
        if lead.op == Operation.BARRIER:
            # gang assembly IS the barrier on this tier: reaching here means
            # every rank of the communicator posted the call in this process.
            # A multi-process gang must NOT reuse this (see backends/dist for
            # the cross-process barrier over the device mesh).  The barrier
            # is also an overlap drain point: no rank may observe it pass
            # while an earlier collective of ITS communicator is still in
            # flight — and a wedged one fails the barrier within the
            # engine deadline instead of hanging it.  Per-key, matching
            # the window's keys-drain-independently contract: a wedged
            # UNRELATED communicator must not fail this barrier.
            if not self.window.drain_key(
                comm.id, drain_deadline_s(self.timeout_s)
            ):
                return ErrorCode.RECEIVE_TIMEOUT
            return ErrorCode.OK
        mesh = self.submesh(comm)
        if mesh is not None:
            code = self._run_op_device(comm, calls, lead, mesh, reqs, t0)
            if code is not None:
                return code
        return self._run_op_host(comm, calls, lead, mesh)

    # -- overlap plane --------------------------------------------------------
    def _park_inflight(self, comm, out, reqs, t0):
        """Hand a dispatched device call's completion to the in-flight
        window: the launch path returns immediately (result adoption has
        already been wired — pointer swaps done, writebacks deferred)
        and the drainer completes the requests when the device future
        resolves.  Falls back to inline completion when there are no
        requests to decouple."""
        if reqs is None:
            jax.block_until_ready(out)
            return ErrorCode.OK
        if t0 is None:
            t0 = time.perf_counter_ns()

        def waiter(out=out):
            jax.block_until_ready(out)

        def on_ready(overlap_ns, depth, ready_ns, reqs=reqs, t0=t0):
            dt = max(ready_ns - t0, 1)
            for req in reqs:
                # overlap_ns is 0 when nothing overlapped this call (a
                # lone sync call riding the window hid no device time) —
                # record None so telemetry never over-credits the window
                req.overlap_ns = overlap_ns or None
                req.inflight_depth = depth
                req.complete(ErrorCode.OK, dt)

        def on_error(exc, reqs=reqs, t0=t0, comm_id=comm.id):
            # a device-side failure surfaces on every request of the
            # launch, with the failure context the flight recorder and
            # ACCLError.details carry
            dt = max(time.perf_counter_ns() - t0, 1)
            ctx = {
                "comm": comm_id,
                "error": f"{type(exc).__name__}: {exc}"[:300],
            }
            for req in reqs:
                if not req.done():  # side-effect-free engine probe
                    req.complete(
                        ErrorCode.INVALID_OPERATION, dt,
                        context=dict(ctx, op=req.op_name),
                    )

        self.window.park(comm.id, waiter, on_ready, on_error)
        return IN_FLIGHT

    # -- zero-host-copy device path ------------------------------------------
    def _plan_device_call(
        self,
        comm: Communicator,
        calls: List[CallOptions],
        lead: CallOptions,
        mesh,
    ) -> Optional[dict]:
        """Validate a gang call for the zero-host-copy path BEFORE any
        device work; returns the call plan, or None to fall back to the
        host-staged path (mixed/host operands, exotic dtypes)."""
        op = lead.op
        if op not in IN_W:
            return None
        size = comm.size
        n = lead.count
        if n <= 0:
            return None
        in_w = n * (size if IN_W[op] == "P" else 1)
        out_w = n * (size if OUT_W[op] == "P" else 1)
        devs = list(mesh.devices.flat)
        npdt = dtype_to_numpy(lead.arithcfg.uncompressed)
        compressed = bool(lead.compression & CompressionFlags.ETH_COMPRESSED)
        wire_npdt = (
            dtype_to_numpy(lead.arithcfg.compressed) if compressed else None
        )

        # which ranks' results get written
        if op in (Operation.REDUCE, Operation.GATHER):
            writers = {lead.root_dst if op == Operation.REDUCE else lead.root_src}
        else:
            writers = set(range(size))

        # validate operands + results device-resident before any work
        any_device = False
        for r, call in enumerate(calls):
            buf = call.op0
            if buf is not None and not buf.is_dummy:
                if not (
                    isinstance(buf, DeviceBuffer)
                    and buf.device == devs[r]
                    and buf.count >= in_w
                    and dtype_to_numpy(buf.dtype) == npdt
                ):
                    return None
                any_device = True
            if r in writers:
                res = call.res
                if res is None or res.is_dummy:
                    continue
                if not (
                    isinstance(res, DeviceBuffer)
                    and res.device == devs[r]
                    and res.count >= out_w
                    and dtype_to_numpy(res.dtype) == npdt
                ):
                    return None
        if not any_device:
            return None
        if op == Operation.BCAST and any(
            c.op0 is not c.res for c in calls
        ):
            # the device bcast program runs in-place (facade contract:
            # op0 IS res on every rank); other shapes stage via the host
            return None
        return {
            "op": op, "size": size, "n": n, "in_w": in_w, "out_w": out_w,
            "devs": devs, "npdt": npdt, "compressed": compressed,
            "wire_npdt": wire_npdt, "writers": writers,
        }

    def _assemble_flat(self, calls, plan, mesh) -> tuple:
        """Assemble the flat 1-D global for a planned device call with as
        few device interactions as possible.

        Preferred mode (single-interaction dispatch): every rank's shard
        is its RAW committed HBM array — zero-copy, zero dispatch — at
        the operands' uniform width ``w >= in_w``; the slice down to the
        call width and the wire-dtype rounding lane are FUSED into the
        collective program itself (``prep``), so operand staging never
        costs a separate device interaction.  Falls back to per-rank prep
        programs (one dispatch each) only for mixed widths.

        Returns ``(global_arr, prep, raw_bufs)`` where ``prep`` is the
        (take_w, wire_name) spec for the fused program and ``raw_bufs``
        is the cache-key buffer list (None when not cacheable).
        """
        from jax.sharding import NamedSharding, PartitionSpec

        ic = self.interactions
        op, size, in_w = plan["op"], plan["size"], plan["in_w"]
        devs, npdt = plan["devs"], plan["npdt"]
        wire_name = (
            np.dtype(plan["wire_npdt"]).name
            if plan["wire_npdt"] is not None and op != Operation.ALLREDUCE
            else None
        )

        arrs = []
        for call in calls:
            buf = call.op0
            if buf is None or buf.is_dummy:
                arrs.append(None)
            else:
                if buf._parent is not None:
                    ic.bump()  # child view: slice program dispatch
                arrs.append(buf.device_array())
        widths = {a.shape[0] for a in arrs if a is not None}
        uniform_w = widths.pop() if len(widths) == 1 else None

        shards = []
        raw_bufs: Optional[list] = []  # root buffers whose _dev went in raw
        if uniform_w is not None and uniform_w >= in_w:
            w = uniform_w
            prep = (
                (in_w, wire_name)
                if (w != in_w or wire_name is not None)
                else None
            )
            for r, (call, arr) in enumerate(zip(calls, arrs)):
                if arr is None:
                    ic.bump()  # on-device zeros for the dummy operand
                    shards.append(_dev_zeros((w,), npdt, devs[r]))
                    raw_bufs = None
                    continue
                shards.append(arr)
                buf = call.op0
                if raw_bufs is not None and buf._parent is None:
                    raw_bufs.append(buf)
                elif buf._parent is not None:
                    raw_bufs = None
        else:
            # mixed widths: per-rank prep programs to the exact call
            # width (one dispatch each — the legacy staging cost)
            w = in_w
            prep = None
            raw_bufs = None
            for r, (call, arr) in enumerate(zip(calls, arrs)):
                if arr is None:
                    ic.bump()
                    shards.append(_dev_zeros((in_w,), npdt, devs[r]))
                else:
                    ic.bump()
                    shards.append(
                        _prep_program(in_w, wire_name, devs[r], True)(arr)
                    )

        # assembled-global reuse: keyed by the BUFFER identities (stable
        # across in-place loops, unlike shard ids), re-validated against
        # each buffer's current _dev; a stale entry is REPLACED under its
        # key, so repeated in-place calls can't accumulate dead entries.
        # Buffers are held by WEAKREF with eviction callbacks — the cached
        # global (which pins every shard's HBM) dies with its buffers, so
        # the cache never outlives what the application released.
        # Donating ops (bcast) bypass the cache entirely.
        cacheable = raw_bufs is not None and op != Operation.BCAST
        global_arr = None
        key = None
        if cacheable:
            key = (tuple(map(id, raw_bufs)), w)
            global_arr = self._asm_lookup(key, raw_bufs)
        if global_arr is None:
            global_arr = jax.make_array_from_single_device_arrays(
                (size * w,),
                NamedSharding(mesh, PartitionSpec(opdriver.AXIS)),
                shards,
            )
            if cacheable:
                self._asm_store(key, global_arr, shards, raw_bufs)
        return global_arr, prep, raw_bufs

    def _asm_lookup(self, key, raw_bufs):
        """Assembled-global cache hit, re-validated against the buffers'
        live identity AND their current committed arrays (see the cache
        notes in _assemble_flat); None on miss/stale."""
        hit = self._asm_cache.get(key)
        if hit is None:
            return None
        hit_bufs = [ref() for ref in hit[2]]
        if all(b is hb for b, hb in zip(raw_bufs, hit_bufs)) and all(
            s is b._dev for s, b in zip(hit[1], raw_bufs)
        ):
            return hit[0]
        return None

    def _asm_store(self, key, global_arr, shards, raw_bufs) -> None:
        if len(self._asm_cache) >= 64 and key not in self._asm_cache:
            self._asm_cache.clear()

        def _evict(_ref, cache=self._asm_cache, key=key):
            cache.pop(key, None)

        self._asm_cache[key] = (
            global_arr,
            shards,
            [weakref.ref(b, _evict) for b in raw_bufs],
        )

    def _run_op_device_prepared(
        self,
        calls: List[CallOptions],
        lead: CallOptions,
        state: dict,
        reqs: Optional[List[Request]] = None,
        t0: Optional[int] = None,
    ) -> Optional[ErrorCode]:
        """The warm path of a planned gang collective: the template,
        sharding, adoption map and jitted program handle all come out of
        the CollectivePlan's prepared state — per call only the operand
        buffers are validated, the global assembled, and the ONE program
        dispatched.  Returns None to fall back to the full path (operand
        shape drift, dummy/view operands, host buffers)."""
        tmpl = state["tmpl"]
        devs, npdt, in_w = tmpl["devs"], tmpl["npdt"], tmpl["in_w"]
        shards = []
        raw_bufs = []
        w = None
        for r, call in enumerate(calls):
            buf = call.op0
            if (
                buf is None
                or not isinstance(buf, DeviceBuffer)
                or buf.is_dummy
                or buf._parent is not None
                or buf.device != devs[r]
                or dtype_to_numpy(buf.dtype) != npdt
            ):
                return None
            arr = buf.device_array()
            aw = arr.shape[0]
            if w is None:
                w = aw
            elif aw != w:
                return None
            shards.append(arr)
            raw_bufs.append(buf)
        if w < in_w:
            return None
        out_w = tmpl["out_w"]
        for r in tmpl["writers"]:
            res = calls[r].res
            if res is None or res.is_dummy:
                continue
            if not (
                isinstance(res, DeviceBuffer)
                and res.device == devs[r]
                and res.count >= out_w
                and dtype_to_numpy(res.dtype) == npdt
            ):
                return None

        key = (tuple(map(id, raw_bufs)), w)
        global_arr = self._asm_lookup(key, raw_bufs)
        if global_arr is None:
            global_arr = jax.make_array_from_single_device_arrays(
                (tmpl["size"] * w,), state["sharding"], shards
            )
            self._asm_store(key, global_arr, shards, raw_bufs)

        prog = state["programs"].get(w)
        if prog is None:
            wire_name = (
                np.dtype(tmpl["wire_npdt"]).name
                if tmpl["wire_npdt"] is not None
                and tmpl["op"] != Operation.ALLREDUCE
                else None
            )
            prep = (
                (in_w, wire_name)
                if (w != in_w or wire_name is not None)
                else None
            )
            name, extra = resolve_lowering(
                tmpl["op"], lead,
                effective_tuning(self.tuning, lead),
                tmpl["wire_npdt"] if tmpl["compressed"] else None,
            )
            prog = opdriver.prepare(
                name, state["mesh"], lead.reduce_function, extra, prep
            )
            state["programs"][w] = prog

        self.interactions.bump()  # THE dispatch: one prepared program
        out = prog(global_arr)
        self._adopt_out_shards(
            out, calls, tmpl, reqs, state["dev_to_rank"]
        )
        return self._park_inflight(lead.comm, out, reqs, t0)

    def _adopt_out_shards(self, out, calls, plan, reqs,
                          dev_to_rank=None) -> None:
        """Place output shards into result buffers.  Exact-width root
        buffers adopt by pointer swap (free); anything needing a
        writeback/trim program is parked as a LAZY store — the request
        materializes it on wait()/test(), and any direct buffer access
        resolves it first — so fire-and-forget chains never pay the
        result-side device interaction at dispatch time."""
        devs, writers, out_w = plan["devs"], plan["writers"], plan["out_w"]
        if dev_to_rank is None:
            dev_to_rank = {d: r for r, d in enumerate(devs)}
        for shard in out.addressable_shards:
            r = dev_to_rank.get(shard.device)
            if r is None or r not in writers:
                continue
            res = calls[r].res
            if res is None or res.is_dummy:
                continue
            sd = shard.data
            if res._parent is None and res.count == out_w:
                res.store(sd, out_w)  # pointer swap — no device program
                continue

            def adopt(sd=sd, res=res, out_w=out_w, ic=self.interactions):
                if res.store(sd, out_w):
                    ic.bump()  # the deferred writeback program

            res.defer_store(adopt)
            if reqs is not None:
                reqs[r].defer_result(res.resolve_pending, handle=sd)

    def _run_op_device(
        self,
        comm: Communicator,
        calls: List[CallOptions],
        lead: CallOptions,
        mesh,
        reqs: Optional[List[Request]] = None,
        t0: Optional[int] = None,
    ) -> Optional[ErrorCode]:
        """Run the collective entirely on device-resident operands.

        Every rank's operand must be a :class:`DeviceBuffer` committed to
        that rank's mesh device (dummies become on-device zeros); the
        per-rank arrays are assembled into ONE sharded global array with
        ``jax.make_array_from_single_device_arrays`` — zero copy — the
        jitted shard_map program (with operand staging FUSED in, see
        ``_assemble_flat``) runs over the mesh, and the output shards are
        adopted back into the result buffers lazily.  The host never
        touches payload bytes, matching the reference's device-to-device
        hot path (``accl.cpp:780-826``), and the whole call is ONE device
        interaction — the reference's one-hostctrl-command-per-collective
        discipline.  Returns None to fall back to the host-staged path.
        """
        # command-ring eager mode (ACCL_CMDRING=eager): a single warm
        # eligible call rides a one-slot refill window — the `ring` fast
        # path beside the prepared-plan path.  Default mode keeps single
        # calls on the prepared path (a one-slot window amortizes
        # nothing) and reserves the ring for batched windows.
        if (
            self.cmdring.eager
            and reqs is not None
            and self.cmdring.supports(lead.op)
        ):
            entries = [
                ([calls[r]], [reqs[r]]) for r in range(len(calls))
            ]
            if self.cmdring.run_batch(comm, entries, 1, t0=t0):
                return IN_FLIGHT
        fp = lead.plan
        fast_eligible = fp is not None and lead.op in _FAST_OPS
        if fast_eligible:
            # prepared state is keyed by the exact COUNT: the owning
            # plan is bucket-keyed, and alternating counts within one
            # bucket must each keep their own template instead of
            # thrashing a single slot
            states = fp.engine.get("gang")
            state = states.get(lead.count) if states else None
            if (
                state is not None
                and state["mesh"] is mesh
                and state["tuning_epoch"] == self.tuning_epoch
            ):
                code = self._run_op_device_prepared(
                    calls, lead, state, reqs, t0
                )
                if code is not None:
                    return code
        plan = self._plan_device_call(comm, calls, lead, mesh)
        if plan is None:
            return None
        if fast_eligible:
            # park the prepared state on the facade's CollectivePlan: the
            # next warm call on this plan skips re-validation, sharding
            # construction and program-cache hashing entirely
            from jax.sharding import NamedSharding, PartitionSpec

            states = fp.engine.setdefault("gang", {})
            if len(states) > 8 and lead.count not in states:
                states.clear()  # pathological count churn within a bucket
            states[lead.count] = {
                "tmpl": plan,
                "mesh": mesh,
                "tuning_epoch": self.tuning_epoch,
                "sharding": NamedSharding(
                    mesh, PartitionSpec(opdriver.AXIS)
                ),
                "dev_to_rank": {
                    d: r for r, d in enumerate(plan["devs"])
                },
                "programs": {},
            }
        op = plan["op"]
        global_arr, prep, raw_bufs = self._assemble_flat(calls, plan, mesh)

        fn = lead.reduce_function
        self.interactions.bump()  # THE dispatch: one fused program
        if op == Operation.ALLREDUCE:
            wire = lead.arithcfg.compressed if plan["compressed"] else None
            # allreduce keeps its wire lane inside its own program (a
            # single rounding); prep carries only the width slice here
            # (_assemble_flat never sets a prep wire for allreduce)
            out = self._allreduce(
                global_arr, mesh, fn, wire, prep=prep,
                tuning=effective_tuning(self.tuning, lead),
            )
        elif op in (
            Operation.REDUCE, Operation.BCAST, Operation.SCATTER,
            Operation.GATHER,
        ):
            donate = op == Operation.BCAST and prep is None
            if donate:
                # The donating bcast consumes shard arrays that may also
                # back cached assembled globals from earlier ops on the
                # same buffers.  JAX copy-on-donate keeps those entries
                # readable, but evict them anyway so no cache hit can ever
                # observe a donated (possibly aliased) array.
                donors = {
                    id(c.op0) for c in calls
                    if c.op0 is not None and not c.op0.is_dummy
                }
                stale = [
                    k for k, v in self._asm_cache.items()
                    if any(id(ref()) in donors for ref in v[2])
                ]
                for k in stale:
                    self._asm_cache.pop(k, None)
            out = self._run_rooted(
                op, global_arr, mesh, lead, donate=donate, prep=prep
            )
        elif op == Operation.ALLGATHER:
            out = opdriver.run_allgather(global_arr, mesh, prep=prep)
        elif op == Operation.REDUCE_SCATTER:
            out = opdriver.run_reduce_scatter(global_arr, mesh, fn, prep=prep)
        elif op == Operation.ALLTOALL:
            out = opdriver.run_alltoall(global_arr, mesh, prep=prep)
        else:  # pragma: no cover - guarded by IN_W
            return None

        self._adopt_out_shards(out, calls, plan, reqs)
        return self._park_inflight(comm, out, reqs, t0)

    def _run_rooted(self, op, global_arr, mesh, lead, donate=False,
                    prep=None):
        return run_rooted_with_tuning(
            op, global_arr, mesh, lead, effective_tuning(self.tuning, lead),
            donate=donate, prep=prep,
        )

    # -- host-staged fallback path -------------------------------------------
    def _run_op_host(
        self,
        comm: Communicator,
        calls: List[CallOptions],
        lead: CallOptions,
        mesh,
    ) -> ErrorCode:
        op = lead.op
        size = comm.size
        fn = lead.reduce_function
        n = lead.count
        compressed = bool(lead.compression & CompressionFlags.ETH_COMPRESSED)
        wire_npdt = (
            dtype_to_numpy(lead.arithcfg.compressed) if compressed else None
        )

        def wire_cast(arr: np.ndarray) -> np.ndarray:
            if wire_npdt is None:
                return arr
            # the shared host codec, per contribution row with each
            # rank's mixed seed (rows ARE the per-rank contributions
            # on this host-staged path, so the rounding matches what
            # the fabric tiers — and the facade's EF residual
            # accounting — compute for the same call)
            from ... import wire as wirecodec

            base_seed = getattr(lead, "wire_seed", 0)
            return np.stack([
                wirecodec.roundtrip(
                    row, lead.arithcfg.compressed,
                    wirecodec.rank_seed(base_seed, r),
                ).astype(arr.dtype)
                for r, row in enumerate(arr)
            ])

        ic = self.interactions
        if op == Operation.ALLREDUCE:
            # no host-side pre-cast here: the compressed program casts to the
            # requested wire dtype itself (single rounding, on device)
            stacked = _np_stack_op0(calls, [n] * size, ic)
            wire = lead.arithcfg.compressed if compressed else None
            out = self._allreduce(
                stacked, mesh, fn, wire,
                tuning=effective_tuning(self.tuning, lead),
            )
            out = np.asarray(out)
            for r, call in enumerate(calls):
                _write_host_result(call.res, out[r], n, ic)
            return ErrorCode.OK

        if op == Operation.REDUCE:
            stacked = wire_cast(_np_stack_op0(calls, [n] * size, ic))
            out = np.asarray(
                self._run_rooted(op, stacked, mesh, lead)
                if mesh is not None
                else self._host_reduce(stacked, fn)[None].repeat(size, 0)
            )
            root = lead.root_dst
            res = calls[root].res
            if res is not None and not res.is_dummy:
                _write_host_result(res, out[root], n, ic)
            return ErrorCode.OK

        if op == Operation.BCAST:
            stacked = wire_cast(_np_stack_op0(calls, [n] * size, ic))
            out = np.asarray(
                self._run_rooted(op, stacked, mesh, lead)
                if mesh is not None
                else stacked[lead.root_src][None].repeat(size, 0)
            )
            for r, call in enumerate(calls):
                _write_host_result(call.res, out[r], n, ic)
            return ErrorCode.OK

        if op == Operation.ALLGATHER:
            stacked = wire_cast(_np_stack_op0(calls, [n] * size, ic))
            out = np.asarray(
                opdriver.run_allgather(stacked, mesh)
                if mesh is not None
                else stacked.reshape(-1)[None].repeat(size, 0)
            )
            for r, call in enumerate(calls):
                _write_host_result(call.res, out[r], size * n, ic)
            return ErrorCode.OK

        if op == Operation.REDUCE_SCATTER:
            stacked = wire_cast(_np_stack_op0(calls, [size * n] * size, ic))
            out = np.asarray(
                opdriver.run_reduce_scatter(stacked, mesh, fn)
                if mesh is not None
                else self._host_reduce(stacked, fn).reshape(size, n)
            )
            for r, call in enumerate(calls):
                _write_host_result(call.res, out[r][:n], n, ic)
            return ErrorCode.OK

        if op == Operation.SCATTER:
            root = lead.root_src
            stacked = wire_cast(_np_stack_op0(calls, [size * n] * size, ic))
            out = np.asarray(
                self._run_rooted(op, stacked, mesh, lead)
                if mesh is not None
                else stacked[root].reshape(size, n)
            )
            for r, call in enumerate(calls):
                _write_host_result(call.res, out[r], n, ic)
            return ErrorCode.OK

        if op == Operation.GATHER:
            root = lead.root_src
            stacked = wire_cast(_np_stack_op0(calls, [n] * size, ic))
            out = np.asarray(
                self._run_rooted(op, stacked, mesh, lead)
                if mesh is not None
                else stacked.reshape(-1)[None].repeat(size, 0)
            )
            res = calls[root].res
            if res is not None and not res.is_dummy:
                _write_host_result(res, out[root], size * n, ic)
            return ErrorCode.OK

        if op == Operation.ALLTOALL:
            stacked = wire_cast(_np_stack_op0(calls, [size * n] * size, ic))
            out = np.asarray(
                opdriver.run_alltoall(stacked, mesh)
                if mesh is not None
                else stacked.reshape(size, size, n).transpose(1, 0, 2).reshape(
                    size, size * n
                )
            )
            for r, call in enumerate(calls):
                _write_host_result(call.res, out[r], size * n, ic)
            return ErrorCode.OK

        return ErrorCode.COLLECTIVE_NOT_IMPLEMENTED

    def _allreduce(self, stacked, mesh, fn, wire_dtype, prep=None,
                   tuning=None):
        if mesh is None:
            if wire_dtype is not None:
                npdt = dtype_to_numpy(wire_dtype)
                stacked = stacked.astype(npdt).astype(stacked.dtype)
            return self._host_reduce(stacked, fn)[None].repeat(stacked.shape[0], 0)
        return run_allreduce_with_tuning(
            stacked, mesh, fn, wire_dtype,
            self.tuning if tuning is None else tuning, prep=prep,
        )

    @staticmethod
    def _host_reduce(stacked: np.ndarray, fn: ReduceFunction) -> np.ndarray:
        return (
            stacked.sum(axis=0, dtype=stacked.dtype)
            if fn == ReduceFunction.SUM
            else stacked.max(axis=0)
        )


# p2p pairing: send/recv matched by (comm, tag, src, dst) independent of the
# collective gang sequence.  Receivers register a *sink* callable so the same
# channel serves buffer receives and recv-to-stream.  Unmatched posts carry a
# watchdog honoring the engine timeout (the firmware's per-call deadline);
# delivery — which may jit the fabric-hop program — runs OUTSIDE the channel
# lock so unrelated pairs never serialize behind a compile.
class _P2PChannel:
    """Tag-matched send/recv rendezvous between rank engines.

    Durations are MEASURED, not sentinels: each post is stamped at entry
    and each request completes with post->delivery wall-clock ns — the
    analog of the reference's per-call device-cycle reads that its
    sendrecv bench is built on (ref xrtdevice.cpp:242-249 get_duration,
    bench.cpp:25-31).  A parked side therefore reports its true wait
    (including the partner's late arrival); the late-arriving side
    reports roughly the delivery/copy cost alone."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sends: Dict[tuple, list] = {}
        self._recvs: Dict[tuple, list] = {}

    def dump_parked(self) -> list:
        """Unmatched-post lines for the debug dump (a parked send holds
        its payload alive — the closest analog of an occupied rx buffer
        on this tier)."""
        lines = []
        with self._lock:
            for kind, table in (("SEND", self._sends), ("RECV", self._recvs)):
                for key, entries in table.items():
                    for _ in entries:
                        comm_id, tag, src, dst = key
                        lines.append(
                            f"rxbuf p2p-{kind} comm={comm_id} tag={tag} "
                            f"src={src} dst={dst} PARKED"
                        )
        return lines

    def post_send(self, key, payload, request, timeout_s=None):
        t0 = time.perf_counter_ns()
        match = None
        with self._lock:
            if self._recvs.get(key):
                sink, rreq, rtimer, rt0 = self._recvs[key].pop(0)
                if rtimer is not None:
                    rtimer.cancel()
                match = (sink, rreq, rt0)
            else:
                self._park(self._sends, key, [payload, request], timeout_s, t0)
        if match is not None:
            self._deliver(match[0], match[1], payload, request, match[2], t0)

    def post_recv(self, key, sink, request, timeout_s=None):
        t0 = time.perf_counter_ns()
        match = None
        with self._lock:
            if self._sends.get(key):
                payload, sreq, stimer, st0 = self._sends[key].pop(0)
                if stimer is not None:
                    stimer.cancel()
                match = (payload, sreq, st0)
            else:
                self._park(self._recvs, key, [sink, request], timeout_s, t0)
        if match is not None:
            self._deliver(sink, request, match[0], match[1], t0, match[2])

    def _park(self, table, key, entry, timeout_s, t0) -> None:
        """Append an unmatched post (caller holds the lock), arming a
        timeout watchdog when requested."""
        entry.append(None)
        entry.append(t0)
        if timeout_s:
            code = (
                ErrorCode.SEND_TIMEOUT
                if table is self._sends
                else ErrorCode.RECEIVE_TIMEOUT
            )
            t = threading.Timer(
                timeout_s, self._expire, (table, key, entry, code)
            )
            t.daemon = True
            entry[2] = t
            t.start()
        table.setdefault(key, []).append(entry)

    def _expire(self, table, key, entry, code) -> None:
        with self._lock:
            # identity-based scan: payloads are arrays, so `in`/`remove`
            # would trip elementwise ==
            lst = table.get(key, [])
            idx = next((i for i, e in enumerate(lst) if e is entry), None)
            if idx is None:
                return  # matched in the meantime: nothing to do
            del lst[idx]
        dt = time.perf_counter_ns() - entry[3]
        comm_id, _tag, src, dst = key
        entry[1].complete(code, dt, context={
            "op": entry[1].op_name,
            "comm": comm_id,
            # the absent partner: the sender for a starved recv, the
            # receiver for a starved send (global rank identities)
            "peer": src if code == ErrorCode.RECEIVE_TIMEOUT else dst,
            "elapsed_s": round(dt / 1e9, 3),
        })

    @staticmethod
    def _deliver(sink, rreq: Request, payload: np.ndarray, sreq,
                 recv_t0: int, send_t0: int):
        try:
            sink(payload)
        except Exception:
            t1 = time.perf_counter_ns()
            rreq.complete(ErrorCode.INVALID_OPERATION, max(t1 - recv_t0, 1))
            sreq.complete(ErrorCode.INVALID_OPERATION, max(t1 - send_t0, 1))
            return
        t1 = time.perf_counter_ns()
        rreq.complete(ErrorCode.OK, max(t1 - recv_t0, 1))
        sreq.complete(ErrorCode.OK, max(t1 - send_t0, 1))


class XLAEngine(StreamPortMixin, BaseEngine):
    """One rank handle's engine over a shared gang context.

    Local ops (copy/combine) execute immediately with jax.numpy on the
    default device; collectives rendezvous at the gang; p2p pairs match in
    the channel (the ICI transfer being a collective-permute is an XLA
    scheduling detail once both sides have arrived)."""

    def __init__(
        self,
        gang: XLAGangContext,
        p2p: Optional[_P2PChannel] = None,
        peers: Optional[Dict[int, "XLAEngine"]] = None,
        device=None,
    ):
        self.gang = gang
        self.p2p = p2p or _P2PChannel()
        if gang.p2p is None:
            gang.p2p = self.p2p
        self.peers = peers if peers is not None else {}
        self.device = device  # this rank's chip; buffers commit to its HBM
        self.timeout_s = DEFAULT_TIMEOUT_S
        self.max_eager_size = 32 * 1024
        self.max_rendezvous_size = MAX_EAGER_SIZE_LIMIT
        self.retry_limit = 0
        self.retry_backoff_s = 0.05
        # QoS arbiter plane: engine-side mirror of SET_TENANT_* writes
        # (comm id -> {class, weight, window_share, ring_slots, rate})
        self.tenants: Dict[int, dict] = {}
        self._init_streams()

    def start(self, options: CallOptions) -> Request:
        req = Request(op_name=options.op.name)
        req.mark_executing()
        self._start_with(options, req)
        return req

    def start_batch(self, items) -> None:
        """Dispatch a flushed command-queue batch.  Maximal runs of gang
        collectives sharing a communicator submit as ONE gang batch event
        (executed as one fused program when every position qualifies —
        see ``XLAGangContext._run_batch_fused``); local ops / p2p / config
        calls break the run and dispatch individually, preserving issue
        order."""
        run: list = []
        run_comm = None

        def flush_run():
            nonlocal run, run_comm
            if run:
                self.gang.submit_batch(
                    run_comm, [o for o, _ in run], [r for _, r in run]
                )
            run, run_comm = [], None

        for options, req in items:
            req.mark_executing()
            gang_eligible = (
                (options.op in IN_W or options.op == Operation.BARRIER)
                and options.stream == StreamFlags.NO_STREAM
            )
            # command-ring p2p: a batched SEND/RECV on a world-2 gang
            # joins the collective run so a matched pair can ride one
            # ring slot (root=src, peer=dst).  Eligibility is
            # pair-symmetric by construction (cmdring.p2p_eligible) so
            # both ends classify identically; unpaired positions fall
            # back to _execute_p2p_pair / the channel below.
            if (
                options.op in (Operation.SEND, Operation.RECV)
                and options.stream == StreamFlags.NO_STREAM
                and self.gang.cmdring.p2p_eligible(options)
            ):
                gang_eligible = True
            if gang_eligible:
                if run_comm is not None and options.comm is not run_comm:
                    flush_run()
                run_comm = options.comm
                run.append((options, req))
            else:
                flush_run()
                self._start_with(options, req)
        flush_run()

    def device_interactions(self) -> int:
        return self.gang.interactions.read()

    # -- contract plane (accl_tpu.contract) ----------------------------------
    def contract_anchor(self):
        """The gang context: every rank handle of this mesh shares it,
        so their verifiers exchange digests on one in-process board (the
        single-process analog of the multi-slice device-side digest
        reduce — ROADMAP item 2)."""
        return self.gang

    def set_contract_verifier(self, verifier) -> None:
        """A divergence verdict must fail the gang's PARKED slots too:
        the detecting rank raises pre-dispatch, which means its peers'
        already-submitted calls would otherwise starve their slot until
        the watchdog — the exact hang the verifier exists to remove."""
        self.contract_verifier = verifier
        if verifier is not None:
            verifier.add_verdict_listener(self.gang.contract_fail)

    def drain_inflight(self, timeout=None) -> bool:
        """Overlap drain point: block until the gang's in-flight window
        is empty (every launched collective completed).  Bounded by
        default — flush()/config callers must not hang forever on a
        wedged device call (the per-request wait()/check() path is
        where its failure surfaces)."""
        return self.gang.window.drain(
            timeout if timeout is not None
            else drain_deadline_s(self.gang.timeout_s)
        )

    # -- membership plane (accl_tpu.membership) ------------------------------
    def set_membership(self, view) -> None:
        """Arm (or with ``None`` disarm) the membership plane: the
        gang's slot-watchdog health transitions forward to the facade
        hook (the board does the agreement exchange — every gang rank
        handle shares the anchor).  Disarm removes the forwarder from
        the shared gang — it must not keep firing (or pin this engine)
        for the gang's lifetime across handle churn."""
        self.membership = view
        fwd = getattr(self, "_mbr_fwd", None)
        if view is None:
            if fwd is not None:
                self.gang.remove_health_listener(fwd)
                self._mbr_fwd = None
            return
        if fwd is None:

            def fwd(session, old, new, eng=self):
                hook = eng.on_health_transition
                if hook is not None:
                    hook(session, old, new)

            self._mbr_fwd = fwd
            self.gang.add_health_listener(fwd)

    def on_membership_cutover(self, plan: dict, addresses: tuple = (),
                              comm_ids: tuple = ()) -> None:
        """Post-cutover session re-arm (shrink AND grow): halt the
        command ring's persistent runs and abandon its per-comm
        sessions (they re-arm lazily over the new membership at the
        next warm window — the documented tear-down/re-arm), drop the
        evicted sessions' watchdog entries — and, on a JOIN, the
        admitted sessions' too: the candidate's previous life may have
        left a ``dead`` verdict that would fail-fast its first
        post-join window — and clear the suspect strikes the failure
        cascade accrued against survivors."""
        for s in tuple(plan.get("evict", ())) + tuple(
            plan.get("admit", ())
        ):
            self.gang.health.pop(s, None)
        # snapshot before iterating: the watchdog timer thread inserts
        # concurrently, and a bare .values() walk can raise mid-cutover
        for h in list(self.gang.health.values()):
            if h["state"] == "suspect":
                h["state"] = "ok"
                h["timeouts"] = 0
        self.gang.cmdring.reset()

    def telemetry_report(self) -> dict:
        """Gang-tier counters for the telemetry snapshot: pending
        rendezvous slots, parked p2p posts, undrained stream ports, and
        the shared interaction counter."""
        with self.gang._lock:
            pending_slots = len(self.gang._slots)
        with self._stream_cv:
            stream_depths = {
                sid: len(chunks)
                for sid, chunks in sorted(self._streams.items())
                if chunks
            }
        return {
            "device_interactions": self.gang.interactions.read(),
            "gang_pending_slots": pending_slots,
            "gang_tuning_epoch": self.gang.tuning_epoch,
            "p2p_parked": len(self.p2p.dump_parked()),
            "stream_depths": stream_depths,
            # overlap plane: the in-flight window's live depth + lifetime
            # counters (launched/completed/failed/max depth/overlap ns)
            "inflight": self.gang.window.stats(),
            # command-ring plane: refill/doorbell counters, occupancy,
            # park state and per-reason fallback counts
            "cmdring": self.gang.cmdring.stats(),
            # QoS arbiter plane: the engine-side tenant quota mirror
            "tenants": {str(k): dict(v) for k, v in
                        sorted(self.tenants.items())},
            "faults": None,
            # monitor plane: rank handles share the gang context, so
            # straggler windows meet on one in-process judge (the
            # contract board's anchor discipline reused)
            "skew_exchange": "board",
        }

    def trace_events(self) -> list:
        """Ring-resident spans (one per slot, nested under its refill
        window, flow-linked to the issuing call) — the gang tier's
        engine-owned rows in the facade's Perfetto export.  Every rank
        handle shares the gang, so every rank file embeds the same
        rows; merge_traces dedups them to one copy (cat ``cmdring``)."""
        return self.gang.cmdring.trace_events()

    def health_report(self, comm: Communicator) -> Dict[int, dict]:
        """Per-peer health from the gang watchdog accounting, keyed by
        comm-relative rank (capabilities()["health"] on the gang tier)."""
        report: Dict[int, dict] = {}
        for i, r in enumerate(comm.ranks):
            if i == comm.local_rank:
                continue
            h = self.gang.health.get(r.session)
            report[i] = dict(h) if h else {
                "state": "ok", "timeouts": 0, "failures": 0, "last_event": ""
            }
        return report

    def _start_with(self, options: CallOptions, req: Request) -> None:
        op = options.op
        if op == Operation.CONFIG:
            req.complete(self._apply_config(options))
        elif op == Operation.NOP:
            req.complete(ErrorCode.OK)
        elif op in (Operation.COPY, Operation.COMBINE):
            if options.stream & StreamFlags.OP0_STREAM:
                # streaming operand arrives asynchronously from a device
                # kernel: wait for it off the caller's thread
                self._spawn_completing(
                    lambda: req.complete(self._local_op(options)), req
                )
            else:
                req.complete(self._local_op(options))
        elif op == Operation.REDUCE and options.stream != StreamFlags.NO_STREAM:
            # stream-operand reduce (ref accl.hpp:514-590): bridge the
            # stream ports onto the gang off-thread
            self._spawn_completing(
                lambda: self._gang_with_streams(options, req), req
            )
        elif op == Operation.SEND:
            self._start_send(options, req)
        elif op == Operation.RECV:
            comm = options.comm
            # p2p keys use *global* rank identities (Rank.session) so that
            # subcommunicator traffic reaches the right engine
            src_world = comm.ranks[options.root_src].session
            me_world = comm.ranks[comm.local_rank].session
            key = (comm.id, options.tag, src_world, me_world)
            if options.stream & StreamFlags.RES_STREAM:
                sink = lambda payload: self.stream_push(
                    options.stream_id, np.asarray(payload).tobytes()
                )
            else:

                def sink(payload, call=options, req=req):
                    if isinstance(payload, jax.Array) and isinstance(
                        call.res, DeviceBuffer
                    ):
                        # both ends device-resident: ride the fabric —
                        # LAZILY.  The hop/trim programs (each a device
                        # interaction) are parked on the result buffer and
                        # run at the receiver's wait()/first data access,
                        # so a fire-and-forget recv chain never pays the
                        # result RTT at match time.  Shape validation
                        # stays EAGER so a mismatched pair still fails at
                        # the channel (INVALID_OPERATION on both sides),
                        # not at a later wait.
                        if payload.ndim != 1 or payload.shape[0] < call.count:
                            raise ValueError(
                                f"p2p payload of shape {payload.shape} "
                                f"into count {call.count}"
                            )
                        ic = self.gang.interactions

                        def deliver(payload=payload, call=call, ic=ic):
                            _p2p_device_deliver(
                                payload, call.res, call.count, ic
                            )

                        call.res.defer_store(deliver)
                        req.defer_result(
                            call.res.resolve_pending, handle=payload
                        )
                        return
                    if isinstance(payload, jax.Array):
                        payload = np.asarray(payload)  # host-side receiver
                    _write_host_result(
                        call.res, payload, call.count, self.gang.interactions
                    )

            self.p2p.post_recv(key, sink, req, timeout_s=self.timeout_s)
        else:
            self.gang.submit(options.comm, options, req)

    def _start_send(self, options: CallOptions, req: Request) -> None:
        """SEND with all four operand routings: buffer/local-stream source x
        tag-matched/remote-stream destination (emulator parity:
        algorithms.op_send)."""
        comm = options.comm

        def resolve_and_route():
            t0 = time.perf_counter_ns()
            cfg = options.arithcfg
            if options.stream & StreamFlags.OP0_STREAM:
                payload = self._pop_stream_payload(options)
                if payload is None:
                    req.complete(ErrorCode.DMA_TIMEOUT)
                    return
            elif isinstance(options.op0, DeviceBuffer) and not (
                options.stream & StreamFlags.RES_STREAM
            ):
                # device-resident send: post the payload as a committed
                # jax.Array (a fresh device copy, so the sender may free or
                # overwrite its buffer immediately); the matched receiver
                # moves it over the fabric with a collective-permute
                src_dev = options.op0.device
                payload = _trim_program(options.count, src_dev)(
                    options.op0.device_array()
                )
                self.gang.interactions.bump()  # the payload-copy program
                if options.compression & CompressionFlags.ETH_COMPRESSED:
                    # compress lane on the sending chip: the wire (and the
                    # ICI hop) carries the narrow dtype
                    payload = _cast_program(
                        dtype_to_numpy(cfg.compressed), src_dev
                    )(payload)
                    self.gang.interactions.bump()
            else:
                payload = np.asarray(
                    options.op0.device_view()[: options.count]
                ).copy()
            if isinstance(payload, np.ndarray) and (
                options.compression & CompressionFlags.ETH_COMPRESSED
            ):
                payload = payload.astype(dtype_to_numpy(cfg.compressed))
            dst_world = comm.ranks[options.root_dst].session
            me_world = comm.ranks[comm.local_rank].session
            if options.stream & StreamFlags.RES_STREAM:
                peer = self.peers.get(dst_world)
                if peer is None:
                    req.complete(ErrorCode.TRANSPORT_ERROR)
                else:
                    peer.stream_push(options.stream_id, payload.tobytes())
                    req.complete(
                        ErrorCode.OK, max(time.perf_counter_ns() - t0, 1)
                    )
                return
            key = (comm.id, options.tag, me_world, dst_world)
            self.p2p.post_send(key, payload, req, timeout_s=self.timeout_s)

        if options.stream & StreamFlags.OP0_STREAM:
            # operand arrives asynchronously from a device kernel: wait for
            # it off the caller's thread (the emulator parks in its scheduler)
            self._spawn_completing(resolve_and_route, req)
        else:
            resolve_and_route()

    def _spawn_completing(self, fn, req: Request) -> None:
        """Run ``fn`` on a daemon thread; an escaping exception completes
        the request with an error instead of leaving the caller waiting
        forever (the scheduler-level guard the emulator tier has)."""

        def run():
            try:
                fn()
            except Exception:
                import traceback

                traceback.print_exc()
                if not req.done():  # side-effect-free engine probe
                    req.complete(ErrorCode.INVALID_OPERATION)

        threading.Thread(
            target=run, name="accl-xla-op", daemon=True
        ).start()

    def _gang_with_streams(self, options: CallOptions, req: Request) -> None:
        """Stream-operand collective: pull OP0 from the stream port, run
        the gang collective on a host-staged temp, deliver the root result
        back to the stream port."""
        import dataclasses

        opts = options
        if opts.stream & StreamFlags.OP0_STREAM:
            payload = self._pop_stream_payload(opts)
            if payload is None:
                req.complete(ErrorCode.DMA_TIMEOUT)
                return
            acc_npdt = dtype_to_numpy(opts.arithcfg.uncompressed)
            tmp = EmuBuffer.from_array(payload.astype(acc_npdt))
            tmp.sync_to_device()
            opts = dataclasses.replace(
                opts, op0=tmp, stream=opts.stream & ~StreamFlags.OP0_STREAM
            )
        res_to_stream = bool(opts.stream & StreamFlags.RES_STREAM)
        tmp_res = None
        if res_to_stream:
            is_root = opts.comm.local_rank == opts.root_dst
            tmp_res = (
                EmuBuffer(opts.count, opts.arithcfg.uncompressed)
                if is_root
                else DummyBuffer(0, opts.arithcfg.uncompressed)
            )
            opts = dataclasses.replace(
                opts, res=tmp_res,
                stream=opts.stream & ~StreamFlags.RES_STREAM,
            )
        inner = Request(op_name=opts.op.name)
        inner.mark_executing()
        self.gang.submit(opts.comm, opts, inner)
        # acclint: allow[unbounded-wait] the gang slot watchdog completes
        # `inner` with RECEIVE_TIMEOUT when the gang never assembles, so
        # this wait is bounded by the engine timeout machinery, not ours
        inner.wait()
        code = inner.get_retcode()
        if (
            code == ErrorCode.OK
            and res_to_stream
            and not tmp_res.is_dummy
        ):
            self._push_stream_result(options, tmp_res.device_view())
        req.complete(code, inner.get_duration_ns())

    def _local_op(self, options: CallOptions) -> ErrorCode:
        n = options.count
        if options.stream & StreamFlags.OP0_STREAM:
            payload = self._pop_stream_payload(options)
            if payload is None:
                return ErrorCode.DMA_TIMEOUT
            acc = payload.astype(
                dtype_to_numpy(options.arithcfg.uncompressed)
            )
            if options.op == Operation.COMBINE:
                other = np.asarray(options.op1.device_view()[:n])
                if options.reduce_function == ReduceFunction.SUM:
                    acc = acc + other
                elif options.reduce_function == ReduceFunction.MAX:
                    acc = np.maximum(acc, other)
                else:
                    return ErrorCode.ARITH_ERROR
            if options.stream & StreamFlags.RES_STREAM:
                self._push_stream_result(options, acc)
            else:
                _write_host_result(
                    options.res, acc, n, self.gang.interactions
                )
            return ErrorCode.OK
        if options.stream & StreamFlags.RES_STREAM:
            src = np.asarray(options.op0.device_view()[:n])
            if options.op == Operation.COMBINE:
                other = np.asarray(options.op1.device_view()[:n])
                if options.reduce_function == ReduceFunction.SUM:
                    src = src + other
                elif options.reduce_function == ReduceFunction.MAX:
                    src = np.maximum(src, other)
                else:
                    return ErrorCode.ARITH_ERROR
            self._push_stream_result(options, src)
            return ErrorCode.OK
        bufs = [options.op0, options.res]
        if options.op == Operation.COMBINE:
            bufs.insert(1, options.op1)
        if all(isinstance(b, DeviceBuffer) for b in bufs) and len(
            {b.device for b in bufs}
        ) == 1:
            # all-device fast path: compute on the owning chip, adopt the
            # result — the reference's DMA-loopback copy/combine with no
            # host in the loop
            src = options.op0.device_array()[:n]
            if options.op == Operation.COMBINE:
                other = options.op1.device_array()[:n]
                if options.reduce_function == ReduceFunction.SUM:
                    out = src + other
                elif options.reduce_function == ReduceFunction.MAX:
                    out = jnp.maximum(src, other)
                else:
                    return ErrorCode.ARITH_ERROR
            else:
                # force a distinct array: a full-count slice returns the
                # IDENTICAL jax.Array, and sharing storage would make a later
                # free_buffer() on either buffer delete the other's data
                out = jnp.copy(src)
            res_npdt = dtype_to_numpy(options.res.dtype)
            if out.dtype != res_npdt:
                out = out.astype(res_npdt)  # cross-dtype copy/combine
            self.gang.interactions.bump()  # the eager device compute
            if options.res.store(out, n):
                self.gang.interactions.bump()
            return ErrorCode.OK
        src = jnp.asarray(options.op0.device_view()[:n])
        if options.op == Operation.COMBINE:
            other = jnp.asarray(options.op1.device_view()[:n])
            if options.reduce_function == ReduceFunction.SUM:
                out = src + other
            elif options.reduce_function == ReduceFunction.MAX:
                out = jnp.maximum(src, other)
            else:
                return ErrorCode.ARITH_ERROR
        else:
            out = src
        _write_host_result(
            options.res, np.asarray(out), n, self.gang.interactions
        )
        return ErrorCode.OK

    def _apply_config(self, options: CallOptions) -> ErrorCode:
        fn = ConfigFunction(options.cfg_function)
        val = options.cfg_value
        if fn == ConfigFunction.RESET:
            self.gang.soft_reset()
        elif fn == ConfigFunction.SET_TIMEOUT:
            if val <= 0:
                return ErrorCode.CONFIG_ERROR
            self.timeout_s = float(val)
            self.gang.timeout_s = float(val)
        elif fn == ConfigFunction.SET_MAX_EAGER_SIZE:
            if not 0 < val <= MAX_EAGER_SIZE_LIMIT:
                return ErrorCode.CONFIG_ERROR
            self.max_eager_size = int(val)
        elif fn == ConfigFunction.SET_MAX_RENDEZVOUS_SIZE:
            if val <= 0:
                return ErrorCode.CONFIG_ERROR
            self.max_rendezvous_size = int(val)
        elif fn == ConfigFunction.SET_RETRY_LIMIT:
            # no wire retransmit on this tier (XLA owns the fabric); the
            # knobs are accepted + stored so set_retry_policy is portable
            if val < 0:
                return ErrorCode.CONFIG_ERROR
            self.retry_limit = int(val)
        elif fn == ConfigFunction.SET_RETRY_BACKOFF:
            if val <= 0:
                return ErrorCode.CONFIG_ERROR
            self.retry_backoff_s = float(val)
        elif fn == ConfigFunction.SET_INFLIGHT_WINDOW:
            from ...constants import MAX_INFLIGHT_WINDOW

            if not 1 <= val <= MAX_INFLIGHT_WINDOW:
                return ErrorCode.CONFIG_ERROR
            # a depth change is itself a drain point: no launch made
            # under the old bound may still be in flight when the new
            # bound starts admitting (bounded — a wedged call fails the
            # config within the engine deadline instead of hanging it)
            if not self.gang.window.drain(
                drain_deadline_s(self.gang.timeout_s)
            ):
                return ErrorCode.RECEIVE_TIMEOUT
            self.gang.window.set_depth(int(val))
        elif fn in (
            ConfigFunction.SET_TENANT_CLASS,
            ConfigFunction.SET_TENANT_WEIGHT,
            ConfigFunction.SET_TENANT_WINDOW_SHARE,
            ConfigFunction.SET_TENANT_RING_SLOTS,
            ConfigFunction.SET_TENANT_RATE,
        ):
            # QoS arbiter plane, validated by the ONE shared validator
            # (arbiter.tenant_config_valid — the same ranges on every
            # tier).  This tier additionally ENFORCES the two device-
            # side quotas: WINDOW_SHARE becomes a per-key depth
            # override on the in-flight window (a drain point like
            # SET_INFLIGHT_WINDOW — nothing launched under the old
            # bound survives it) and RING_SLOTS the command ring's
            # refill-window slot budget.  Class/weight/rate stay
            # arbiter-side state, mirrored for introspection.
            from ...arbiter import tenant_config_field, tenant_config_valid

            if not tenant_config_valid(fn, val):
                return ErrorCode.CONFIG_ERROR
            if fn == ConfigFunction.SET_TENANT_WINDOW_SHARE:
                if not self.gang.window.drain(
                    drain_deadline_s(self.gang.timeout_s)
                ):
                    return ErrorCode.RECEIVE_TIMEOUT
                self.gang.window.set_key_depth(
                    int(options.cfg_key), int(val)
                )
            elif fn == ConfigFunction.SET_TENANT_RING_SLOTS:
                self.gang.cmdring.set_slot_budget(
                    int(options.cfg_key), int(val)
                )
            self.tenants.setdefault(
                int(options.cfg_key), {}
            )[tenant_config_field(fn)] = val
        elif fn == ConfigFunction.SET_TUNING:
            return self._apply_tuning(options)
        return ErrorCode.OK

    def _apply_tuning(self, options: CallOptions) -> ErrorCode:
        code = apply_tuning(self.gang.tuning, options)
        if code == ErrorCode.OK:
            self.gang.tuning_epoch += 1
        return code

    def create_buffer(self, count: int, dtype, host_only: bool = False,
                      data=None):
        """HBM-resident DeviceBuffer on this rank's chip; host-only
        buffers (and device-less fallback ranks) stay host pairs."""
        return make_buffer(
            self.device, count, dtype, host_only=host_only, data=data
        )

    def dump_rx_buffers(self) -> str:
        """Rx-accounting dump for the gang tier (the role of the
        reference's rx-buffer spare-queue dump, accl.cpp dump_rx_buffers):
        the live slot state here is parked gang rendezvous slots,
        unmatched p2p posts, and undrained stream-port chunks.  Lines for
        occupied state carry the ``rxbuf`` token WITHOUT ``IDLE`` so the
        soak/stress leak filters (benchmarks/chip_soak.py,
        tests/test_soak.py) read this tier's dump exactly like the
        emulator pool's — a clean engine emits no ``rxbuf`` line at all."""
        lines = [
            "XLA gang rx state "
            f"(device={self.device}, "
            f"device_interactions={self.gang.interactions.read()}):"
        ]
        lines += self.gang.dump_state()
        lines += self.p2p.dump_parked()
        with self._stream_cv:
            for sid, chunks in sorted(self._streams.items()):
                if chunks:
                    lines.append(
                        f"rxbuf stream-port {sid} depth={len(chunks)} "
                        "UNDRAINED"
                    )
        if len(lines) == 1:
            lines.append("all slots IDLE")
        return "\n".join(lines)

    def shutdown(self) -> None:
        # overlap plane: drain and stop the shared window's drainer (the
        # first rank handle's deinit does the work; later ones find it
        # already stopped — parks then degrade to inline completion)
        self.gang.window.stop()
        # command ring: halt every resident sequencer run so the
        # long-running programs return promptly instead of riding out
        # their linger with the process tearing down around them
        self.gang.cmdring.halt_sessions()
