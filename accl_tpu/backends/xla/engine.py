"""XLA device backend: the ACCL facade over a real device mesh.

The reference's device tier drives one offload engine per FPGA over the
100G fabric; the TPU equivalent is SPMD — *one* XLA program executes the
collective across every chip at once.  This backend bridges the MPI-like
per-rank call model onto that: rank handles submit their operands into a
shared :class:`XLAGangContext`; when every rank of a communicator has posted
the matching call, the gang runs one jitted ``shard_map`` program over the
mesh (built from ``accl_tpu.ops``) and distributes the per-rank results.

This is the semantic bridge SURVEY.md §7 calls the hard part ("eager/
rendezvous semantics vs XLA's static world"): tag-matched point-to-point
pairs rendezvous *at the gang*, and the data then moves with a
collective-permute on ICI.

Mapping notes (ref -> here):
* communicator        -> sub-``Mesh`` over the first ``comm.size`` devices
                         (ref: comm tables in exchange memory)
* eager/rendezvous    -> collapsed: gang rendezvous + XLA scheduling
                         (ref: protocol select at c:587/667/808)
* compression flags   -> wire-dtype cast stages around the collective
                         (ref: hp_compression lanes)
* per-call perf ctr   -> wall-clock ns around the XLA program
"""

from __future__ import annotations

import functools
import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ...communicator import Communicator
from ...constants import (
    CompressionFlags,
    ConfigFunction,
    DEFAULT_TIMEOUT_S,
    ErrorCode,
    MAX_EAGER_SIZE_LIMIT,
    Operation,
    ReduceFunction,
    StreamFlags,
    dtype_to_numpy,
)
from ...buffer import (
    DeviceBuffer,
    DummyBuffer,
    EmuBuffer,
    dev_zeros as _dev_zeros,
    make_buffer,
)
from ...request import Request
from ..base import BaseEngine, CallOptions, StreamPortMixin
from ...ops import driver as opdriver


def _np_stack_op0(calls: List[CallOptions], counts: List[int]) -> np.ndarray:
    """Stack per-rank operands (rank-major) into one (size, n) array."""
    rows = []
    width = max(counts) if counts else 0
    for call, n in zip(calls, counts):
        if call.op0 is not None and not call.op0.is_dummy:
            row = np.asarray(call.op0.device_view()[:n])
            if row.size < width:
                row = np.pad(row, (0, width - row.size))
        else:
            row = np.zeros(width, dtype_to_numpy(call.arithcfg.uncompressed))
        rows.append(row)
    return np.stack(rows)


def _write_host_result(buf, row, n: int) -> None:
    """Place a host-computed result row into any buffer type (the fallback
    path's writer; the zero-copy path uses DeviceBuffer.store directly)."""
    if isinstance(buf, DeviceBuffer):
        npdt = dtype_to_numpy(buf.dtype)
        arr = jax.device_put(np.asarray(row)[:n].astype(npdt), buf.device)
        buf.store(arr, n)
    else:
        dst = buf.device_view()[:n]
        np.copyto(dst, np.asarray(row)[:n].astype(dst.dtype))


# The shard prep/trim steps run as tiny cached jitted programs rather than
# eager ops: eager slicing dispatches its index scalars host->device, which
# would break the zero-host-copy guarantee (and trip transfer guards).
@functools.lru_cache(maxsize=1024)
def _prep_program(width: int, wire_name: Optional[str], device,
                  flat: bool = False):
    """Slice/round a rank's operand into a shard: ``flat`` keeps the
    (width,) 1-D layout (the engine's flat globals), otherwise the stacked
    (1, width) row.  Flat exact-size uncompressed operands never get here —
    they plug in raw with no program at all."""
    from jax.sharding import SingleDeviceSharding

    def f(a):
        a = a[:width]
        if wire_name is not None:
            a = a.astype(jnp.dtype(wire_name)).astype(a.dtype)
        return a if flat else a.reshape(1, width)

    return jax.jit(f, out_shardings=SingleDeviceSharding(device))


@functools.lru_cache(maxsize=1024)
def _trim_program(width: int, device):
    from jax.sharding import SingleDeviceSharding

    return jax.jit(
        lambda a: a.reshape(-1)[:width],
        out_shardings=SingleDeviceSharding(device),
    )


@functools.lru_cache(maxsize=1024)
def _cast_program(npdt, device):
    from jax.sharding import SingleDeviceSharding

    return jax.jit(
        lambda a: a.astype(npdt),
        out_shardings=SingleDeviceSharding(device),
    )


@functools.lru_cache(maxsize=512)
def _p2p_hop_program(src_dev, dst_dev):
    """The device-fabric hop for a matched send/recv pair: a jitted
    collective-permute over a two-device mesh [src, dst] — on real TPU
    slices the payload moves over ICI, the analog of the reference's
    packetizer->wire->depacketizer path (ccl_offload_control.c:573-710).
    Returns (mesh, program)."""
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec

    try:
        from jax import shard_map
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map  # type: ignore

    mesh = Mesh([src_dev, dst_dev], ("p2p",))
    spec = PartitionSpec("p2p")
    prog = jax.jit(
        shard_map(
            lambda x: lax.ppermute(x, "p2p", [(0, 1)]),
            mesh=mesh,
            in_specs=(spec,),
            out_specs=spec,
            check_vma=False,
        )
    )
    return mesh, prog


def _p2p_device_deliver(payload, res: DeviceBuffer, count: int) -> None:
    """Move a device-resident p2p payload to the receiver's chip with a
    collective-permute and adopt it into the result buffer — no host in
    the data path."""
    from jax.sharding import NamedSharding, PartitionSpec

    if payload.ndim != 1 or payload.shape[0] < count:
        raise ValueError(
            f"p2p payload of shape {payload.shape} into count {count}"
        )
    (src_dev,) = payload.devices()
    dst_dev = res.device
    res_npdt = dtype_to_numpy(res.dtype)
    if src_dev == dst_dev:
        # self-send: a device-local copy (jit output, distinct array)
        arr = _trim_program(count, dst_dev)(payload)
    else:
        mesh, prog = _p2p_hop_program(src_dev, dst_dev)
        shards = [
            _prep_program(count, None, src_dev)(payload),
            _dev_zeros((1, count), payload.dtype, dst_dev),
        ]
        global_in = jax.make_array_from_single_device_arrays(
            (2, count),
            NamedSharding(mesh, PartitionSpec("p2p")),
            shards,
        )
        out = prog(global_in)
        arr = next(
            s.data for s in out.addressable_shards if s.device == dst_dev
        )
        arr = _trim_program(count, dst_dev)(arr)
    if arr.dtype != res_npdt:
        # wire-compressed payload: decompress lane on the receiving chip
        arr = _cast_program(res_npdt, dst_dev)(arr)
    res.store(arr, count)



# per-op operand/result widths in units of ``count`` ('P' = size*count)
IN_W = {
    Operation.ALLREDUCE: 1, Operation.REDUCE: 1, Operation.BCAST: 1,
    Operation.ALLGATHER: 1, Operation.GATHER: 1,
    Operation.REDUCE_SCATTER: "P", Operation.SCATTER: "P",
    Operation.ALLTOALL: "P",
}
OUT_W = {
    Operation.ALLREDUCE: 1, Operation.REDUCE: 1, Operation.BCAST: 1,
    Operation.SCATTER: 1, Operation.REDUCE_SCATTER: 1,
    Operation.ALLGATHER: "P", Operation.GATHER: "P",
    Operation.ALLTOALL: "P",
}


def run_rooted_with_tuning(op, global_arr, mesh, lead, tuning, donate=False):
    """Rooted collective with algorithm selection from the tuning
    registers: XLA lowering, or the rooted Pallas ring-relay kernels (the
    algorithm-faithful mode of the reference's rooted trees).  Shared by
    the single-process gang and the multi-process dist engine."""
    nseg = int(tuning.get("ring_segments", 1))
    fn = lead.reduce_function
    if op == Operation.REDUCE:
        if tuning.get("reduce_algorithm", "xla") == "pallas_ring":
            return opdriver.run_pallas_reduce(
                global_arr, mesh, lead.root_dst, fn, nseg
            )
        return opdriver.run_reduce(global_arr, mesh, lead.root_dst, fn)
    if op == Operation.BCAST:
        if tuning.get("bcast_algorithm", "xla") == "pallas_ring":
            return opdriver.run_pallas_bcast(
                global_arr, mesh, lead.root_src, nseg
            )
        return opdriver.run_bcast(
            global_arr, mesh, lead.root_src, donate=donate
        )
    if op == Operation.SCATTER:
        if tuning.get("scatter_algorithm", "xla") == "pallas_ring":
            return opdriver.run_pallas_scatter(
                global_arr, mesh, lead.root_src, nseg
            )
        return opdriver.run_scatter(global_arr, mesh, lead.root_src)
    if op == Operation.GATHER:
        if tuning.get("gather_algorithm", "xla") == "pallas_ring":
            return opdriver.run_pallas_gather(
                global_arr, mesh, lead.root_src, nseg
            )
        return opdriver.run_gather(global_arr, mesh, lead.root_src)
    raise ValueError(op)  # pragma: no cover


def apply_tuning(tuning: dict, options) -> ErrorCode:
    """Validate + apply one SET_TUNING register write into a device-tier
    tuning table (shared by the gang and dist engines; identical checks
    to the emulator/native tiers)."""
    from ...constants import (
        ALGORITHM_TUNING_KEYS,
        AllreduceAlgorithm,
        ROOTED_ALGORITHMS,
        TUNING_KEY_NAMES,
        TuningKey,
    )

    try:
        key = TuningKey(int(options.cfg_key))
    except ValueError:
        return ErrorCode.CONFIG_ERROR
    val = options.cfg_value
    if val < 0:
        return ErrorCode.CONFIG_ERROR
    if key in ALGORITHM_TUNING_KEYS:
        try:
            algo = AllreduceAlgorithm(int(val))
        except ValueError:
            return ErrorCode.CONFIG_ERROR
        if (
            key != TuningKey.ALLREDUCE_ALGORITHM
            and algo not in ROOTED_ALGORITHMS
        ):
            return ErrorCode.CONFIG_ERROR
        tuning[TUNING_KEY_NAMES[key]] = algo.name.lower()
    elif key == TuningKey.RING_SEGMENTS:
        if int(val) < 1:
            return ErrorCode.CONFIG_ERROR
        tuning["ring_segments"] = int(val)
    else:
        if key == TuningKey.GATHER_FLAT_TREE_MAX_FANIN and val < 1:
            return ErrorCode.CONFIG_ERROR
        tuning[TUNING_KEY_NAMES[key]] = int(val)
    return ErrorCode.OK


def run_allreduce_with_tuning(global_arr, mesh, fn, wire_dtype, tuning):
    """Allreduce with algorithm + segmentation + wire compression from the
    tuning registers."""
    algo = tuning.get("allreduce_algorithm", "xla")
    nseg = int(tuning.get("ring_segments", 1))
    bidir = algo == "pallas_ring_bidir"
    if wire_dtype is not None:
        wire_name = dtype_to_numpy(wire_dtype).name
        if algo in ("pallas_ring", "pallas_ring_bidir"):
            # compression lanes run inside the kernel
            return opdriver.run_pallas_allreduce(
                global_arr, mesh, fn, nseg, wire_dtype=wire_name,
                bidirectional=bidir,
            )
        return opdriver.run_compressed_allreduce(
            global_arr, mesh, fn, wire_dtype=wire_name
        )
    if algo == "ring":
        return opdriver.run_ring_allreduce(global_arr, mesh, fn, nseg)
    if algo in ("pallas_ring", "pallas_ring_bidir"):
        return opdriver.run_pallas_allreduce(
            global_arr, mesh, fn, nseg, bidirectional=bidir
        )
    return opdriver.run_allreduce(global_arr, mesh, fn)


class _GangSlot:
    def __init__(self, world: int, timeout_s: float):
        self.calls: Dict[int, Tuple[CallOptions, Request]] = {}
        self.world = world
        self.deadline = time.monotonic() + timeout_s
        self.watchdog: Optional[threading.Timer] = None


class XLAGangContext:
    """Shared per-process rendezvous point for all rank handles on a mesh."""

    def __init__(self, mesh=None):
        self.mesh = mesh  # full mesh; sub-meshes derived per communicator
        self._lock = threading.Lock()
        self._slots: Dict[tuple, _GangSlot] = {}
        self._seq: Dict[Tuple[int, int], int] = {}  # (comm_id, rank) -> call #
        self._submeshes: Dict[int, object] = {}
        self.timeout_s = DEFAULT_TIMEOUT_S
        # assembled-global reuse: repeated calls on the same operand
        # buffers rebuild an identical sharded view, so cache it keyed by
        # shard identity (strong refs keep ids stable; identity re-checked
        # on hit).  Donating ops bypass this (donation would invalidate
        # the cached view).
        self._asm_cache: Dict[tuple, tuple] = {}
        # algorithm-selection tuning registers (the reference's runtime
        # flat-vs-tree threshold registers, accl.cpp:1198-1208):
        #   allreduce_algorithm: "xla" (XLA's scheduler picks),
        #   "ring" (explicit ppermute pipeline), "pallas_ring" (the
        #   Pallas remote-DMA kernel)
        self.tuning = {"allreduce_algorithm": "xla", "ring_segments": 1}

    # -- communicator -> mesh -----------------------------------------------
    def submesh(self, comm: Communicator):
        """Sub-mesh over the communicator's member devices — rank i of the
        communicator executes on the device of its *global* rank identity
        (``Rank.session``), so a subcommunicator of ranks {4..7} runs on
        devices 4-7, not 0-3.  None when the host has fewer devices than the
        membership needs — execution falls back to host numpy, the
        single-controller analog of the reference's emulator tier."""
        sessions = tuple(r.session for r in comm.ranks)
        if sessions in self._submeshes:
            return self._submeshes[sessions]
        devs = jax.devices()
        if max(sessions) < len(devs):
            from jax.sharding import Mesh

            mesh = Mesh([devs[s] for s in sessions], (opdriver.AXIS,))
        else:
            mesh = None
        self._submeshes[sessions] = mesh
        return mesh

    # -- gang assembly -------------------------------------------------------
    def submit(self, comm: Communicator, options: CallOptions, request: Request):
        with self._lock:
            seq_key = (comm.id, comm.local_rank)
            seq = self._seq.get(seq_key, 0)
            self._seq[seq_key] = seq + 1
            slot_key = (comm.id, seq)
            slot = self._slots.get(slot_key)
            arm = False
            if slot is None:
                slot = _GangSlot(comm.size, self.timeout_s)
                self._slots[slot_key] = slot
                arm = True  # exactly one watchdog per slot
            slot.calls[comm.local_rank] = (options, request)
            ready = len(slot.calls) == slot.world
            if ready:
                del self._slots[slot_key]
                if slot.watchdog is not None:
                    slot.watchdog.cancel()
        if ready:
            self._execute(comm, slot)
        elif arm:
            self._arm_watchdog(slot_key, slot)

    def soft_reset(self) -> None:
        """ref ``ACCL`` soft-reset recovery (accl.cpp:57-89): abandon all
        stale gang state so a world that lost a collective (e.g. one rank
        timed out while a peer never submitted) can realign.

        Collective by contract, like the reference's: every rank handle
        issues CONFIG/RESET with no new collectives in flight; each call
        idempotently clears the shared tables, so after the last rank's
        reset all per-communicator sequence counters restart at 0 and the
        next collective matches at a fresh slot.  Any still-parked call is
        completed with RECEIVE_TIMEOUT (its gang never assembled)."""
        with self._lock:
            slots = list(self._slots.values())
            self._slots.clear()
            self._seq.clear()
            self._asm_cache.clear()
        for slot in slots:
            if slot.watchdog is not None:
                slot.watchdog.cancel()
            for _, req in slot.calls.values():
                if not req.test():
                    req.complete(ErrorCode.RECEIVE_TIMEOUT)

    def _arm_watchdog(self, slot_key, slot: _GangSlot) -> None:
        def fire():
            with self._lock:
                live = self._slots.get(slot_key) is slot
                if live:
                    del self._slots[slot_key]
            if live:
                for _, req in slot.calls.values():
                    req.complete(ErrorCode.RECEIVE_TIMEOUT)

        t = threading.Timer(max(0.01, slot.deadline - time.monotonic()), fire)
        t.daemon = True
        slot.watchdog = t
        t.start()

    # -- execution -----------------------------------------------------------
    def _execute(self, comm: Communicator, slot: _GangSlot) -> None:
        t0 = time.perf_counter_ns()
        calls = [slot.calls[r][0] for r in range(slot.world)]
        reqs = [slot.calls[r][1] for r in range(slot.world)]
        lead = calls[0]
        try:
            sig = lambda c: (
                c.op, c.count, c.reduce_function, c.root_src, c.root_dst,
                c.compression,
            )
            if any(sig(c) != sig(lead) for c in calls[1:]):
                code = ErrorCode.INVALID_OPERATION  # mismatched gang calls
            else:
                # named range in the xprof timeline (the per-call span the
                # reference's perf counter provides, SURVEY §5 tracing)
                with jax.profiler.TraceAnnotation(
                    f"accl::{lead.op.name.lower()}"
                ):
                    code = self._run_op(comm, calls, lead)
        except Exception:
            import traceback

            traceback.print_exc()
            code = ErrorCode.INVALID_OPERATION
        dt = time.perf_counter_ns() - t0
        for req in reqs:
            req.complete(code, dt)

    def _run_op(
        self, comm: Communicator, calls: List[CallOptions], lead: CallOptions
    ) -> ErrorCode:
        if lead.op == Operation.BARRIER:
            # gang assembly IS the barrier on this tier: reaching here means
            # every rank of the communicator posted the call in this process.
            # A multi-process gang must NOT reuse this (see backends/dist for
            # the cross-process barrier over the device mesh).
            return ErrorCode.OK
        mesh = self.submesh(comm)
        if mesh is not None:
            code = self._run_op_device(comm, calls, lead, mesh)
            if code is not None:
                return code
        return self._run_op_host(comm, calls, lead, mesh)

    # -- zero-host-copy device path ------------------------------------------
    def _run_op_device(
        self,
        comm: Communicator,
        calls: List[CallOptions],
        lead: CallOptions,
        mesh,
    ) -> Optional[ErrorCode]:
        """Run the collective entirely on device-resident operands.

        Every rank's operand must be a :class:`DeviceBuffer` committed to
        that rank's mesh device (dummies become on-device zeros); the
        per-rank arrays are assembled into ONE sharded global array with
        ``jax.make_array_from_single_device_arrays`` — zero copy — the
        jitted shard_map program runs over the mesh, and the output shards
        are adopted back into the result buffers.  The host never touches
        payload bytes, matching the reference's device-to-device hot path
        (``accl.cpp:780-826``).  Returns None to fall back to the
        host-staged path (mixed/host operands, exotic dtypes).
        """
        op = lead.op
        if op not in IN_W:
            return None
        size = comm.size
        n = lead.count
        if n <= 0:
            return None
        in_w = n * (size if IN_W[op] == "P" else 1)
        out_w = n * (size if OUT_W[op] == "P" else 1)
        devs = list(mesh.devices.flat)
        npdt = dtype_to_numpy(lead.arithcfg.uncompressed)
        compressed = bool(lead.compression & CompressionFlags.ETH_COMPRESSED)
        wire_npdt = (
            dtype_to_numpy(lead.arithcfg.compressed) if compressed else None
        )

        # which ranks' results get written
        if op in (Operation.REDUCE, Operation.GATHER):
            writers = {lead.root_dst if op == Operation.REDUCE else lead.root_src}
        else:
            writers = set(range(size))

        # validate operands + results device-resident before any work
        any_device = False
        for r, call in enumerate(calls):
            buf = call.op0
            if buf is not None and not buf.is_dummy:
                if not (
                    isinstance(buf, DeviceBuffer)
                    and buf.device == devs[r]
                    and buf.count >= in_w
                    and dtype_to_numpy(buf.dtype) == npdt
                ):
                    return None
                any_device = True
            if r in writers:
                res = call.res
                if res is None or res.is_dummy:
                    continue
                if not (
                    isinstance(res, DeviceBuffer)
                    and res.device == devs[r]
                    and res.count >= out_w
                    and dtype_to_numpy(res.dtype) == npdt
                ):
                    return None
        if not any_device:
            return None
        if op == Operation.BCAST and any(
            c.op0 is not c.res for c in calls
        ):
            # the donating bcast program consumes its operand; only safe for
            # the facade's in-place form (op0 IS res on every rank)
            return None

        from jax.sharding import NamedSharding, PartitionSpec

        # wire-dtype rounding before the op (the hp_compression lanes);
        # allreduce keeps this inside its program for a single rounding
        wire_name = (
            np.dtype(wire_npdt).name
            if wire_npdt is not None and op != Operation.ALLREDUCE
            else None
        )
        # flat 1-D global: each rank's shard is its raw HBM array whenever
        # the buffer width matches the call exactly (no per-rank prep
        # program, the dominant dispatch cost of the old (size, w) layout)
        shards = []
        raw_bufs: Optional[list] = []  # root buffers whose _dev went in raw
        for r, call in enumerate(calls):
            buf = call.op0
            if buf is None or buf.is_dummy:
                shards.append(_dev_zeros((in_w,), npdt, devs[r]))
                raw_bufs = None
                continue
            arr = buf.device_array()
            if (
                wire_name is None
                and arr.shape == (in_w,)
                and getattr(buf, "_parent", None) is None
            ):
                shards.append(arr)
                if raw_bufs is not None:
                    raw_bufs.append(buf)
            else:
                shards.append(_prep_program(in_w, wire_name, devs[r], True)(arr))
                raw_bufs = None
        # assembled-global reuse: keyed by the BUFFER identities (stable
        # across in-place loops, unlike shard ids), re-validated against
        # each buffer's current _dev; a stale entry is REPLACED under its
        # key, so repeated in-place calls can't accumulate dead entries.
        # Buffers are held by WEAKREF with eviction callbacks — the cached
        # global (which pins every shard's HBM) dies with its buffers, so
        # the cache never outlives what the application released.
        # Donating ops (bcast) bypass the cache entirely.
        cacheable = raw_bufs is not None and op != Operation.BCAST
        global_arr = None
        key = None
        if cacheable:
            key = (tuple(map(id, raw_bufs)), in_w)
            hit = self._asm_cache.get(key)
            if hit is not None:
                hit_bufs = [r() for r in hit[2]]
                if all(
                    b is hb for b, hb in zip(raw_bufs, hit_bufs)
                ) and all(
                    s is b._dev for s, b in zip(hit[1], raw_bufs)
                ):
                    global_arr = hit[0]
        if global_arr is None:
            global_arr = jax.make_array_from_single_device_arrays(
                (size * in_w,),
                NamedSharding(mesh, PartitionSpec(opdriver.AXIS)),
                shards,
            )
            if cacheable:
                if len(self._asm_cache) >= 64 and key not in self._asm_cache:
                    self._asm_cache.clear()

                def _evict(_ref, cache=self._asm_cache, key=key):
                    cache.pop(key, None)

                self._asm_cache[key] = (
                    global_arr,
                    shards,
                    [weakref.ref(b, _evict) for b in raw_bufs],
                )

        fn = lead.reduce_function
        if op == Operation.ALLREDUCE:
            wire = lead.arithcfg.compressed if compressed else None
            out = self._allreduce(global_arr, mesh, fn, wire)
        elif op in (
            Operation.REDUCE, Operation.BCAST, Operation.SCATTER,
            Operation.GATHER,
        ):
            if op == Operation.BCAST:
                # The donating bcast consumes shard arrays that may also
                # back cached assembled globals from earlier ops on the
                # same buffers.  JAX copy-on-donate keeps those entries
                # readable, but evict them anyway so no cache hit can ever
                # observe a donated (possibly aliased) array.
                donors = {
                    id(c.op0) for c in calls
                    if c.op0 is not None and not c.op0.is_dummy
                }
                stale = [
                    k for k, v in self._asm_cache.items()
                    if any(id(ref()) in donors for ref in v[2])
                ]
                for k in stale:
                    self._asm_cache.pop(k, None)
            out = self._run_rooted(op, global_arr, mesh, lead, donate=True)
        elif op == Operation.ALLGATHER:
            out = opdriver.run_allgather(global_arr, mesh)
        elif op == Operation.REDUCE_SCATTER:
            out = opdriver.run_reduce_scatter(global_arr, mesh, fn)
        elif op == Operation.ALLTOALL:
            out = opdriver.run_alltoall(global_arr, mesh)
        else:  # pragma: no cover - guarded by IN_W
            return None

        dev_to_rank = {d: r for r, d in enumerate(devs)}
        for shard in out.addressable_shards:
            r = dev_to_rank.get(shard.device)
            if r is None or r not in writers:
                continue
            res = calls[r].res
            if res is None or res.is_dummy:
                continue
            # flat layout: the (out_w,) shard adopts straight into the
            # buffer (pointer swap when widths match — no trim program)
            res.store(shard.data, out_w)
        return ErrorCode.OK

    def _run_rooted(self, op, global_arr, mesh, lead, donate=False):
        return run_rooted_with_tuning(
            op, global_arr, mesh, lead, self.tuning, donate=donate
        )

    # -- host-staged fallback path -------------------------------------------
    def _run_op_host(
        self,
        comm: Communicator,
        calls: List[CallOptions],
        lead: CallOptions,
        mesh,
    ) -> ErrorCode:
        op = lead.op
        size = comm.size
        fn = lead.reduce_function
        n = lead.count
        compressed = bool(lead.compression & CompressionFlags.ETH_COMPRESSED)
        wire_npdt = (
            dtype_to_numpy(lead.arithcfg.compressed) if compressed else None
        )

        def wire_cast(arr: np.ndarray) -> np.ndarray:
            if wire_npdt is None:
                return arr
            return arr.astype(wire_npdt).astype(arr.dtype)

        if op == Operation.ALLREDUCE:
            # no host-side pre-cast here: the compressed program casts to the
            # requested wire dtype itself (single rounding, on device)
            stacked = _np_stack_op0(calls, [n] * size)
            wire = lead.arithcfg.compressed if compressed else None
            out = self._allreduce(stacked, mesh, fn, wire)
            out = np.asarray(out)
            for r, call in enumerate(calls):
                _write_host_result(call.res, out[r], n)
            return ErrorCode.OK

        if op == Operation.REDUCE:
            stacked = wire_cast(_np_stack_op0(calls, [n] * size))
            out = np.asarray(
                self._run_rooted(op, stacked, mesh, lead)
                if mesh is not None
                else self._host_reduce(stacked, fn)[None].repeat(size, 0)
            )
            root = lead.root_dst
            res = calls[root].res
            if res is not None and not res.is_dummy:
                _write_host_result(res, out[root], n)
            return ErrorCode.OK

        if op == Operation.BCAST:
            stacked = wire_cast(_np_stack_op0(calls, [n] * size))
            out = np.asarray(
                self._run_rooted(op, stacked, mesh, lead)
                if mesh is not None
                else stacked[lead.root_src][None].repeat(size, 0)
            )
            for r, call in enumerate(calls):
                _write_host_result(call.res, out[r], n)
            return ErrorCode.OK

        if op == Operation.ALLGATHER:
            stacked = wire_cast(_np_stack_op0(calls, [n] * size))
            out = np.asarray(
                opdriver.run_allgather(stacked, mesh)
                if mesh is not None
                else stacked.reshape(-1)[None].repeat(size, 0)
            )
            for r, call in enumerate(calls):
                _write_host_result(call.res, out[r], size * n)
            return ErrorCode.OK

        if op == Operation.REDUCE_SCATTER:
            stacked = wire_cast(_np_stack_op0(calls, [size * n] * size))
            out = np.asarray(
                opdriver.run_reduce_scatter(stacked, mesh, fn)
                if mesh is not None
                else self._host_reduce(stacked, fn).reshape(size, n)
            )
            for r, call in enumerate(calls):
                _write_host_result(call.res, out[r][:n], n)
            return ErrorCode.OK

        if op == Operation.SCATTER:
            root = lead.root_src
            stacked = wire_cast(_np_stack_op0(calls, [size * n] * size))
            out = np.asarray(
                self._run_rooted(op, stacked, mesh, lead)
                if mesh is not None
                else stacked[root].reshape(size, n)
            )
            for r, call in enumerate(calls):
                _write_host_result(call.res, out[r], n)
            return ErrorCode.OK

        if op == Operation.GATHER:
            root = lead.root_src
            stacked = wire_cast(_np_stack_op0(calls, [n] * size))
            out = np.asarray(
                self._run_rooted(op, stacked, mesh, lead)
                if mesh is not None
                else stacked.reshape(-1)[None].repeat(size, 0)
            )
            res = calls[root].res
            if res is not None and not res.is_dummy:
                _write_host_result(res, out[root], size * n)
            return ErrorCode.OK

        if op == Operation.ALLTOALL:
            stacked = wire_cast(_np_stack_op0(calls, [size * n] * size))
            out = np.asarray(
                opdriver.run_alltoall(stacked, mesh)
                if mesh is not None
                else stacked.reshape(size, size, n).transpose(1, 0, 2).reshape(
                    size, size * n
                )
            )
            for r, call in enumerate(calls):
                _write_host_result(call.res, out[r], size * n)
            return ErrorCode.OK

        return ErrorCode.COLLECTIVE_NOT_IMPLEMENTED

    def _allreduce(self, stacked, mesh, fn, wire_dtype):
        if mesh is None:
            if wire_dtype is not None:
                npdt = dtype_to_numpy(wire_dtype)
                stacked = stacked.astype(npdt).astype(stacked.dtype)
            return self._host_reduce(stacked, fn)[None].repeat(stacked.shape[0], 0)
        return run_allreduce_with_tuning(
            stacked, mesh, fn, wire_dtype, self.tuning
        )

    @staticmethod
    def _host_reduce(stacked: np.ndarray, fn: ReduceFunction) -> np.ndarray:
        return (
            stacked.sum(axis=0, dtype=stacked.dtype)
            if fn == ReduceFunction.SUM
            else stacked.max(axis=0)
        )


# p2p pairing: send/recv matched by (comm, tag, src, dst) independent of the
# collective gang sequence.  Receivers register a *sink* callable so the same
# channel serves buffer receives and recv-to-stream.  Unmatched posts carry a
# watchdog honoring the engine timeout (the firmware's per-call deadline);
# delivery — which may jit the fabric-hop program — runs OUTSIDE the channel
# lock so unrelated pairs never serialize behind a compile.
class _P2PChannel:
    """Tag-matched send/recv rendezvous between rank engines.

    Durations are MEASURED, not sentinels: each post is stamped at entry
    and each request completes with post->delivery wall-clock ns — the
    analog of the reference's per-call device-cycle reads that its
    sendrecv bench is built on (ref xrtdevice.cpp:242-249 get_duration,
    bench.cpp:25-31).  A parked side therefore reports its true wait
    (including the partner's late arrival); the late-arriving side
    reports roughly the delivery/copy cost alone."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sends: Dict[tuple, list] = {}
        self._recvs: Dict[tuple, list] = {}

    def post_send(self, key, payload, request, timeout_s=None):
        t0 = time.perf_counter_ns()
        match = None
        with self._lock:
            if self._recvs.get(key):
                sink, rreq, rtimer, rt0 = self._recvs[key].pop(0)
                if rtimer is not None:
                    rtimer.cancel()
                match = (sink, rreq, rt0)
            else:
                self._park(self._sends, key, [payload, request], timeout_s, t0)
        if match is not None:
            self._deliver(match[0], match[1], payload, request, match[2], t0)

    def post_recv(self, key, sink, request, timeout_s=None):
        t0 = time.perf_counter_ns()
        match = None
        with self._lock:
            if self._sends.get(key):
                payload, sreq, stimer, st0 = self._sends[key].pop(0)
                if stimer is not None:
                    stimer.cancel()
                match = (payload, sreq, st0)
            else:
                self._park(self._recvs, key, [sink, request], timeout_s, t0)
        if match is not None:
            self._deliver(sink, request, match[0], match[1], t0, match[2])

    def _park(self, table, key, entry, timeout_s, t0) -> None:
        """Append an unmatched post (caller holds the lock), arming a
        timeout watchdog when requested."""
        entry.append(None)
        entry.append(t0)
        if timeout_s:
            code = (
                ErrorCode.SEND_TIMEOUT
                if table is self._sends
                else ErrorCode.RECEIVE_TIMEOUT
            )
            t = threading.Timer(
                timeout_s, self._expire, (table, key, entry, code)
            )
            t.daemon = True
            entry[2] = t
            t.start()
        table.setdefault(key, []).append(entry)

    def _expire(self, table, key, entry, code) -> None:
        with self._lock:
            # identity-based scan: payloads are arrays, so `in`/`remove`
            # would trip elementwise ==
            lst = table.get(key, [])
            idx = next((i for i, e in enumerate(lst) if e is entry), None)
            if idx is None:
                return  # matched in the meantime: nothing to do
            del lst[idx]
        entry[1].complete(code, time.perf_counter_ns() - entry[3])

    @staticmethod
    def _deliver(sink, rreq: Request, payload: np.ndarray, sreq,
                 recv_t0: int, send_t0: int):
        try:
            sink(payload)
        except Exception:
            t1 = time.perf_counter_ns()
            rreq.complete(ErrorCode.INVALID_OPERATION, max(t1 - recv_t0, 1))
            sreq.complete(ErrorCode.INVALID_OPERATION, max(t1 - send_t0, 1))
            return
        t1 = time.perf_counter_ns()
        rreq.complete(ErrorCode.OK, max(t1 - recv_t0, 1))
        sreq.complete(ErrorCode.OK, max(t1 - send_t0, 1))


class XLAEngine(StreamPortMixin, BaseEngine):
    """One rank handle's engine over a shared gang context.

    Local ops (copy/combine) execute immediately with jax.numpy on the
    default device; collectives rendezvous at the gang; p2p pairs match in
    the channel (the ICI transfer being a collective-permute is an XLA
    scheduling detail once both sides have arrived)."""

    def __init__(
        self,
        gang: XLAGangContext,
        p2p: Optional[_P2PChannel] = None,
        peers: Optional[Dict[int, "XLAEngine"]] = None,
        device=None,
    ):
        self.gang = gang
        self.p2p = p2p or _P2PChannel()
        self.peers = peers if peers is not None else {}
        self.device = device  # this rank's chip; buffers commit to its HBM
        self.timeout_s = DEFAULT_TIMEOUT_S
        self.max_eager_size = 32 * 1024
        self.max_rendezvous_size = MAX_EAGER_SIZE_LIMIT
        self._init_streams()

    def start(self, options: CallOptions) -> Request:
        req = Request(op_name=options.op.name)
        req.mark_executing()
        op = options.op
        if op == Operation.CONFIG:
            req.complete(self._apply_config(options))
        elif op == Operation.NOP:
            req.complete(ErrorCode.OK)
        elif op in (Operation.COPY, Operation.COMBINE):
            if options.stream & StreamFlags.OP0_STREAM:
                # streaming operand arrives asynchronously from a device
                # kernel: wait for it off the caller's thread
                self._spawn_completing(
                    lambda: req.complete(self._local_op(options)), req
                )
            else:
                req.complete(self._local_op(options))
        elif op == Operation.REDUCE and options.stream != StreamFlags.NO_STREAM:
            # stream-operand reduce (ref accl.hpp:514-590): bridge the
            # stream ports onto the gang off-thread
            self._spawn_completing(
                lambda: self._gang_with_streams(options, req), req
            )
        elif op == Operation.SEND:
            self._start_send(options, req)
        elif op == Operation.RECV:
            comm = options.comm
            # p2p keys use *global* rank identities (Rank.session) so that
            # subcommunicator traffic reaches the right engine
            src_world = comm.ranks[options.root_src].session
            me_world = comm.ranks[comm.local_rank].session
            key = (comm.id, options.tag, src_world, me_world)
            if options.stream & StreamFlags.RES_STREAM:
                sink = lambda payload: self.stream_push(
                    options.stream_id, np.asarray(payload).tobytes()
                )
            else:

                def sink(payload, call=options):
                    if isinstance(payload, jax.Array) and isinstance(
                        call.res, DeviceBuffer
                    ):
                        # both ends device-resident: ride the fabric
                        _p2p_device_deliver(payload, call.res, call.count)
                        return
                    if isinstance(payload, jax.Array):
                        payload = np.asarray(payload)  # host-side receiver
                    _write_host_result(call.res, payload, call.count)

            self.p2p.post_recv(key, sink, req, timeout_s=self.timeout_s)
        else:
            self.gang.submit(options.comm, options, req)
        return req

    def _start_send(self, options: CallOptions, req: Request) -> None:
        """SEND with all four operand routings: buffer/local-stream source x
        tag-matched/remote-stream destination (emulator parity:
        algorithms.op_send)."""
        comm = options.comm

        def resolve_and_route():
            t0 = time.perf_counter_ns()
            cfg = options.arithcfg
            if options.stream & StreamFlags.OP0_STREAM:
                payload = self._pop_stream_payload(options)
                if payload is None:
                    req.complete(ErrorCode.DMA_TIMEOUT)
                    return
            elif isinstance(options.op0, DeviceBuffer) and not (
                options.stream & StreamFlags.RES_STREAM
            ):
                # device-resident send: post the payload as a committed
                # jax.Array (a fresh device copy, so the sender may free or
                # overwrite its buffer immediately); the matched receiver
                # moves it over the fabric with a collective-permute
                src_dev = options.op0.device
                payload = _trim_program(options.count, src_dev)(
                    options.op0.device_array()
                )
                if options.compression & CompressionFlags.ETH_COMPRESSED:
                    # compress lane on the sending chip: the wire (and the
                    # ICI hop) carries the narrow dtype
                    payload = _cast_program(
                        dtype_to_numpy(cfg.compressed), src_dev
                    )(payload)
            else:
                payload = np.asarray(
                    options.op0.device_view()[: options.count]
                ).copy()
            if isinstance(payload, np.ndarray) and (
                options.compression & CompressionFlags.ETH_COMPRESSED
            ):
                payload = payload.astype(dtype_to_numpy(cfg.compressed))
            dst_world = comm.ranks[options.root_dst].session
            me_world = comm.ranks[comm.local_rank].session
            if options.stream & StreamFlags.RES_STREAM:
                peer = self.peers.get(dst_world)
                if peer is None:
                    req.complete(ErrorCode.TRANSPORT_ERROR)
                else:
                    peer.stream_push(options.stream_id, payload.tobytes())
                    req.complete(
                        ErrorCode.OK, max(time.perf_counter_ns() - t0, 1)
                    )
                return
            key = (comm.id, options.tag, me_world, dst_world)
            self.p2p.post_send(key, payload, req, timeout_s=self.timeout_s)

        if options.stream & StreamFlags.OP0_STREAM:
            # operand arrives asynchronously from a device kernel: wait for
            # it off the caller's thread (the emulator parks in its scheduler)
            self._spawn_completing(resolve_and_route, req)
        else:
            resolve_and_route()

    def _spawn_completing(self, fn, req: Request) -> None:
        """Run ``fn`` on a daemon thread; an escaping exception completes
        the request with an error instead of leaving the caller waiting
        forever (the scheduler-level guard the emulator tier has)."""

        def run():
            try:
                fn()
            except Exception:
                import traceback

                traceback.print_exc()
                if not req.test():
                    req.complete(ErrorCode.INVALID_OPERATION)

        threading.Thread(target=run, daemon=True).start()

    def _gang_with_streams(self, options: CallOptions, req: Request) -> None:
        """Stream-operand collective: pull OP0 from the stream port, run
        the gang collective on a host-staged temp, deliver the root result
        back to the stream port."""
        import dataclasses

        opts = options
        if opts.stream & StreamFlags.OP0_STREAM:
            payload = self._pop_stream_payload(opts)
            if payload is None:
                req.complete(ErrorCode.DMA_TIMEOUT)
                return
            acc_npdt = dtype_to_numpy(opts.arithcfg.uncompressed)
            tmp = EmuBuffer.from_array(payload.astype(acc_npdt))
            tmp.sync_to_device()
            opts = dataclasses.replace(
                opts, op0=tmp, stream=opts.stream & ~StreamFlags.OP0_STREAM
            )
        res_to_stream = bool(opts.stream & StreamFlags.RES_STREAM)
        tmp_res = None
        if res_to_stream:
            is_root = opts.comm.local_rank == opts.root_dst
            tmp_res = (
                EmuBuffer(opts.count, opts.arithcfg.uncompressed)
                if is_root
                else DummyBuffer(0, opts.arithcfg.uncompressed)
            )
            opts = dataclasses.replace(
                opts, res=tmp_res,
                stream=opts.stream & ~StreamFlags.RES_STREAM,
            )
        inner = Request(op_name=opts.op.name)
        inner.mark_executing()
        self.gang.submit(opts.comm, opts, inner)
        inner.wait()  # gang watchdog bounds this
        code = inner.get_retcode()
        if (
            code == ErrorCode.OK
            and res_to_stream
            and not tmp_res.is_dummy
        ):
            self._push_stream_result(options, tmp_res.device_view())
        req.complete(code, inner.get_duration_ns())

    def _local_op(self, options: CallOptions) -> ErrorCode:
        n = options.count
        if options.stream & StreamFlags.OP0_STREAM:
            payload = self._pop_stream_payload(options)
            if payload is None:
                return ErrorCode.DMA_TIMEOUT
            acc = payload.astype(
                dtype_to_numpy(options.arithcfg.uncompressed)
            )
            if options.op == Operation.COMBINE:
                other = np.asarray(options.op1.device_view()[:n])
                if options.reduce_function == ReduceFunction.SUM:
                    acc = acc + other
                elif options.reduce_function == ReduceFunction.MAX:
                    acc = np.maximum(acc, other)
                else:
                    return ErrorCode.ARITH_ERROR
            if options.stream & StreamFlags.RES_STREAM:
                self._push_stream_result(options, acc)
            else:
                _write_host_result(options.res, acc, n)
            return ErrorCode.OK
        if options.stream & StreamFlags.RES_STREAM:
            src = np.asarray(options.op0.device_view()[:n])
            if options.op == Operation.COMBINE:
                other = np.asarray(options.op1.device_view()[:n])
                if options.reduce_function == ReduceFunction.SUM:
                    src = src + other
                elif options.reduce_function == ReduceFunction.MAX:
                    src = np.maximum(src, other)
                else:
                    return ErrorCode.ARITH_ERROR
            self._push_stream_result(options, src)
            return ErrorCode.OK
        bufs = [options.op0, options.res]
        if options.op == Operation.COMBINE:
            bufs.insert(1, options.op1)
        if all(isinstance(b, DeviceBuffer) for b in bufs) and len(
            {b.device for b in bufs}
        ) == 1:
            # all-device fast path: compute on the owning chip, adopt the
            # result — the reference's DMA-loopback copy/combine with no
            # host in the loop
            src = options.op0.device_array()[:n]
            if options.op == Operation.COMBINE:
                other = options.op1.device_array()[:n]
                if options.reduce_function == ReduceFunction.SUM:
                    out = src + other
                elif options.reduce_function == ReduceFunction.MAX:
                    out = jnp.maximum(src, other)
                else:
                    return ErrorCode.ARITH_ERROR
            else:
                # force a distinct array: a full-count slice returns the
                # IDENTICAL jax.Array, and sharing storage would make a later
                # free_buffer() on either buffer delete the other's data
                out = jnp.copy(src)
            res_npdt = dtype_to_numpy(options.res.dtype)
            if out.dtype != res_npdt:
                out = out.astype(res_npdt)  # cross-dtype copy/combine
            options.res.store(out, n)
            return ErrorCode.OK
        src = jnp.asarray(options.op0.device_view()[:n])
        if options.op == Operation.COMBINE:
            other = jnp.asarray(options.op1.device_view()[:n])
            if options.reduce_function == ReduceFunction.SUM:
                out = src + other
            elif options.reduce_function == ReduceFunction.MAX:
                out = jnp.maximum(src, other)
            else:
                return ErrorCode.ARITH_ERROR
        else:
            out = src
        _write_host_result(options.res, np.asarray(out), n)
        return ErrorCode.OK

    def _apply_config(self, options: CallOptions) -> ErrorCode:
        fn = ConfigFunction(options.cfg_function)
        val = options.cfg_value
        if fn == ConfigFunction.RESET:
            self.gang.soft_reset()
        elif fn == ConfigFunction.SET_TIMEOUT:
            if val <= 0:
                return ErrorCode.CONFIG_ERROR
            self.timeout_s = float(val)
            self.gang.timeout_s = float(val)
        elif fn == ConfigFunction.SET_MAX_EAGER_SIZE:
            if not 0 < val <= MAX_EAGER_SIZE_LIMIT:
                return ErrorCode.CONFIG_ERROR
            self.max_eager_size = int(val)
        elif fn == ConfigFunction.SET_MAX_RENDEZVOUS_SIZE:
            if val <= 0:
                return ErrorCode.CONFIG_ERROR
            self.max_rendezvous_size = int(val)
        elif fn == ConfigFunction.SET_TUNING:
            return self._apply_tuning(options)
        return ErrorCode.OK

    def _apply_tuning(self, options: CallOptions) -> ErrorCode:
        return apply_tuning(self.gang.tuning, options)

    def create_buffer(self, count: int, dtype, host_only: bool = False,
                      data=None):
        """HBM-resident DeviceBuffer on this rank's chip; host-only
        buffers (and device-less fallback ranks) stay host pairs."""
        return make_buffer(
            self.device, count, dtype, host_only=host_only, data=data
        )

    def shutdown(self) -> None:
        pass
