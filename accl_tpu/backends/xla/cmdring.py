"""The gang engine's command-ring sessions: arm / refill / teardown.

This is the host half of the TPU CCLO analog (the device half is
``ops/pallas/cmdring.py``, the mailbox protocol ``accl_tpu/cmdring.py``):
host code that used to *issue* collectives becomes code that *refills a
queue*.  A warm batched window of N eligible collectives is encoded
into N slots of the per-communicator ring and handed to the
**persistent sequencer**:

* first window of a burst: ONE program dispatch arms a sequencer *run*
  (``dispatches`` counter) and the window rides it;
* every further window while the run is live: a **mailbox post** — the
  doorbell is a host memory write, zero program launches
  (``mailbox_posts`` counter).  A warm sustained stream of K windows
  therefore executes with 0 re-dispatches after the first
  (counter-asserted by tests/test_cmdring.py), which is the reference
  firmware's actual execution model: the run loop lives on the device
  and the host only writes commands into the FIFO.

The opcode space is the FULL warm set (``constants.CMDRING_OPCODES``):
allreduce, bcast, reduce-scatter, allgather, alltoall, barrier, and
matched send/recv pairs; compressed (wire-cast) windows ride the ring
with the cast lowered into the decode loop, and f16 windows ride the
f32 compute view.  Everything else — cold calls, oversized payloads,
host operands, mixed dtypes, unpaired p2p — falls back to the ordinary
host-dispatch paths with the reason counted in
:meth:`GangCommandRing.stats`.

Lifecycle (the ``run loop`` states of the reference firmware):

* **parked** — no run accepting, no window in flight: the sequencer
  program has returned and the device stream is free (no spin, no
  occupancy).  The next refill re-arms with one dispatch.
* **resident** — a run is live and lingering on the mailbox; a refill
  is a doorbell write.
* **armed** — windows in flight; the in-flight window
  (``overlap.InflightWindow``) is the refill window: its drain points
  block on the device status words the sequencer pushed.
* **teardown/reset** — ``soft_reset`` halts every run's mailbox (the
  ``HALT`` opcode marks this transition in the slot schema), clears
  every session and realigns seqn/head at 0.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ...cmdring import (
    SequencerMailbox,
    WindowShape,
    complementary_pair,
    default_linger_s,
    default_run_windows,
    encode_fparam,
    encode_slot,
    fused_slot_eligible,
    register_mailbox,
    ring_widths,
    unregister_mailbox,
)
from ...constants import (
    CMDRING_DEPTH_DEFAULT,
    CMDRING_DEPTH_ENV,
    CMDRING_ENV,
    CMDRING_FIELDS,
    CMDRING_FUSED_OPCODES,
    CMDRING_MAX_BYTES_ENV,
    CMDRING_MAX_DEPTH,
    CMDRING_MAX_PAYLOAD_BYTES,
    CMDRING_OPCODES,
    CMDRING_ST_OK,
    ErrorCode,
    FusedCompute,
    Operation,
    dtype_to_numpy,
)
from ...membership import CircuitBreaker
from ...overlap import drain_deadline_s

_F = CMDRING_FIELDS

#: ring-session circuit breaker (membership plane): window failures
#: against a dying peer strike the per-comm breaker; OPEN degrades the
#: comm's dispatch ring -> host (counted ``circuit_open``), HALF_OPEN
#: re-probes with an INLINE window (one-shot program, no persistent
#: run to wedge) after the cool-down, success restores the ring.
CMDRING_BREAKER_COOLDOWN_ENV = "ACCL_CMDRING_COOLDOWN_S"
CMDRING_BREAKER_COOLDOWN_S = 2.0
CMDRING_BREAKER_THRESHOLD = 2

#: ops whose operand/result widths scale with world size ('P' slots)
_P_WIDE = (Operation.REDUCE_SCATTER, Operation.ALLTOALL)


def _env_mode() -> str:
    return os.environ.get(CMDRING_ENV, "1").strip().lower()


#: opcode word chaos poisoning writes into a refill's first slot —
#: out of every lowering's opcode range, so the sequencer reports
#: BAD_OP and the slot fails fast with INVALID_OPERATION
_CHAOS_BAD_OPCODE = 0x7F


class _RingMsgType:
    """Message-type token for ring-refill pseudo-messages shown to the
    fault injector (``FaultRule(msg_type="RING")`` matches them; int
    rules never do — the ring is not a wire MsgType)."""

    name = "RING"

    def __int__(self) -> int:
        return -1

    def __str__(self) -> str:
        return "RING"


_RING_MSG_TYPE = _RingMsgType()


class _RingRefillMsg:
    """One refill window as the fault injector sees it: the host encode
    (src 0) ringing the gang's doorbell.  ``dst`` is None — only
    wildcard-dst rules reach the ring path."""

    __slots__ = ("comm_id", "src", "dst", "tag", "msg_type", "seqn")

    def __init__(self, comm_id: int, seqn: int):
        self.comm_id = comm_id
        self.src = 0
        self.dst = None
        self.tag = 0
        self.msg_type = _RING_MSG_TYPE
        self.seqn = seqn


def default_lowering() -> str:
    """Sequencer lowering: the Pallas remote-DMA mega-window kernel on a
    real TPU, the persistent XLA session everywhere else (the
    emulator/CI tier).  Override with ``ACCL_CMDRING_LOWERING``."""
    explicit = os.environ.get("ACCL_CMDRING_LOWERING")
    if explicit in ("xla", "pallas"):
        return explicit
    import jax

    return "pallas" if jax.default_backend() == "tpu" else "xla"


class _RowAdopter:
    """Deferred host-row adoption with COLLAPSING: park the result
    placement on the buffer (the PR 1 lazy-adoption discipline) so a
    fire-and-forget window never pays the writeback at completion
    time — and when a later ring window writes the SAME buffer before
    anyone read it, update the parked row in place instead of chaining
    another thunk.  A warm stream writing one result buffer K times
    otherwise replays K chained stores (K device interactions) at
    first read.  Collapsing is allowed ONLY when no other deferred
    write slipped in between (the buffer's ``_defer_seq`` proves it) —
    partial/foreign writes must keep layering in issue order."""

    def __init__(self, gang):
        self._gang = gang
        self._lock = threading.Lock()
        self._gen = 0
        # (root id, arm generation) -> (buf, row, n): every armed thunk
        # owns its own generation slot, so an interleaved foreign defer
        # can never make an EARLIER thunk drain a LATER generation's row
        self._rows: Dict[tuple, tuple] = {}
        self._armed: Dict[int, tuple] = {}  # root id -> (defer_seq, gen)
        # one weakref per tracked root, with an eviction callback: a
        # buffer dropped with its deferred store unresolved must not
        # strand its parked row (unbounded growth over a fire-and-
        # forget loop), and a recycled id(root) must never match a dead
        # buffer's stale entries (the callback runs before the id can
        # be reused)
        self._reaper: Dict[int, object] = {}

    def _track(self, root, key: int) -> None:
        """Caller holds self._lock."""
        if key in self._reaper:
            return
        import weakref

        def evict(_ref, self=self, key=key):
            with self._lock:
                self._reaper.pop(key, None)
                self._armed.pop(key, None)
                for k in [k for k in self._rows if k[0] == key]:
                    self._rows.pop(k, None)

        self._reaper[key] = weakref.ref(root, evict)

    def adopt(self, buf, row: np.ndarray, n: int) -> None:
        root = buf._root()
        key = id(root)
        with root._plock:
            with self._lock:
                self._track(root, key)
                armed = self._armed.get(key)
                if armed is not None and armed[0] == root._defer_seq:
                    parked = self._rows.get((key, armed[1]))
                    # collapse ONLY a rewrite of the SAME destination
                    # region (same buffer object, same width): two ring
                    # writes to different slices of one root must
                    # layer, not replace each other
                    if (
                        parked is not None
                        and parked[0] is buf
                        and parked[2] == n
                    ):
                        self._rows[(key, armed[1])] = (buf, row, n)
                        return
                self._gen += 1
                gen = self._gen
                self._rows[(key, gen)] = (buf, row, n)

            def place(self=self, key=key, gen=gen):
                with self._lock:
                    parked = self._rows.pop((key, gen), None)
                    if (
                        self._armed.get(key) is not None
                        and self._armed[key][1] == gen
                    ):
                        self._armed.pop(key, None)
                if parked is not None:
                    from .engine import _write_host_result

                    _write_host_result(
                        parked[0], parked[1], parked[2],
                        self._gang.interactions,
                    )

            buf.defer_store(place)
            with self._lock:
                self._armed[key] = (root._defer_seq, gen)


class _WindowPark:
    """One in-flight refill window's completion record (the status-FIFO
    side of the mailbox protocol)."""

    __slots__ = ("window_id", "event", "status", "results", "plans",
                 "reqs_per_slot", "calls_per_slot", "t0", "settled",
                 "slots_info", "form", "logged")

    def __init__(self, window_id: int, plans, reqs_per_slot,
                 calls_per_slot, t0):
        self.window_id = window_id
        self.event = threading.Event()
        self.status: Optional[np.ndarray] = None
        self.results: Optional[dict] = None
        self.plans = plans
        self.reqs_per_slot = reqs_per_slot
        self.calls_per_slot = calls_per_slot
        self.t0 = t0
        # session bookkeeping (written-ledger decrement, last_status)
        # done exactly once, by whichever completion path ran
        self.settled = False
        # introspection: per-slot facts for the window log (seqn,
        # opcode, the issuing call's trace id), the dispatch form
        # (inline / mailbox), and the logged-once latch
        self.slots_info: list = []
        self.form = "inline"
        self.logged = False


class _ResidentRun:
    """One live sequencer run: its mailbox, the dispatch thread that
    owns the long-running program, and the failure latch.

    The program is dispatched from a dedicated ``accl-cmdring-run``
    thread: XLA executes callback-bearing programs synchronously on the
    dispatching thread (single-device CPU meshes always; others per
    runtime), and the refill path must never become the run loop — the
    host's doorbell returns immediately whatever the runtime does.  The
    thread exists per RUN, not per window: a warm sustained stream of K
    windows costs one thread spawn, the same amortization as the one
    dispatch."""

    __slots__ = ("mbox", "mbox_id", "shape", "thread", "failed", "exc")

    def __init__(self, mbox, mbox_id, shape):
        self.mbox = mbox
        self.mbox_id = mbox_id
        self.shape = shape
        self.thread: Optional[threading.Thread] = None
        self.failed = threading.Event()
        self.exc: Optional[BaseException] = None

    def launch(self, mesh, run_windows: int) -> None:
        from ...ops.pallas import cmdring as devring

        def drive(self=self, mesh=mesh, run_windows=run_windows):
            try:
                handle = devring.run_session(
                    mesh, self.shape, self.mbox_id, run_windows
                )
                import jax

                jax.block_until_ready(handle)
            except BaseException as e:  # surface to every parked window
                self.exc = e
                self.failed.set()
                self.mbox.halt()
                import traceback

                traceback.print_exc()

        t = threading.Thread(
            target=drive, name="accl-cmdring-run", daemon=True
        )
        self.thread = t
        t.start()


class _RingSession:
    """Per-communicator ring state: the persistent host mirror of the
    device ring (wrap-around is real — slot i of refill k+1 reuses the
    words of slot i of refill k-depth), the monotone seqn, the live
    resident run, and the cross-window write-dependency ledger."""

    __slots__ = ("ring", "head", "seqn", "run", "parks", "written",
                 "next_window", "last_status")

    def __init__(self, depth: int):
        from ...constants import CMDRING_SLOT_WORDS

        self.ring = np.zeros((depth, CMDRING_SLOT_WORDS), np.int32)
        self.head = 0
        self.seqn = 0
        self.run: Optional[_ResidentRun] = None
        self.parks: List[_WindowPark] = []   # outstanding, refill order
        self.written: Dict[int, int] = {}    # result-root id -> pending
        self.next_window = 0
        self.last_status: Optional[np.ndarray] = None


class GangCommandRing:
    """One gang context's command ring (all communicators' sessions)."""

    def __init__(self, gang):
        self.gang = gang
        mode = _env_mode()
        self.enabled = mode not in ("0", "off", "false", "")
        self.eager = mode == "eager"
        try:
            depth = int(
                os.environ.get(CMDRING_DEPTH_ENV, CMDRING_DEPTH_DEFAULT)
            )
        except ValueError:
            depth = CMDRING_DEPTH_DEFAULT
        self.depth = max(1, min(depth, CMDRING_MAX_DEPTH))
        try:
            self.max_bytes = int(
                os.environ.get(
                    CMDRING_MAX_BYTES_ENV, CMDRING_MAX_PAYLOAD_BYTES
                )
            )
        except ValueError:
            self.max_bytes = CMDRING_MAX_PAYLOAD_BYTES
        self.lowering = default_lowering()
        self.run_windows = default_run_windows()
        self.linger_s = default_linger_s()
        self._lock = threading.Lock()
        self._sessions: Dict[int, _RingSession] = {}
        self._inflight_windows = 0
        # cached committed zeros shards for token/dummy slots (barrier,
        # the p2p pair's non-source ranks): first use dispatches the
        # zeros program (counted), warm windows reuse with no dispatch
        self._zeros: Dict[tuple, object] = {}
        # collapsing deferred adoption for mailbox-window results
        self._adopter = _RowAdopter(gang)
        self._drained_runs: List[_ResidentRun] = []  # awaiting unregister
        # lifetime counters (telemetry_report()["cmdring"]).  One
        # counter backs both the refill and doorbell stats keys: every
        # refill rings the doorbell exactly once (as a program dispatch
        # arming a run, or as a mailbox post into a live one).
        self.refills = 0          # refill windows (= doorbells)
        self.dispatches = 0       # sequencer program launches (runs)
        self.mailbox_posts = 0    # refills that rode a live run
        self.slots_enqueued = 0   # collectives executed ring-resident
        self.wraps = 0            # head wrapped past the ring depth
        self.resets = 0           # soft_reset teardowns (runs halted)
        self.max_window = 0
        self.last_window = 0
        self.op_slots: Dict[str, int] = {}  # per-opcode residency
        self.fallbacks: Dict[str, int] = {}
        # introspection plane: a bounded log of completed windows
        # (per-slot seqn/opcode/retcode/trace-id next to the host-side
        # timing — basis "host": neither lowering can write a device
        # clock next to the status word on this mesh, and the snapshot
        # says so instead of faking device time), a window-latency
        # log2-us histogram, and the facade's failure hook (postmortem
        # plane: run latch / drain deadline / dispatch error)
        from collections import deque as _deque

        try:
            log_cap = int(os.environ.get("ACCL_CMDRING_WINDOW_LOG", "64"))
        except ValueError:
            log_cap = 64
        self._window_log = _deque(maxlen=max(8, log_cap))
        self.windows_logged = 0
        self.window_latency: Dict[int, int] = {}
        self.window_latency_sum_us = 0.0
        self.on_failure = None
        # per-comm ring circuit breakers (membership plane): window
        # failures degrade that comm's dispatch ring -> inline -> host,
        # re-probing after a cool-down — a dying peer no longer needs a
        # full soft_reset to get the ring back
        try:
            cooldown = float(os.environ.get(
                CMDRING_BREAKER_COOLDOWN_ENV, CMDRING_BREAKER_COOLDOWN_S
            ))
        except ValueError:
            cooldown = CMDRING_BREAKER_COOLDOWN_S
        self.breaker_cooldown_s = cooldown
        self._breakers: Dict[int, CircuitBreaker] = {}
        # QoS arbiter plane (SET_TENANT_RING_SLOTS): per-comm slot
        # budgets — a budgeted tenant's warm batches chunk into refill
        # windows of at most its budget, so a flooder pays extra
        # doorbells instead of monopolizing whole ring windows.  Plus
        # per-comm slot residency totals, the counter the fairness
        # tests assert ring-share against.
        self._slot_budgets: Dict[int, int] = {}
        self.comm_slots: Dict[int, int] = {}
        self.budgeted_windows = 0
        # chaos plane: per-action counts of fault-injector verdicts
        # applied to refill windows (tests assert fail-fast + recovery)
        self.chaos_faults: Dict[str, int] = {}

    # -- introspection -------------------------------------------------------
    def supports(self, op) -> bool:
        """Whether ``op`` has a sequencer opcode — the ONE definition of
        the ring's warm-path subset lives in
        ``constants.CMDRING_OPCODES`` (the engine's eager hook and the
        batch eligibility both ask here)."""
        return op in CMDRING_OPCODES

    def p2p_eligible(self, options) -> bool:
        """SPMD-uniform gang eligibility for a batched SEND/RECV: both
        ends of a pair must classify identically — INCLUDING the legal
        mismatched pairs the channel supports (cross-dtype cast,
        compressed-one-side), where count/dtype/compression differ
        between the ends.  So only genuinely pair-symmetric facts gate
        here (ring enabled, world size); everything per-call — size,
        dtype, compression, buffer residency — is screened by the ring
        planner with BOTH calls visible, and disqualified positions
        re-route through the channel with unbatched semantics."""
        return self.enabled and options.comm.size == 2 and options.count > 0

    @property
    def parked(self) -> bool:
        """True when no refill window is in flight AND no run still
        accepts posts — the sequencer program has returned the device
        stream (no device work, no spin, no occupancy)."""
        with self._lock:
            if self._inflight_windows:
                return False
            return not any(
                s.run is not None and s.run.mbox.accepting
                for s in self._sessions.values()
            )

    def last_status(self, comm_id: int) -> Optional[np.ndarray]:
        """The most recent window's device status words for a session
        (the determinism test replays a window and compares these)."""
        with self._lock:
            s = self._sessions.get(comm_id)
            return None if s is None or s.last_status is None else (
                s.last_status.copy()
            )

    def stats(self) -> dict:
        breakers = self._breaker_snapshots()
        with self._lock:
            live_mboxes = [
                s.run.mbox for s in self._sessions.values()
                if s.run is not None
            ]
        # mailbox locks taken OUTSIDE the ring lock (leaf discipline,
        # like the breaker snapshots): queued-but-unpulled refill
        # windows across every live run — how far the host runs ahead
        mailbox_depth = sum(m.depth() for m in live_mboxes)
        with self._lock:
            resident = any(
                s.run is not None and s.run.mbox.accepting
                for s in self._sessions.values()
            )
            state = (
                "armed" if self._inflight_windows
                else ("resident" if resident else "parked")
            )
            return {
                "enabled": self.enabled,
                "mode": "eager" if self.eager else
                        ("batch" if self.enabled else "off"),
                "lowering": self.lowering,
                "depth": self.depth,
                "run_windows": self.run_windows,
                "linger_ms": round(self.linger_s * 1e3, 3),
                "state": state,
                "refills": self.refills,
                "doorbells": self.refills,  # every refill rings once
                "dispatches": self.dispatches,
                "mailbox_posts": self.mailbox_posts,
                "slots": self.slots_enqueued,
                "wraps": self.wraps,
                "resets": self.resets,
                "max_window": self.max_window,
                # refill occupancy: how full the last doorbell's window
                # filled the ring (1.0 = a full ring per refill)
                "occupancy": round(self.last_window / self.depth, 3)
                if self.last_window else 0.0,
                # sustained occupancy: refill windows served per program
                # dispatch — the persistence gauge (>1 means the
                # sequencer survived across refills; the warm target is
                # the full run budget)
                "sustained_occupancy": round(
                    self.refills / self.dispatches, 3
                ) if self.dispatches else 0.0,
                "ops": dict(self.op_slots),
                "fallbacks": dict(self.fallbacks),
                "chaos_faults": dict(self.chaos_faults),
                "breakers": breakers,
                # QoS arbiter plane: configured per-comm slot budgets,
                # per-comm ring-slot residency (the fairness evidence)
                # and how many windows a budget actually clamped
                "slot_budgets": {
                    str(c): b for c, b in sorted(self._slot_budgets.items())
                },
                "comm_slots": {
                    str(c): n for c, n in sorted(self.comm_slots.items())
                },
                "budgeted_windows": self.budgeted_windows,
                # introspection plane: the refill-window timeline (per-
                # slot seqn/opcode/retcode/trace-id, host-basis timing),
                # the window-latency histogram, and the mailbox depth
                "mailbox_depth": mailbox_depth,
                "windows_logged": self.windows_logged,
                "window_latency_sum_us": round(
                    self.window_latency_sum_us, 3
                ),
                "window_latency_log2_us": {
                    str(k): v
                    for k, v in sorted(self.window_latency.items())
                },
                "windows": list(self._window_log)[-16:],
            }

    def _breaker_snapshots(self) -> dict:
        with self._lock:
            items = list(self._breakers.items())
        # breaker locks taken OUTSIDE the ring lock (leaf discipline)
        return {str(c): brk.snapshot() for c, brk in items}

    def _fallback(self, reason: str) -> bool:
        with self._lock:
            self.fallbacks[reason] = self.fallbacks.get(reason, 0) + 1
        return False

    def note_fallback(self, reason: str) -> None:
        """Count a ring miss decided OUTSIDE run_batch (the engine's
        fused host decomposition) on the same fallback table the
        evidence gates read."""
        self._fallback(reason)

    def set_slot_budget(self, comm_id: int,
                        slots: Optional[int]) -> None:
        """Per-comm refill-window slot budget (the QoS arbiter's
        SET_TENANT_RING_SLOTS lever): ``comm_id``'s warm batches chunk
        into windows of at most ``slots`` ring slots; None clears."""
        with self._lock:
            if slots is None:
                self._slot_budgets.pop(int(comm_id), None)
            else:
                self._slot_budgets[int(comm_id)] = max(
                    1, min(int(slots), self.depth)
                )

    def slot_budget_of(self, comm_id: int) -> Optional[int]:
        with self._lock:
            return self._slot_budgets.get(int(comm_id))

    def breaker_for(self, comm_id: int) -> CircuitBreaker:
        """The comm's ring circuit breaker (membership plane): strikes
        on window failures, degrades ring -> inline -> host, re-probes
        after the cool-down."""
        with self._lock:
            brk = self._breakers.get(comm_id)
            if brk is None:
                brk = self._breakers[comm_id] = CircuitBreaker(
                    threshold=CMDRING_BREAKER_THRESHOLD,
                    cooldown_s=self.breaker_cooldown_s,
                )
            return brk

    # -- teardown ------------------------------------------------------------
    def reset(self) -> None:
        """soft_reset: halt every run's mailbox (the sequencer programs
        drain their backlog and return — the HALT transition) and
        realign every session's seqn/head at 0 (the gang has already
        drained the in-flight window — the full-flush contract)."""
        with self._lock:
            runs = [
                s.run for s in self._sessions.values() if s.run is not None
            ]
            self._sessions.clear()
            self._inflight_windows = 0
            self.resets += 1
            self._breakers.clear()  # full recovery re-closes the ring
            self._drained_runs.extend(runs)
        for run in runs:
            run.mbox.halt()
        self._prune_retired_runs()

    def _prune_retired_runs(self) -> None:
        """Unregister the mailboxes of retired runs whose programs have
        actually RETURNED (every rank pulled the HALT) — a halted run
        still draining its queued windows must keep its registry entry,
        or its pulls degrade to HALT payloads and the queued windows'
        requests strand (halt() promises queued windows execute)."""
        with self._lock:
            keep, drop = [], []
            for run in self._drained_runs:
                (drop if run.mbox.drained.is_set() else keep).append(run)
            self._drained_runs = keep
        for run in drop:
            unregister_mailbox(run.mbox_id)

    def halt_sessions(self) -> None:
        """Engine shutdown: same run teardown as reset, without touching
        the counters or session mirrors — and the run threads are
        JOINED (bounded): a sequencer program still draining while the
        interpreter tears the XLA runtime down aborts the process."""
        with self._lock:
            runs = [
                s.run for s in self._sessions.values() if s.run is not None
            ]
            runs += self._drained_runs
            self._drained_runs = []
        for run in runs:
            run.mbox.halt()
        for run in runs:
            if run.thread is not None:
                run.thread.join(timeout=10.0)
            unregister_mailbox(run.mbox_id)

    # -- position planning ---------------------------------------------------
    def _plan_collective(self, comm, calls, lead, mesh):
        """Plan one collective position (the device-residency screen of
        the ordinary path, shared): None means host operands."""
        return self.gang._plan_device_call(comm, calls, lead, mesh)

    def _plan_barrier(self, comm, mesh, npdt) -> dict:
        devs = list(mesh.devices.flat)
        return {
            "op": Operation.BARRIER, "size": comm.size, "n": 1,
            "in_w": 1, "out_w": 1, "devs": devs,
            "npdt": npdt, "compressed": False, "wire_npdt": None,
            "writers": set(),
        }

    def _plan_p2p(self, comm, calls, mesh) -> Optional[dict]:
        """Plan a matched SEND/RECV pair position (world-2 gangs): one
        slot with root=src, peer=dst.  None when the position is not a
        complementary pair — the caller counts the reason and the
        ordinary paths (``_execute_p2p_pair``) own it."""
        if comm.size != 2:
            return None
        pair = complementary_pair(calls)
        if pair is None:
            return None
        src, dst = pair
        snd, rcv = calls[src], calls[dst]
        # the ring is for the floor-bound regime (same bound as the
        # collective slots; the pair decision sees BOTH calls, so the
        # verdict is symmetric by construction)
        if (
            snd.count * snd.arithcfg.uncompressed_elem_bytes
            > self.max_bytes
        ):
            return None
        from ...buffer import DeviceBuffer

        devs = list(mesh.devices.flat)
        op0 = snd.op0
        res = rcv.res
        n = snd.count
        if not (
            isinstance(op0, DeviceBuffer) and not op0.is_dummy
            and op0.device == devs[src] and op0.count >= n
        ):
            return None
        if not (
            isinstance(res, DeviceBuffer) and not res.is_dummy
            and res.device == devs[dst] and res.count >= n
        ):
            return None
        npdt = dtype_to_numpy(snd.arithcfg.uncompressed)
        return {
            "op": snd.op, "size": comm.size, "n": n,
            "in_w": n, "out_w": n, "devs": devs, "npdt": npdt,
            "compressed": False, "wire_npdt": None,
            "writers": {dst}, "p2p": (src, dst),
        }

    def _plan_fused(self, comm, calls, lead, plan, fuse: int):
        """Re-validate a planned position against the fused-slot
        geometry and patch the plan to the packed operand widths.
        Returns the patched plan dict, or the fallback REASON string
        (the shared :func:`accl_tpu.cmdring.fused_slot_eligible`
        predicate — the numpy-only CI smoke gates the same verdicts)."""
        in_w, out_w = ring_widths(
            lead.op, lead.count, comm.size, fuse=fuse
        )
        # the smallest packed operand across the gang decides width
        # eligibility: every rank must have staged the full fused row
        opn = in_w
        for c in calls:
            buf = c.op0
            if buf is None or buf.is_dummy:
                opn = 0
                break
            if buf.count < in_w:
                opn = min(opn, int(buf.count))
        reason = fused_slot_eligible(
            fuse, lead.op, comm.size, lead.count, opn, plan["npdt"],
            compressed=bool(plan["compressed"]),
        )
        if reason is not None:
            return reason
        patched = dict(plan)
        patched["fuse"] = int(fuse)
        patched["fparam"] = float(getattr(lead, "fuse_param", 0.0))
        patched["in_w"] = in_w
        patched["out_w"] = out_w
        # the hop offset of an attn-hop slot rides the call's root_src
        # (SPMD-uniform — the same value on every rank by _sig match)
        if FusedCompute(fuse) == FusedCompute.ATTN_HOP:
            patched["hop"] = int(lead.root_src) % comm.size
        return patched

    def _slot_opcode(self, plan):
        """The CmdOpcode one planned slot encodes as (fused slots remap
        their base op through CMDRING_FUSED_OPCODES)."""
        fuse = plan.get("fuse", 0)
        if fuse:
            return CMDRING_FUSED_OPCODES[FusedCompute(fuse)]
        return CMDRING_OPCODES[plan["op"]]

    # -- the refill path -----------------------------------------------------
    def run_batch(self, comm, entries, npos: int,
                  t0: Optional[int] = None) -> bool:
        """Try to execute a fully matched batch slot ring-resident.
        Returns False — having dispatched NOTHING — when any position
        disqualifies (the ordinary fused/sequential paths then own the
        batch); True once dispatch begins (request completion is owned
        by the ring's window parks)."""
        if not self.enabled:
            return False
        gang = self.gang
        mesh = gang.submesh(comm)
        if mesh is None or npos == 0:
            return False
        # ring circuit breaker (membership plane): an OPEN comm rides
        # host dispatch until the cool-down; HALF_OPEN probes with the
        # inline window form (no persistent run to wedge on a dying
        # peer); a probe success restores the ring
        brk = self.breaker_for(comm.id)
        verdict = brk.allow()
        if verdict == CircuitBreaker.OPEN:
            return self._fallback("circuit_open")
        probe = verdict == "probe"
        # explicit algorithm registers (global or per-call TuningPlan
        # overlay) selecting a non-XLA lowering keep their meaning: the
        # ring is its own lowering and must not shadow a requested one
        # (mirrors _run_batch_fused's disqualifiers)
        keys = gang._BATCH_TUNING_KEYS
        if any(gang.tuning.get(k, "xla") != "xla" for k in keys):
            return self._fallback("tuning_override")
        for options_list, _ in entries:
            for c in options_list:
                if c.tuning and any(
                    c.tuning.get(k, "xla") != "xla" for k in keys
                ):
                    return self._fallback("tuning_override")
        if t0 is None:
            t0 = time.perf_counter_ns()

        plans = []
        written: set = set()  # result roots of earlier positions
        window_npdt = None
        barrier_positions = []
        for i in range(npos):
            calls = [e[0][i] for e in entries]
            lead = calls[0]
            if lead.op in (Operation.SEND, Operation.RECV):
                plan = self._plan_p2p(comm, calls, mesh)
                if plan is None:
                    # not a complementary pair (or host operands): the
                    # ordinary paths own the whole batch
                    return self._fallback("p2p_unpaired")
            elif lead.op not in CMDRING_OPCODES:
                return self._fallback("unsupported_op")
            elif any(gang._sig(c) != gang._sig(lead) for c in calls[1:]):
                return False  # torn gang: surface through the host path
            elif lead.op == Operation.BARRIER:
                plan = None  # dtype-agnostic; filled once npdt is known
                barrier_positions.append(i)
                plans.append((calls, lead, plan))
                continue
            else:
                fuse = int(getattr(lead, "fuse", 0))
                if fuse:
                    # fused slots size by their packed operand geometry
                    # (grads ‖ param tail, kv ‖ q), not the base op's
                    n_eff, _ = ring_widths(
                        lead.op, lead.count, comm.size, fuse=fuse
                    )
                else:
                    n_eff = lead.count * (
                        comm.size if lead.op in _P_WIDE else 1
                    )
                nbytes = n_eff * lead.arithcfg.uncompressed_elem_bytes
                if nbytes > self.max_bytes:
                    return self._fallback("oversized")
                plan = self._plan_collective(comm, calls, lead, mesh)
                if plan is None:
                    return self._fallback("host_operands")
                if fuse:
                    plan = self._plan_fused(comm, calls, lead, plan, fuse)
                    if isinstance(plan, str):
                        return self._fallback(plan)
            # one payload dtype per window: the pallas lowering packs
            # every slot into ONE concatenated buffer, where a mixed
            # window would silently promote
            if window_npdt is None:
                window_npdt = plan["npdt"]
            elif plan["npdt"] != window_npdt:
                return self._fallback("mixed_dtype")
            # all operands assemble BEFORE dispatch/post: a position
            # reading an earlier position's result would see pre-window
            # bytes — only the sequential path orders such chains
            for call in calls:
                buf = call.op0
                if (
                    buf is not None
                    and not buf.is_dummy
                    and id(buf._root()) in written
                ):
                    return self._fallback("data_dependency")
            for r in plan["writers"]:
                res = calls[r].res
                if res is not None and not res.is_dummy:
                    written.add(id(res._root()))
            plans.append((calls, lead, plan))
        if window_npdt is None:
            window_npdt = np.dtype(np.float32)  # all-barrier window
        for i in barrier_positions:
            calls, lead, _ = plans[i]
            plans[i] = (calls, lead,
                        self._plan_barrier(comm, mesh, window_npdt))

        # windows of at most `depth` slots — clamped to the comm's QoS
        # slot budget when one is configured (the flooder pays extra
        # doorbells; unbudgeted tenants keep full windows): each window
        # is one refill (doorbell) — a program dispatch only when no
        # run is live
        with self._lock:
            budget = self._slot_budgets.get(comm.id)
        eff_depth = min(self.depth, budget) if budget else self.depth
        for lo in range(0, npos, eff_depth):
            window = plans[lo:lo + eff_depth]
            if budget and npos > eff_depth:
                with self._lock:
                    self.budgeted_windows += 1
            reqs_per_slot = [
                [e[1][i] for e in entries]
                for i in range(lo, lo + len(window))
            ]
            try:
                self._dispatch_window(
                    comm, mesh, window, reqs_per_slot, t0, probe=probe
                )
            except Exception:
                # this window's dispatch failed: fail ITS slots and the
                # not-yet-dispatched remainder — earlier windows are in
                # flight and complete (or fail) from their own parks;
                # never re-execute a collective
                import traceback

                traceback.print_exc()
                brk.record_failure("dispatch_error")
                # postmortem plane: a failed window DISPATCH is a ring
                # failure too (the latch path covers in-flight wedges)
                if self.on_failure is not None:
                    try:
                        self.on_failure(comm.id, "dispatch_error")
                    except Exception:
                        pass
                dt = time.perf_counter_ns() - t0
                for i in range(lo, npos):
                    for e in entries:
                        req = e[1][i]
                        if not req.done():  # side-effect-free probe
                            req.ring_resident = True
                            req.complete(ErrorCode.INVALID_OPERATION, dt)
                break
        return True

    # -- slot encoding -------------------------------------------------------
    def _encode(self, session: _RingSession, lead, plan) -> np.ndarray:
        """Encode one collective into the session's next ring slot —
        through the CollectivePlan's cached slot template when the call
        carries a plan (the plan -> slot encoding cache), patching only
        the per-call fields (seqn, count, root, peer, function)."""
        op = plan["op"]
        opcode = self._slot_opcode(plan)
        wire = 0
        if plan["compressed"] and plan["wire_npdt"] is not None:
            wire = int(lead.arithcfg.compressed)
        fp = getattr(lead, "plan", None)
        tmpl = fp.cmdring_slot if fp is not None else None
        if tmpl is None:
            tmpl = encode_slot(
                0,
                opcode,
                0,
                dtype=int(lead.arithcfg.uncompressed),
                function=lead.reduce_function,
                root=0,
                nseg=1,
                wire=wire,
            )
            if fp is not None:
                fp.cmdring_slot = tmpl
        words = np.array(tmpl, np.int32)
        words[_F["seqn"]] = session.seqn & 0x7FFFFFFF
        words[_F["opcode"]] = int(opcode)
        words[_F["count"]] = plan["n"]
        words[_F["function"]] = int(lead.reduce_function)
        words[_F["wire"]] = wire
        # quantized wire plane: the call's SR seed rides the flags word
        # as slot DATA (rank-mixed inside the decode loop) — seed churn
        # on a warm compressed stream never recompiles the sequencer
        words[_F["flags"]] = int(getattr(lead, "wire_seed", 0)) & 0x7FFFFFFF
        # fused compute slots: the epilogue scalar rides the fparam
        # word Q16.16; an attn-hop slot's hop OFFSET rides the peer
        # word (SPMD-uniform — each rank derives its source on device)
        words[_F["fparam"]] = (
            encode_fparam(plan["fparam"]) if plan.get("fuse") else 0
        )
        if "p2p" in plan:
            words[_F["root"]] = plan["p2p"][0]
            words[_F["peer"]] = plan["p2p"][1]
        else:
            words[_F["root"]] = (
                lead.root_src if op == Operation.BCAST else 0
            )
            words[_F["peer"]] = plan.get("hop", 0)
        slot_idx = session.head % self.ring_depth_of(session)
        session.ring[slot_idx] = words
        session.head += 1
        session.seqn += 1
        return words

    @staticmethod
    def ring_depth_of(session: _RingSession) -> int:
        return session.ring.shape[0]

    # -- window shape + payload ----------------------------------------------
    def _window_shape(self, comm, window) -> WindowShape:
        in_ws, out_ws, wires = [], [], []
        npdt = None
        for _, lead, plan in window:
            in_w, out_w = ring_widths(
                plan["op"], plan["n"], comm.size,
                fuse=plan.get("fuse", 0),
            )
            in_ws.append(in_w)
            out_ws.append(out_w)
            wires.append(
                np.dtype(plan["wire_npdt"]).name
                if plan["compressed"] and plan["wire_npdt"] is not None
                else None
            )
            npdt = plan["npdt"]
        return WindowShape(len(window), in_ws, out_ws, wires, npdt)

    def _payload_rows(self, comm, window, shape: WindowShape):
        """Per-slot per-rank operand rows — the refill's command
        payload, as VIEWS of the committed device arrays (zero-copy
        snapshots: jax arrays are immutable and later stores swap
        pointers, so what the mailbox holds can never mutate; the only
        copy on the wire is the pull's host→device move).  ``None``
        rows (dummy operands, barrier tokens, the p2p pair's non-source
        ranks) pull as zeros."""
        payload = []
        for k, (calls, lead, plan) in enumerate(window):
            w = shape.in_ws[k]
            if plan["op"] == Operation.BARRIER:
                payload.append(None)
                continue
            src_only = plan.get("p2p")
            rows = []
            for r, call in enumerate(calls):
                buf = call.op0
                if (
                    (src_only is not None and r != src_only[0])
                    or buf is None
                    or buf.is_dummy
                ):
                    rows.append(None)
                    continue
                view = np.asarray(buf.device_view()[:w])
                if view.shape[0] < w:
                    padded = np.zeros((w,), shape.npdt)
                    padded[: view.shape[0]] = view
                    view = padded
                rows.append(view)
            payload.append(rows)
        return payload

    def _wait_written_dependencies(self, session: _RingSession,
                                   window) -> None:
        """Cross-window ordering: a refill whose OPERAND was written by
        a still-in-flight earlier window must wait for that window's
        completion before snapshotting payload bytes (within one batch
        the data_dependency fallback already rejects such chains; this
        covers chains across batches riding one live run)."""
        roots = set()
        for calls, _, plan in window:
            for call in calls:
                buf = call.op0
                if buf is not None and not buf.is_dummy:
                    roots.add(id(buf._root()))
        with self._lock:
            pending = bool(roots & set(session.written))
            parks = list(session.parks) if pending else []
        deadline = time.monotonic() + drain_deadline_s(
            self.gang.timeout_s
        )
        for park in parks:
            if not park.event.wait(
                max(0.01, deadline - time.monotonic())
            ):
                # NEVER snapshot stale operand bytes: surfacing beats
                # silently computing on pre-write data (the caller
                # fails this window's requests, same as the waiter's
                # wedged-run path)
                raise TimeoutError(
                    "command-ring refill blocked on an in-flight "
                    "window writing its operand past the drain "
                    "deadline"
                )

    def _window_posture(self, window):
        """Per-window sequencer posture: the lead call's tuning-register
        overlay (``CMDRING_RUN_WINDOWS`` / ``CMDRING_LINGER_US``, raced
        as autotuner axes and dispatched per plan key) over the gang's
        env-default registers.  0 = default — the env knobs keep
        steering any call without an overlay."""
        lead = window[0][1]
        t = lead.effective_tuning(getattr(self.gang, "tuning", None) or {})
        rw = int(t.get("cmdring_run_windows", 0) or 0)
        lus = int(t.get("cmdring_linger_us", 0) or 0)
        run_windows = rw if rw > 0 else self.run_windows
        linger_s = (lus / 1e6) if lus > 0 else self.linger_s
        return run_windows, linger_s

    def _chaos_hook(self, comm, window, slots_np):
        """The chaos plane's reach into the ring path.  Refills never
        cross the emulated fabric, so the installed fault injector sees
        each window as ONE pseudo-message of type ``"RING"``:
        ``corrupt``/``drop`` poison the first slot's opcode word to an
        out-of-range value — the sequencer reports BAD_OP and that
        slot's requests complete INVALID_OPERATION fast, never a hang
        (a silently vanished refill would strand its waiters);
        ``delay`` sleeps a bounded interval before the doorbell.
        Returns the (possibly poisoned) slot rows."""
        from ...contract import _injector_for

        inj = _injector_for(getattr(self.gang, "fabric", None))
        if inj is None:
            return slots_np
        msg = _RingRefillMsg(comm.id, int(slots_np[0, _F["seqn"]]))
        v = inj.on_send(msg)
        action = None
        if v.corrupt or v.drop or v.dead_dst:
            action = "corrupt" if v.corrupt else "drop"
            slots_np = slots_np.copy()
            slots_np[0, _F["opcode"]] = _CHAOS_BAD_OPCODE
        if v.delay_s > 0:
            with self._lock:
                self.chaos_faults["delay"] = (
                    self.chaos_faults.get("delay", 0) + 1
                )
            time.sleep(min(float(v.delay_s), 1.0))
        if action is not None:
            with self._lock:
                self.chaos_faults[action] = (
                    self.chaos_faults.get(action, 0) + 1
                )
        return slots_np

    # -- dispatch ------------------------------------------------------------
    def _dispatch_window(self, comm, mesh, window, reqs_per_slot,
                         t0, probe: bool = False) -> None:
        gang = self.gang
        n = len(window)
        shape = self._window_shape(comm, window)
        lowering = self._effective_lowering(shape, window)
        with self._lock:
            session = self._sessions.get(comm.id)
            if session is None:
                session = self._sessions[comm.id] = _RingSession(self.depth)
        self._wait_written_dependencies(session, window)
        with self._lock:
            start = session.head
            slot_rows = [
                self._encode(session, lead, plan)
                for _, lead, plan in window
            ]
            if (start % self.depth) + n > self.depth:
                self.wraps += 1
            self.refills += 1
            self.slots_enqueued += n
            # per-comm residency: the ring-share counter the QoS
            # fairness evidence reads (tenant = communicator)
            self.comm_slots[comm.id] = self.comm_slots.get(comm.id, 0) + n
            self.last_window = n
            self.max_window = max(self.max_window, n)
            for _, _, plan in window:
                name = self._slot_opcode(plan).name
                self.op_slots[name] = self.op_slots.get(name, 0) + 1
            window_id = session.next_window
            session.next_window += 1
            park = _WindowPark(
                window_id,
                [plan for _, _, plan in window],
                reqs_per_slot,
                [calls for calls, _, _ in window],
                t0,
            )
            # introspection: per-slot facts captured at encode time —
            # the (seqn, opcode) written into the ring words plus the
            # issuing call's trace id (flow linkage into the merged
            # timeline)
            for k, (_calls, _, plan) in enumerate(window):
                tid = None
                for req in reqs_per_slot[k]:
                    m = getattr(req, "_tmeta", None)
                    if m and m.get("trace_id"):
                        tid = m["trace_id"]
                        break
                park.slots_info.append({
                    "seqn": int(slot_rows[k][_F["seqn"]]),
                    "opcode": self._slot_opcode(plan).name,
                    "trace_id": tid,
                })
            session.parks.append(park)
            for k, (calls, _, plan) in enumerate(window):
                for r in plan["writers"]:
                    res = calls[r].res
                    if res is not None and not res.is_dummy:
                        rid = id(res._root())
                        session.written[rid] = (
                            session.written.get(rid, 0) + 1
                        )
            self._inflight_windows += 1
        slots_np = self._chaos_hook(comm, window, np.stack(slot_rows))

        try:
            gang.interactions.bump()  # THE refill: one host interaction
            # for the whole window (an inline dispatch, a dispatch
            # arming a resident run, or a mailbox write into one)
            run = None
            waiter_st = None
            if lowering == "xla":
                with self._lock:
                    live = (
                        session.run is not None
                        and session.run.shape == shape
                        and session.run.mbox.accepting
                    )
                    # the stream detector: an earlier window of this
                    # session is still in flight — the host is running
                    # ahead of the device, the regime the resident run
                    # exists for.  A lone window takes the inline form
                    # (zero-copy operands, async dispatch, no mailbox
                    # round trip on its latency path).
                    streaming = len(session.parks) > 1
                if (live or streaming) and not probe:
                    # (a half-open probe window stays INLINE — the
                    # ring -> inline degradation step: one-shot
                    # program, no persistent run to wedge)
                    payload = self._payload_rows(comm, window, shape)
                    park.form = "mailbox"
                    run = self._post_or_dispatch(
                        comm, mesh, session, shape, window_id, slots_np,
                        payload, self._window_posture(window),
                    )
                else:
                    waiter_st = self._dispatch_inline(
                        comm, mesh, shape, park, slots_np, window, "xla"
                    )
            else:
                waiter_st = self._dispatch_inline(
                    comm, mesh, shape, park, slots_np, window, lowering
                )
            self._park_window(comm, session, park, run, waiter_st, t0)
        except BaseException:
            # the window never parked: the armed count must not leak
            # (the parked/no-spin posture is part of the contract)
            with self._lock:
                self._inflight_windows = max(0, self._inflight_windows - 1)
                if park in session.parks:
                    session.parks.remove(park)
            raise

    def _effective_lowering(self, shape: WindowShape, window) -> str:
        """Per-window lowering.  The Pallas mega-window kernel cannot
        take f16 wire casts (no Mosaic f16 — the f32 compute view
        cannot express the f16 rounding lane on the VPU), and BARRIER
        tokens / SEND-RECV pair slots assemble their payload through
        the mailbox rather than the zero-copy flat globals; such
        windows ride the XLA session INSTEAD of falling back to host
        dispatch — still ring-resident, fallback counters untouched."""
        if self.lowering != "pallas":
            return self.lowering
        f16 = np.dtype(np.float16)
        if np.dtype(shape.npdt) == f16:
            return "xla"
        if any(w is not None and np.dtype(w) == f16 for w in shape.wires):
            return "xla"
        return "pallas"

    def _post_or_dispatch(self, comm, mesh, session, shape, window_id,
                          slots_np, payload, posture) -> "_ResidentRun":
        """The persistent doorbell: post into the live run when one
        accepts this shape, else arm a fresh run (ONE dispatch) and
        post the window as its first pull.  Returns the run the window
        rode (its failure latch feeds the window's waiter).  ``posture``
        is the arming window's (run_windows, linger_s) from its tuning
        overlay — a live run keeps the posture it launched with."""
        run_windows, linger_s = posture
        with self._lock:
            run = session.run
        if run is not None and run.shape == shape:
            if run.mbox.post(window_id, slots_np, payload):
                with self._lock:
                    self.mailbox_posts += 1
                return run
        if run is not None:
            run.mbox.halt()  # stale shape / spent budget: let it drain
            with self._lock:
                self._drained_runs.append(run)
            self._prune_retired_runs()
        mbox = SequencerMailbox(
            comm.size, shape,
            run_windows=run_windows,
            linger_s=linger_s,
            on_window_done=self._make_window_done(comm.id),
        )
        mid = register_mailbox(mbox)
        ok = mbox.post(window_id, slots_np, payload)
        assert ok  # fresh mailbox always accepts its first window
        new_run = _ResidentRun(mbox, mid, shape)
        new_run.launch(mesh, run_windows)
        with self._lock:
            session.run = new_run
            self.dispatches += 1
        return new_run

    def _settle_window(self, session, park) -> None:
        """Session bookkeeping at window completion, exactly once per
        window whichever completion path ran: decrement the
        written-root ledger (cross-window dependency releases) and
        stash the status words for introspection."""
        with self._lock:
            if park.settled:
                return
            park.settled = True
            if park.status is not None:
                session.last_status = np.asarray(park.status, np.int32)
            for k, plan in enumerate(park.plans):
                for r in plan["writers"]:
                    res = park.calls_per_slot[k][r].res
                    if res is not None and not res.is_dummy:
                        rid = id(res._root())
                        left = session.written.get(rid, 1) - 1
                        if left <= 0:
                            session.written.pop(rid, None)
                        else:
                            session.written[rid] = left

    def _log_window(self, comm_id: int, park: _WindowPark, status,
                    end_ns: int, run=None, error=None) -> None:
        """One completed (or failed) window into the bounded window
        log: per-slot (seqn, opcode, retcode, trace id) next to the
        host-side timing — basis ``"host"`` labeled honestly (neither
        lowering can write a device clock next to its status words on
        this mesh; the mailbox's posted/pulled/pushed stamps are the
        closest observable refill timeline).  Logged exactly once per
        window whichever completion path ran."""
        from ...telemetry import _perf_to_epoch_us

        with self._lock:
            if park.logged:
                return
            park.logged = True
        slots = []
        for k, info in enumerate(park.slots_info):
            ret = None
            if status is not None and k < len(status):
                ret = int(status[k][1])
            slots.append(dict(info, retcode=ret))
        t0_us = _perf_to_epoch_us(park.t0)
        end_us = _perf_to_epoch_us(end_ns)
        entry = {
            "window_id": park.window_id,
            "comm": comm_id,
            "form": park.form,
            "ts_us": round(t0_us, 3),
            "dur_us": round(max(end_us - t0_us, 0.001), 3),
            "slots": slots,
            "basis": "host",
        }
        if error is not None:
            entry["error"] = str(error)[:200]
        if run is not None:
            timing = run.mbox.take_timing(park.window_id)
            if timing is not None:
                entry["mailbox_us"] = {
                    k2.replace("_ns", "_us"):
                        round(_perf_to_epoch_us(v), 3)
                    for k2, v in timing.items()
                }
        with self._lock:
            self._window_log.append(entry)
            self.windows_logged += 1
            lat_us = max(end_us - t0_us, 0.001)
            b = max(1, int(lat_us)).bit_length() - 1
            self.window_latency[b] = self.window_latency.get(b, 0) + 1
            self.window_latency_sum_us += lat_us

    def window_log(self, last: Optional[int] = None) -> List[dict]:
        with self._lock:
            log = list(self._window_log)
        return log if last is None else log[-last:]

    def trace_events(self) -> List[dict]:
        """The window log as Chrome/Perfetto events: one span per
        refill window and one span per slot nested under it (cat
        ``cmdring`` so merge_traces dedups the shared-gang rows), each
        slot flow-linked (``f`` phase) to the issuing call's trace id —
        intake→refill→window-execution→completion reads as connected
        arrows in the merged timeline."""
        pid = os.getpid()
        events: List[dict] = []
        log = self.window_log()
        if not log:
            return events
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 2,
            "args": {"name": f"cmdring (pid {pid})"},
        })
        events.append({
            "ph": "M", "name": "thread_name", "pid": pid, "tid": 2,
            "args": {"name": "ring windows"},
        })
        for entry in log:
            ts, dur = entry["ts_us"], entry["dur_us"]
            events.append({
                "name": f"cmdring::window[{len(entry['slots'])}]",
                "cat": "cmdring",
                "ph": "X",
                "ts": ts,
                "dur": dur,
                "pid": pid,
                "tid": 2,
                "args": {
                    k: v for k, v in entry.items() if k != "slots"
                },
            })
            n = max(1, len(entry["slots"]))
            for k, slot in enumerate(entry["slots"]):
                # slots execute in order within the window: render
                # them as equal sub-spans parented (by containment)
                # under the refill window span
                s_ts = ts + dur * k / n
                s_dur = dur / n
                events.append({
                    "name": f"cmdring::{slot['opcode'].lower()}",
                    "cat": "cmdring",
                    "ph": "X",
                    "ts": round(s_ts, 3),
                    "dur": round(s_dur, 3),
                    "pid": pid,
                    "tid": 2,
                    "args": dict(slot, window=entry["window_id"]),
                })
                if slot.get("trace_id"):
                    # a STEP (`t`) on the issuing call's flow: the
                    # arrow renders without claiming a flow END — the
                    # call's own s/f pair lives on the rank rows, and
                    # a slot whose issuing record rolled out of the
                    # flight ring must not fail flow validation
                    events.append({
                        "name": "accl::flow",
                        "cat": "cmdring",
                        "ph": "t",
                        "id": f"0x{slot['trace_id']:08x}",
                        "ts": round(s_ts + s_dur / 2, 3),
                        "pid": pid,
                        "tid": 2,
                        "args": {"window": entry["window_id"]},
                    })
        return events

    def _make_window_done(self, comm_id: int):
        """Completion hook one mailbox carries: adopt results (deferred
        stores), stash status, complete the slots' requests, release
        the park's event.  Runs on the run thread (the push callback's
        context), outside every mailbox lock.  Completing HERE — not in
        the drainer's on_ready — saves two thread handoffs per window
        on the latency path; ordering holds because one run pushes its
        windows strictly in order on one thread, and the park entry
        still rides the in-flight window so every drain point sees
        it."""

        def on_done(window_id, status, results, comm_id=comm_id):
            with self._lock:
                session = self._sessions.get(comm_id)
                park = None
                if session is not None:
                    for p in session.parks:
                        if p.window_id == window_id:
                            park = p
                            break
            if park is None:
                return  # torn down (soft_reset) while in flight
            for k, plan in enumerate(park.plans):
                out_w = plan["out_w"] if "p2p" not in plan else plan["n"]
                for r in sorted(plan["writers"]):
                    res = park.calls_per_slot[k][r].res
                    if res is None or res.is_dummy:
                        continue
                    row = results.get(r)
                    if row is None:
                        continue
                    self._adopter.adopt(res, row[k][:out_w], out_w)
            park.status = np.asarray(status, np.int32)
            if session is not None:
                self._settle_window(session, park)
            # Complete the slots' requests NOW (the latency path): the
            # drainer's on_ready then finds them done and only settles
            # the window-plane accounting.  Guarded: a LATE push racing
            # the waiter's drain-deadline failure must not flip
            # already-failed requests back to OK.  Cross-window WRITE
            # ordering needs no extra fence here: XLA serializes
            # program execution per device, so every rank's run-R2
            # pushes strictly follow its run-R1 pushes — window
            # completions (all-ranks fan-in) therefore fire in
            # execution order, and successive adoptions of one buffer
            # land newest-last.
            sv = park.status
            dt = max(time.perf_counter_ns() - park.t0, 1)
            for i, slot_reqs in enumerate(park.reqs_per_slot):
                code = (
                    ErrorCode.OK
                    if i < len(sv) and int(sv[i, 1]) == CMDRING_ST_OK
                    else ErrorCode.INVALID_OPERATION
                )
                for req in slot_reqs:
                    if req.done():  # side-effect-free engine probe
                        continue
                    req.ring_resident = True
                    req.complete(code, dt)
            park.event.set()

        return on_done

    def _dispatch_inline(self, comm, mesh, shape, park, slots_np,
                         window, lowering):
        """The one-shot window form: ONE async program executes the
        window on zero-copy assembled operand globals (no mailbox on
        the latency path — a lone drained window costs exactly what the
        pre-persistent ring charged).  On the pallas lowering this is
        the mega-window Mosaic kernel with a backlog of one; a flushed
        batch larger than the ring depth dispatches once per depth
        window, in order.  Returns the status global the park's waiter
        blocks on."""
        from ...ops.pallas import cmdring as devring

        gang = self.gang
        globals_ = [
            self._assemble_ring_global(calls, plan, mesh)
            for calls, lead, plan in window
        ]
        import jax

        with jax.profiler.TraceAnnotation(
            f"accl::cmdring[{len(window)}]"
        ):
            st, results = devring.run_windows(
                [(slots_np, globals_)], mesh, shape, lowering=lowering,
            )
        with self._lock:
            self.dispatches += 1
        for k, (calls, lead, plan) in enumerate(window):
            gang._adopt_out_shards(
                results[0][k], calls, plan, park.reqs_per_slot[k]
            )
        return st

    def _zeros_shard(self, w: int, npdt, dev):
        key = (int(w), np.dtype(npdt).str, dev)
        arr = self._zeros.get(key)
        if arr is None:
            from ...buffer import dev_zeros

            self.gang.interactions.bump()  # the one-time zeros program
            arr = self._zeros[key] = dev_zeros((int(w),), npdt, dev)
        return arr

    def _assemble_ring_global(self, calls, plan, mesh):
        """Zero-copy operand global for one ring slot.  Collective
        slots use the gang's assembled-flat machinery (raw committed
        shards, cached); BARRIER tokens and SEND/RECV pair slots build
        theirs from cached zeros shards plus (for p2p) the source
        rank's raw array — warm windows assemble with no dispatch."""
        op = plan["op"]
        if op != Operation.BARRIER and "p2p" not in plan:
            g, _prep, _raw = self.gang._assemble_flat(calls, plan, mesh)
            return g
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from ...ops import driver as opdriver

        size, in_w = plan["size"], plan["in_w"]
        devs, npdt = plan["devs"], plan["npdt"]
        src = plan.get("p2p", (None, None))[0]
        shards = []
        for r, call in enumerate(calls):
            if src is not None and r == src:
                arr = call.op0.device_array()
                if arr.shape[0] != in_w:
                    from .engine import _prep_program

                    self.gang.interactions.bump()
                    arr = _prep_program(in_w, None, devs[r], True)(arr)
                shards.append(arr)
            else:
                shards.append(self._zeros_shard(in_w, npdt, devs[r]))
        return jax.make_array_from_single_device_arrays(
            (size * in_w,),
            NamedSharding(mesh, PartitionSpec(opdriver.AXIS)),
            shards,
        )

    # -- completion ----------------------------------------------------------
    def _park_window(self, comm, session, park, run, waiter_st,
                     t0) -> None:
        """Hand the window's completion to the in-flight window (the
        refill window): the drainer blocks on the device status words
        — the mailbox park event on the resident path, the status
        global on the inline path — then completes every slot's
        requests with its per-slot retcode."""
        gang = self.gang

        def window_done():
            with self._lock:
                self._inflight_windows = max(0, self._inflight_windows - 1)
                if park in session.parks:
                    session.parks.remove(park)

        if waiter_st is not None:
            # inline form: the status global IS the completion word
            def waiter(park=park, st=waiter_st):
                import jax

                from ...ops.pallas.cmdring import status_view

                jax.block_until_ready(st)
                park.status = status_view(st)[: len(park.plans)]
                self._settle_window(session, park)
                park.event.set()
        else:
            def waiter(park=park, run=run):
                deadline = time.monotonic() + drain_deadline_s(
                    gang.timeout_s
                )
                while True:
                    if park.event.wait(0.2):
                        return
                    if run is not None and run.failed.is_set():
                        raise RuntimeError(
                            "sequencer run failed: "
                            f"{type(run.exc).__name__}: {run.exc}"
                        )
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            "command-ring window never completed "
                            "(sequencer run wedged past the drain "
                            "deadline)"
                        )

        def on_ready(overlap_ns, depth, ready_ns, park=park, t0=t0,
                     run=run):
            # the xla mailbox path completed the requests on the run
            # thread already (on_window_done, the latency path); this
            # settles anything still pending (the pallas backlog path,
            # torn-down sessions) and the window-plane accounting
            sv = park.status
            dt = max(ready_ns - t0, 1)
            self._log_window(comm.id, park, sv, ready_ns, run=run)
            window_done()
            # a completed window closes (or restores) the comm's ring
            # circuit breaker — per-slot BAD_OP retcodes are opcode
            # errors, not transport failures, and don't strike
            self.breaker_for(comm.id).success()
            for i, slot_reqs in enumerate(park.reqs_per_slot):
                code = (
                    ErrorCode.OK
                    if sv is not None and i < len(sv)
                    and int(sv[i, 1]) == CMDRING_ST_OK
                    else ErrorCode.INVALID_OPERATION
                )
                for req in slot_reqs:
                    if req.done():  # side-effect-free engine probe
                        continue
                    req.overlap_ns = overlap_ns or None
                    req.inflight_depth = depth
                    req.ring_resident = True
                    req.complete(code, dt)

        def on_error(exc, park=park, run=run, t0=t0, comm_id=comm.id):
            dt = max(time.perf_counter_ns() - t0, 1)
            err = f"{type(exc).__name__}: {exc}"
            self._log_window(
                comm_id, park, park.status, time.perf_counter_ns(),
                run=run, error=err,
            )
            # postmortem plane: the ring failure latch — the facade's
            # BlackBox captures the window log + flight evidence
            if self.on_failure is not None:
                try:
                    self.on_failure(comm_id, err)
                except Exception:  # must never mask the failure path
                    pass
            window_done()
            # window failure (run latch, drain deadline, dispatch
            # error): strike the comm's ring breaker — repeated strikes
            # open it and the comm degrades to host dispatch until the
            # cool-down probe
            self.breaker_for(comm_id).record_failure(
                type(exc).__name__
            )
            # tear down the run THIS window rode (an inline window rode
            # none) — never whatever run the session points at now,
            # which may be a healthy successor serving later windows.
            # The mailbox stays registered until the program actually
            # returns (queued windows still drain), then prunes.
            if run is not None:
                with self._lock:
                    if session.run is run:
                        session.run = None
                    self._drained_runs.append(run)
                run.mbox.halt()
                self._prune_retired_runs()
            ctx = {
                "comm": comm_id,
                "error": f"{type(exc).__name__}: {exc}"[:300],
            }
            for slot_reqs in park.reqs_per_slot:
                for req in slot_reqs:
                    if not req.done():  # side-effect-free engine probe
                        req.ring_resident = True
                        req.complete(
                            ErrorCode.INVALID_OPERATION, dt,
                            context=dict(ctx, op=req.op_name),
                        )

        gang.window.park(comm.id, waiter, on_ready, on_error, ring=True)
