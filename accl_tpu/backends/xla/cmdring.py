"""The gang engine's command-ring session: arm / refill / teardown.

This is the host half of the TPU CCLO analog (the device half is
``ops/pallas/cmdring.py``): host code that used to *issue* collectives
becomes code that *refills a queue*.  A warm batched window of N
eligible collectives is encoded into N slots of the per-communicator
ring, written to the device and executed by ONE sequencer dispatch —
one host refill interaction however large the window (counter-asserted
by tests/test_cmdring.py).  Everything else — cold calls, oversized
payloads, compressed lanes, host operands, unsupported ops — falls back
to the ordinary host-dispatch paths, with the reason counted in
:meth:`GangCommandRing.stats`.

Lifecycle (the ``run loop`` states of the reference firmware, modeled
at the session level):

* **parked** — no window in flight: the sequencer waits on the doorbell
  (no device work, no spin).  A refill underrun — host slower than the
  sequencer — simply returns the ring here.
* **armed**  — one or more refill windows in flight; the in-flight
  window (``overlap.InflightWindow``) is the refill window: its drain
  points block on the device status word the sequencer wrote.
* **teardown/reset** — ``soft_reset`` parks the sequencer, clears every
  session and realigns seqn/head at 0 (the ``HALT`` opcode marks this
  transition in the slot schema).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ...constants import (
    CMDRING_DEPTH_DEFAULT,
    CMDRING_DEPTH_ENV,
    CMDRING_ENV,
    CMDRING_FIELDS,
    CMDRING_MAX_BYTES_ENV,
    CMDRING_MAX_DEPTH,
    CMDRING_MAX_PAYLOAD_BYTES,
    CMDRING_SLOT_WORDS,
    CMDRING_ST_OK,
    CmdOpcode,
    ErrorCode,
    Operation,
)

_F = CMDRING_FIELDS

#: Operation -> CmdOpcode for the sequencer's warm-path subset
_RING_OPS = {
    Operation.ALLREDUCE: CmdOpcode.ALLREDUCE,
    Operation.BCAST: CmdOpcode.BCAST,
}


def _env_mode() -> str:
    return os.environ.get(CMDRING_ENV, "1").strip().lower()


def default_lowering() -> str:
    """Sequencer lowering: the Pallas remote-DMA kernel on a real TPU,
    the XLA gather lowering everywhere else (the emulator/CI tier —
    this box's jax has no Pallas interpreter; see compat).  Override
    with ``ACCL_CMDRING_LOWERING``."""
    explicit = os.environ.get("ACCL_CMDRING_LOWERING")
    if explicit in ("xla", "pallas"):
        return explicit
    import jax

    return "pallas" if jax.default_backend() == "tpu" else "xla"


class _RingSession:
    """Per-communicator ring state: the persistent host mirror of the
    device ring (wrap-around is real — slot i of refill k+1 reuses the
    words of slot i of refill k-depth) plus the monotone seqn."""

    __slots__ = ("ring", "head", "seqn")

    def __init__(self, depth: int):
        self.ring = np.zeros((depth, CMDRING_SLOT_WORDS), np.int32)
        self.head = 0
        self.seqn = 0


class GangCommandRing:
    """One gang context's command ring (all communicators' sessions)."""

    def __init__(self, gang):
        self.gang = gang
        mode = _env_mode()
        self.enabled = mode not in ("0", "off", "false", "")
        self.eager = mode == "eager"
        try:
            depth = int(
                os.environ.get(CMDRING_DEPTH_ENV, CMDRING_DEPTH_DEFAULT)
            )
        except ValueError:
            depth = CMDRING_DEPTH_DEFAULT
        self.depth = max(1, min(depth, CMDRING_MAX_DEPTH))
        try:
            self.max_bytes = int(
                os.environ.get(
                    CMDRING_MAX_BYTES_ENV, CMDRING_MAX_PAYLOAD_BYTES
                )
            )
        except ValueError:
            self.max_bytes = CMDRING_MAX_PAYLOAD_BYTES
        self.lowering = default_lowering()
        self._lock = threading.Lock()
        self._sessions: Dict[int, _RingSession] = {}
        self._inflight_windows = 0
        # lifetime counters (telemetry_report()["cmdring"]).  One
        # counter backs both the refill and doorbell stats keys: on
        # this tier the slot write and the doorbell ride the same
        # dispatch, so they cannot diverge by construction.
        self.refills = 0          # refill windows dispatched (= doorbells)
        self.slots_enqueued = 0   # collectives executed ring-resident
        self.wraps = 0            # head wrapped past the ring depth
        self.resets = 0           # soft_reset teardowns (sequencer parked)
        self.max_window = 0
        self.last_window = 0
        self.fallbacks: Dict[str, int] = {}

    # -- introspection -------------------------------------------------------
    def supports(self, op) -> bool:
        """Whether ``op`` has a sequencer opcode — the ONE definition of
        the ring's warm-path subset (the engine's eager hook asks here
        instead of duplicating the table)."""
        return op in _RING_OPS

    @property
    def parked(self) -> bool:
        """True when no refill window is in flight — the sequencer waits
        on the doorbell instead of spinning (the underrun posture)."""
        with self._lock:
            return self._inflight_windows == 0

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "mode": "eager" if self.eager else
                        ("batch" if self.enabled else "off"),
                "lowering": self.lowering,
                "depth": self.depth,
                "state": "parked" if self._inflight_windows == 0
                         else "armed",
                "refills": self.refills,
                "doorbells": self.refills,  # one dispatch = one doorbell
                "slots": self.slots_enqueued,
                "wraps": self.wraps,
                "resets": self.resets,
                "max_window": self.max_window,
                # refill occupancy: how full the last doorbell's window
                # filled the ring (1.0 = a full ring per refill)
                "occupancy": round(self.last_window / self.depth, 3)
                if self.last_window else 0.0,
                "fallbacks": dict(self.fallbacks),
            }

    def _fallback(self, reason: str) -> bool:
        with self._lock:
            self.fallbacks[reason] = self.fallbacks.get(reason, 0) + 1
        return False

    # -- teardown ------------------------------------------------------------
    def reset(self) -> None:
        """soft_reset: park the sequencer and realign every session's
        seqn/head at 0 (the gang has already drained the in-flight
        window — the full-flush contract)."""
        with self._lock:
            self._sessions.clear()
            self._inflight_windows = 0
            self.resets += 1

    # -- the refill path -----------------------------------------------------
    def run_batch(self, comm, entries, npos: int,
                  t0: Optional[int] = None) -> bool:
        """Try to execute a fully matched batch slot ring-resident.
        Returns False — having dispatched NOTHING — when any position
        disqualifies (the ordinary fused/sequential paths then own the
        batch); True once dispatch begins (request completion is owned
        by the ring's window parks)."""
        if not self.enabled:
            return False
        gang = self.gang
        mesh = gang.submesh(comm)
        if mesh is None or npos == 0:
            return False
        # explicit algorithm registers (global or per-call TuningPlan
        # overlay) selecting a non-XLA lowering keep their meaning: the
        # ring is its own lowering and must not shadow a requested one
        # (mirrors _run_batch_fused's disqualifiers)
        keys = gang._BATCH_TUNING_KEYS
        if any(gang.tuning.get(k, "xla") != "xla" for k in keys):
            return self._fallback("tuning_override")
        for options_list, _ in entries:
            for c in options_list:
                if c.tuning and any(
                    c.tuning.get(k, "xla") != "xla" for k in keys
                ):
                    return self._fallback("tuning_override")
        if t0 is None:
            t0 = time.perf_counter_ns()

        plans = []
        written: set = set()  # result roots of earlier positions
        window_npdt = None
        for i in range(npos):
            calls = [e[0][i] for e in entries]
            lead = calls[0]
            if lead.op not in _RING_OPS:
                return self._fallback("unsupported_op")
            if any(gang._sig(c) != gang._sig(lead) for c in calls[1:]):
                return False  # torn gang: surface through the host path
            nbytes = lead.count * lead.arithcfg.uncompressed_elem_bytes
            if nbytes > self.max_bytes:
                return self._fallback("oversized")
            plan = gang._plan_device_call(comm, calls, lead, mesh)
            if plan is None:
                return self._fallback("host_operands")
            if plan["compressed"]:
                return self._fallback("compressed")
            # one dtype per window: the pallas lowering packs every
            # slot into ONE concatenated buffer, where a mixed window
            # would silently promote — and mosaic has no f16 at all
            if window_npdt is None:
                window_npdt = plan["npdt"]
            elif plan["npdt"] != window_npdt:
                return self._fallback("mixed_dtype")
            if (
                self.lowering == "pallas"
                and np.dtype(plan["npdt"]) == np.float16
            ):
                return self._fallback("mosaic_dtype")
            # all operands assemble BEFORE the one dispatch: a position
            # reading an earlier position's result would see pre-window
            # bytes — only the sequential path orders such chains
            for call in calls:
                buf = call.op0
                if (
                    buf is not None
                    and not buf.is_dummy
                    and id(buf._root()) in written
                ):
                    return self._fallback("data_dependency")
            for r in plan["writers"]:
                res = calls[r].res
                if res is not None and not res.is_dummy:
                    written.add(id(res._root()))
            plans.append((calls, lead, plan))

        # windows of at most `depth` slots: each window is one refill
        # interaction (slot write + doorbell dispatch)
        for lo in range(0, npos, self.depth):
            window = plans[lo:lo + self.depth]
            reqs_per_slot = [
                [e[1][i] for e in entries]
                for i in range(lo, lo + len(window))
            ]
            try:
                self._dispatch_window(
                    comm, mesh, window, reqs_per_slot, t0
                )
            except Exception:
                # this window's dispatch failed: fail ITS slots and the
                # not-yet-dispatched remainder — earlier windows are in
                # flight and complete (or fail) from their own parks;
                # never re-execute a collective
                import traceback

                traceback.print_exc()
                dt = time.perf_counter_ns() - t0
                for i in range(lo, npos):
                    for e in entries:
                        req = e[1][i]
                        if not req.done():  # side-effect-free probe
                            req.ring_resident = True
                            req.complete(ErrorCode.INVALID_OPERATION, dt)
                break
        return True

    def _encode(self, session: _RingSession, lead, plan) -> np.ndarray:
        """Encode one collective into the session's next ring slot —
        through the CollectivePlan's cached slot template when the call
        carries a plan (the plan -> slot encoding cache), patching only
        the per-call fields (seqn, count, root, function)."""
        from ...ops.pallas.cmdring import encode_slot

        fp = lead.plan
        tmpl = fp.cmdring_slot if fp is not None else None
        if tmpl is None:
            tmpl = encode_slot(
                0,
                _RING_OPS[lead.op],
                0,
                dtype=int(lead.arithcfg.uncompressed),
                function=lead.reduce_function,
                root=0,
                nseg=1,
            )
            if fp is not None:
                fp.cmdring_slot = tmpl
        words = np.array(tmpl, np.int32)
        words[_F["seqn"]] = session.seqn & 0x7FFFFFFF
        words[_F["count"]] = lead.count
        words[_F["function"]] = int(lead.reduce_function)
        words[_F["root"]] = (
            lead.root_src if lead.op == Operation.BCAST else 0
        )
        slot_idx = session.head % self.ring_depth_of(session)
        session.ring[slot_idx] = words
        session.head += 1
        session.seqn += 1
        return words

    @staticmethod
    def ring_depth_of(session: _RingSession) -> int:
        return session.ring.shape[0]

    def _dispatch_window(self, comm, mesh, window, reqs_per_slot,
                         t0) -> None:
        from ...ops.pallas import cmdring as devring

        gang = self.gang
        n = len(window)
        globals_ = []
        take_ws = []
        adopt = []  # (calls, plan) per slot, for result adoption
        with self._lock:
            session = self._sessions.get(comm.id)
            if session is None:
                session = self._sessions[comm.id] = _RingSession(self.depth)
            start = session.head
            slot_rows = []
            for calls, lead, plan in window:
                slot_rows.append(self._encode(session, lead, plan))
            if (start % self.depth) + n > self.depth:
                self.wraps += 1
            self.refills += 1
            self.slots_enqueued += n
            self.last_window = n
            self.max_window = max(self.max_window, n)
            self._inflight_windows += 1
        slots_np = np.stack(slot_rows)

        try:
            for calls, lead, plan in window:
                global_arr, prep, _raw = gang._assemble_flat(
                    calls, plan, mesh
                )
                globals_.append(global_arr)
                take_ws.append(plan["in_w"])
                adopt.append((calls, plan))

            gang.interactions.bump()  # THE refill: slot write + doorbell,
            # one host interaction for the whole window
            import jax

            with jax.profiler.TraceAnnotation(f"accl::cmdring[{n}]"):
                st, outs = devring.run_window(
                    slots_np, globals_, mesh, take_ws, self.lowering
                )
            for i, (calls, plan) in enumerate(adopt):
                gang._adopt_out_shards(
                    outs[i], calls, plan, reqs_per_slot[i]
                )
            self._park_window(comm, st, outs, reqs_per_slot, t0)
        except BaseException:
            # the window never parked: the armed count must not leak
            # (the parked/no-spin posture is part of the contract)
            with self._lock:
                self._inflight_windows = max(0, self._inflight_windows - 1)
            raise

    def _park_window(self, comm, st, outs, reqs_per_slot, t0) -> None:
        """Hand the window's completion to the in-flight window (the
        refill window): the drainer blocks on the device status word
        the sequencer wrote, then completes every slot's requests with
        its per-slot retcode."""
        from ...ops.pallas.cmdring import status_view

        gang = self.gang

        def waiter(st=st, outs=outs):
            import jax

            jax.block_until_ready(st)
            for o in outs:
                jax.block_until_ready(o)

        def window_done():
            with self._lock:
                self._inflight_windows = max(0, self._inflight_windows - 1)

        def on_ready(overlap_ns, depth, ready_ns,
                     reqs_per_slot=reqs_per_slot, t0=t0):
            sv = status_view(st)
            dt = max(ready_ns - t0, 1)
            window_done()
            for i, slot_reqs in enumerate(reqs_per_slot):
                code = (
                    ErrorCode.OK
                    if i < len(sv) and int(sv[i, 1]) == CMDRING_ST_OK
                    else ErrorCode.INVALID_OPERATION
                )
                for req in slot_reqs:
                    req.overlap_ns = overlap_ns or None
                    req.inflight_depth = depth
                    req.ring_resident = True
                    req.complete(code, dt)

        def on_error(exc, reqs_per_slot=reqs_per_slot, t0=t0,
                     comm_id=comm.id):
            dt = max(time.perf_counter_ns() - t0, 1)
            window_done()
            ctx = {
                "comm": comm_id,
                "error": f"{type(exc).__name__}: {exc}"[:300],
            }
            for slot_reqs in reqs_per_slot:
                for req in slot_reqs:
                    if not req.done():  # side-effect-free engine probe
                        req.ring_resident = True
                        req.complete(
                            ErrorCode.INVALID_OPERATION, dt,
                            context=dict(ctx, op=req.op_name),
                        )

        gang.window.park(comm.id, waiter, on_ready, on_error, ring=True)
