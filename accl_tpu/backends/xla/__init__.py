from .engine import XLAEngine, XLAGangContext  # noqa: F401
