"""The emulated wire: message format and transports between rank engines.

Role model: the reference's ``eth_intf`` message header {count, tag, src,
seqn, strm, dst, msg_type, host, vaddr} (``kernels/cclo/hls/eth_intf/
eth_intf.h:114-151``) and the emulator's ZMQ pub/sub "ethernet"
(``test/model/zmq/zmq_server.h:39-45``).  Two transports:

* ``InProcFabric`` — rank engines in one process, per-rank thread-safe
  inboxes.  This is the CI workhorse tier.
* ``SocketFabric`` — one process per rank, length-prefixed messages over TCP
  sockets (the multi-process tier, mirroring the reference's one-emulator-
  process-per-rank layout).

Message types follow the reference wire protocol (``eth_intf.h:42-45``):
EAGER data messages, rendezvous INIT (address exchange) and WR_DONE
(completion notification).  Rendezvous data is a one-sided write: the fabric
delivers it straight into pre-registered receiver memory, then surfaces a
WR_DONE notification — mirroring an RDMA WRITE executed by the NIC with no
receiver-CPU involvement (``dummy_cyt_rdma_stack``).
"""

from __future__ import annotations

import dataclasses
import enum
import pickle
import socket
import struct
import sys
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional

from ...faults import FaultInjector, FaultPlan, PeerDeadError
from ...utils.logging import Log, LogLevel

# Per-message wire tracing (ACCL_DEBUG=TRACE): events route through the
# telemetry plane's buffered ring (accl_tpu.telemetry.wire_event) instead
# of synchronous stderr writes, so tracing no longer perturbs the
# timings being traced; ACCL_TRACE_STDERR=1 opts the stderr sink back in.
# One level compare per send when tracing is off.
_WIRE_LOG = Log("wire")


class MsgType(enum.IntEnum):
    EAGER = 0  # tag/seqn-matched segment into an RX buffer
    RNDZV_INIT = 2  # receiver announces a writable address
    RNDZV_WR_DONE = 3  # write completed into receiver memory
    RNDZV_DATA = 4  # the one-sided write itself (fabric-internal)
    STREAM = 5  # routed directly to a device stream port
    ACK = 6  # eager-segment delivery acknowledgment (retransmit protocol)
    VERIFY = 7  # contract-plane verdict relay (JSON payload): a rank
    # that convicted a divergence tells its peers so their in-flight
    # calls fail fast too instead of waiting out the engine deadline
    MEMBER = 8  # membership-plane agreement frame (JSON payload): the
    # shrink protocol's propose/confirm exchange on one-process-per-
    # rank fabrics (board-anchored tiers exchange in process instead)
    POSTMORTEM = 9  # postmortem-bundle solicitation (JSON payload): a
    # failing rank asks its peers for their evidence tails and peers
    # reply best-effort within the requester's bounded deadline
    # (board-anchored tiers solicit in process instead)


@dataclasses.dataclass
class Message:
    msg_type: MsgType
    comm_id: int
    src: int  # sender rank within the communicator
    dst: int  # destination rank within the communicator
    tag: int
    seqn: int = 0
    vaddr: int = 0  # rendezvous buffer token
    count: int = 0  # payload bytes (redundant w/ len(payload), kept for parity)
    strm: int = 0  # stream id for MsgType.STREAM
    payload: bytes = b""
    ack: int = 0  # 1 = sender requests an ACK (retransmit protocol armed)
    reply_to: str = ""  # sender's fabric address for ACKs
    csum: int = 0  # crc32 of payload; stamped by the fabric on first send
    epoch: int = 0  # sender's communicator-instance epoch (seqn dedup scope)
    # contract plane (accl_tpu.contract, ACCL_VERIFY=1): the sender's
    # latest completed verification window piggybacks on every message —
    # three ints of header, zero extra traffic.  vfy_window -1 = no
    # stamp (verifier off or no window completed yet).
    vfy_gen: int = 0
    vfy_window: int = -1
    vfy_digest: int = 0
    # monitor plane (accl_tpu.monitor): the sender's latest completed
    # straggler-skew window (window index + mean wait in us) rides the
    # same piggyback cadence — two header fields, zero extra traffic.
    # skw_window -1 = no stamp (monitor off or no window completed).
    skw_window: int = -1
    skw_mean_us: float = 0.0
    # membership plane (accl_tpu.membership): the sender's membership
    # EPOCH — globally aligned by the eviction agreement (unlike the
    # process-local communicator epochs), so receivers can discard
    # stale pre-shrink frames still in flight at cutover (seqn matching
    # ignores epochs; a stale chunk of the aborted collective would
    # otherwise corrupt the first post-shrink collective's receives)
    mbr: int = 0
    # send wall-timestamp (time_ns; 0 = unstamped): receivers measure
    # per-source arrival latency from it — the straggler analyzer's
    # direct observable of a slow sender/link.  Wall clock because it
    # is the only clock two processes share; cross-host skew is
    # whatever NTP leaves (same-host fabrics are exact).
    sent_ns: int = 0
    # causal trace plane (accl_tpu.telemetry): the sender's CURRENT
    # collective trace id piggybacks on every message (one int; same
    # one-probe-per-send discipline as vfy_/skw_) — receivers record a
    # wire-hop flow step, so a merged timeline links send→recv across
    # processes.  0 = unstamped (flows off, or no call in flight).
    trc: int = 0


class Endpoint:
    """Receiving side of a rank: inbox + rendezvous write registry.

    The engine registers writable memory under a vaddr token; incoming
    RNDZV_DATA is copied there by the fabric (the "NIC") and converted into a
    WR_DONE notification in the inbox.
    """

    def __init__(self, deliver_cb: Optional[Callable[[Message], None]] = None):
        self._lock = threading.Lock()
        self._inbox: List[Message] = []
        self._wr_registry: Dict[int, memoryview] = {}
        self._deliver_cb = deliver_cb
        self.on_activity: Optional[Callable[[], None]] = None
        # contract plane: the receiving rank's verifier hook — observes
        # peers' piggybacked digest claims on every delivered message
        self.contract_hook: Optional[Callable[[Message], None]] = None
        # monitor plane: the receiving rank's skew hook — observes
        # peers' piggybacked straggler-window claims the same way
        self.skew_hook: Optional[Callable[[Message], None]] = None
        # membership plane: the receiving rank's agreement hook —
        # observes MEMBER propose/confirm frames at delivery
        self.membership_hook: Optional[Callable[[Message], None]] = None
        # postmortem plane: the receiving rank's solicitation hook —
        # observes POSTMORTEM request/reply frames at delivery (frames
        # are consumed here, never parked in the inbox: they carry no
        # collective matching signature)
        self.postmortem_hook: Optional[Callable[[Message], None]] = None
        # wire-integrity accounting: payloads whose crc32 no longer matches
        # the stamped csum are discarded here (the rx dataplane's bit-error
        # detection; the sender's retransmit protocol recovers them)
        self.corrupt_drops = 0

    def register_write_target(self, vaddr: int, mem: memoryview) -> None:
        with self._lock:
            self._wr_registry[vaddr] = mem

    def deliver(self, msg: Message) -> None:
        if msg.payload and msg.csum and zlib.crc32(msg.payload) != msg.csum:
            with self._lock:
                self.corrupt_drops += 1
                if msg.msg_type == MsgType.RNDZV_DATA:
                    # the one-sided write can never complete now (there is
                    # no rendezvous retransmit; the receiver will time out)
                    # — drop the write target so the registry doesn't pin
                    # the buffer forever
                    self._wr_registry.pop(msg.vaddr, None)
            if self.on_activity is not None:
                self.on_activity()
            return
        # contract hook AFTER the csum guard: a corrupt-fault frame is
        # discarded above and must never be consumed as a digest claim
        # or a relayed VERIFY verdict
        hook = self.contract_hook
        if hook is not None and (
            msg.vfy_window >= 0 or msg.msg_type == MsgType.VERIFY
        ):
            try:
                hook(msg)  # a verifier failure must never drop traffic
            except Exception:  # pragma: no cover - defensive
                pass
        mhook = self.membership_hook
        if mhook is not None and msg.msg_type == MsgType.MEMBER:
            # after the csum guard like the contract hook: a corrupt
            # frame must never be consumed as an agreement vote
            try:
                mhook(msg)
            except Exception:  # pragma: no cover - defensive
                pass
        shook = self.skew_hook
        if shook is not None and (msg.skw_window >= 0 or msg.sent_ns):
            # after the csum guard like the contract hook: a corrupt
            # frame's skew claim must not poison the judge
            try:
                shook(msg)
            except Exception:  # pragma: no cover - defensive
                pass
        if msg.trc:
            # causal trace plane: a piggybacked trace id records one
            # wire-hop flow step (sampled bounded ring — never raises,
            # never drops traffic)
            try:
                from ...telemetry import wire_flow

                wire_flow(msg.trc, msg.src, msg.dst, msg.comm_id)
            except Exception:  # pragma: no cover - defensive
                pass
        if msg.msg_type == MsgType.POSTMORTEM:
            phook = self.postmortem_hook
            if phook is not None:
                try:
                    phook(msg)
                except Exception:  # pragma: no cover - defensive
                    pass
            if self.on_activity is not None:
                self.on_activity()
            return
        if msg.msg_type == MsgType.RNDZV_DATA:
            with self._lock:
                mem = self._wr_registry.pop(msg.vaddr)
            mem[: len(msg.payload)] = msg.payload
            done = Message(
                MsgType.RNDZV_WR_DONE,
                msg.comm_id,
                msg.src,
                msg.dst,
                msg.tag,
                vaddr=msg.vaddr,
                count=msg.count,
            )
            self._push(done)
        else:
            self._push(msg)

    def _push(self, msg: Message) -> None:
        with self._lock:
            self._inbox.append(msg)
        if self._deliver_cb is not None:
            self._deliver_cb(msg)
        if self.on_activity is not None:
            self.on_activity()

    def take_matching(self, pred: Callable[[Message], bool]) -> Optional[Message]:
        """Remove and return the first inbox message satisfying ``pred``."""
        with self._lock:
            for i, m in enumerate(self._inbox):
                if pred(m):
                    return self._inbox.pop(i)
        return None

    def pending(self) -> int:
        with self._lock:
            return len(self._inbox)

    def clear(self) -> int:
        """Drop every parked message and stale rendezvous write targets
        (soft-reset recovery); returns the number of messages discarded."""
        with self._lock:
            n = len(self._inbox)
            self._inbox.clear()
            self._wr_registry.clear()
            return n


class Fabric:
    """Abstract transport: address -> endpoint delivery.

    The base class owns the chaos-plane hook: :meth:`send` stamps the wire
    checksum, consults the installed :class:`FaultInjector` (drop / delay /
    duplicate / corrupt / kill / partition), then hands surviving copies to
    the transport's :meth:`_transmit`."""

    _injector: Optional[FaultInjector] = None
    _delay_lock: Optional[threading.Lock] = None
    #: modeled link rate in bytes/s (None = unpaced, the default): the
    #: emulated wire's bandwidth model.  The in-process transports move
    #: frames at memcpy speed (~10 GB/s), which is no wire at all — a
    #: compression sweep measured there reads codec cost only.  With a
    #: rate set (``set_wire_rate`` / ACCL_WIRE_GBPS, read by the bench
    #: harness), every transmit pays payload_bytes/rate of wall clock,
    #: serialized per sender like a real NIC — deterministic, byte-
    #: proportional, honest about WHAT is being measured (the artifact
    #: records the modeled rate).
    _wire_rate_Bps: Optional[float] = None

    def set_wire_rate(self, gbps: Optional[float]) -> None:
        """Model the link at ``gbps`` gigabits/s (None disables)."""
        self._wire_rate_Bps = (
            None if not gbps else float(gbps) * 1e9 / 8.0
        )

    # -- topology plane (accl_tpu.topology): two-class paced model ----------
    #: per-link-class modeled rates in bytes/s (the two-tier wire: fast
    #: ICI within a slice, slow DCN across).  None entries fall back to
    #: the single-class ``_wire_rate_Bps`` (which may itself be None =
    #: unpaced).  Classification consults the topology registered per
    #: communicator — comm-relative rank spaces, consistent because
    #: each registered topology lives in its own comm's space.
    _ici_rate_Bps: Optional[float] = None
    _dcn_rate_Bps: Optional[float] = None

    def set_wire_rates(self, ici_gbps: Optional[float] = None,
                       dcn_gbps: Optional[float] = None) -> None:
        """Model the two link classes separately (gigabits/s; None
        disables that class's override)."""
        self._ici_rate_Bps = (
            None if not ici_gbps else float(ici_gbps) * 1e9 / 8.0
        )
        self._dcn_rate_Bps = (
            None if not dcn_gbps else float(dcn_gbps) * 1e9 / 8.0
        )

    def register_topology(self, comm_id: int, topology) -> None:
        """Attach (or with ``None`` detach) the slice descriptor for one
        communicator's rank space — the send path classifies (and
        counts) every wire byte of that comm as ICI vs DCN with one
        dict probe, the contract/skew/trace stamp discipline."""
        topos = getattr(self, "_topologies", None)
        if topos is None:
            topos = self._topologies = {}
            self._class_lock = threading.Lock()
            self._class_bytes = {"ici": 0, "dcn": 0, "loopback": 0,
                                 "unclassified": 0}
            self._class_msgs = {"ici": 0, "dcn": 0, "loopback": 0,
                                "unclassified": 0}
        if topology is None:
            topos.pop(comm_id, None)
        else:
            topos[comm_id] = topology

    def _link_class_of(self, msg: "Message") -> str:
        topos = getattr(self, "_topologies", None)
        if not topos:
            return "unclassified"
        topo = topos.get(msg.comm_id)
        if topo is None:
            return "unclassified"
        try:
            cls = topo.link_class(msg.src, msg.dst)
        except KeyError:
            return "unclassified"
        return cls.name.lower()

    def wire_class_stats(self) -> dict:
        """Per-link-class byte/message counters + the modeled rates —
        the telemetry evidence the topology capture gate counter-asserts
        (hierarchical must cut DCN bytes by ~the slice factor)."""
        lock = getattr(self, "_class_lock", None)
        if lock is None:
            bytes_, msgs = {}, {}
        else:
            with lock:
                bytes_ = dict(self._class_bytes)
                msgs = dict(self._class_msgs)
        return {
            "bytes": bytes_,
            "messages": msgs,
            "rates_gbps": {
                "ici": (
                    None if self._ici_rate_Bps is None
                    else self._ici_rate_Bps * 8.0 / 1e9
                ),
                "dcn": (
                    None if self._dcn_rate_Bps is None
                    else self._dcn_rate_Bps * 8.0 / 1e9
                ),
                "default": (
                    None if self._wire_rate_Bps is None
                    else self._wire_rate_Bps * 8.0 / 1e9
                ),
            },
        }

    def reset_wire_class_stats(self) -> None:
        lock = getattr(self, "_class_lock", None)
        if lock is not None:
            with lock:
                for k in self._class_bytes:
                    self._class_bytes[k] = 0
                    self._class_msgs[k] = 0

    def _pace(self, msg: "Message") -> None:
        rate = self._wire_rate_Bps
        if getattr(self, "_topologies", None):
            cls = self._link_class_of(msg)
            with self._class_lock:
                self._class_bytes[cls] += len(msg.payload)
                self._class_msgs[cls] += 1
            if cls == "ici" and self._ici_rate_Bps is not None:
                rate = self._ici_rate_Bps
            elif cls == "dcn" and self._dcn_rate_Bps is not None:
                rate = self._dcn_rate_Bps
            elif cls == "loopback":
                rate = None  # self-delivery is never paced
        if rate and msg.payload:
            time.sleep(len(msg.payload) / rate)

    def install_fault_plan(self, plan: Optional[FaultPlan]) -> Optional[FaultInjector]:
        """Arm (or with ``None``, disarm) a fault plan on this fabric."""
        if self._delay_lock is None:
            # the ordered-delay state is created HERE (setup time,
            # single-threaded) rather than lazily on the send path: two
            # senders racing a lazy first-touch could each build their
            # own queue dict and orphan one side's delayed frames
            self._delay_lock = threading.Lock()
            self._delayed = {}
        self._injector = FaultInjector(plan) if plan is not None else None
        return self._injector

    @property
    def fault_injector(self) -> Optional[FaultInjector]:
        return self._injector

    # -- contract plane (accl_tpu.contract) ----------------------------------
    def register_contract(self, comm_id: int, rank: int, verifier) -> None:
        """Arm outbound digest stamping for (communicator, sending rank):
        the send path piggybacks ``verifier.stamp(comm_id)`` onto every
        message that rank sends on that communicator."""
        stamps = getattr(self, "_contract_stamps", None)
        if stamps is None:
            stamps = self._contract_stamps = {}
        stamps[(comm_id, rank)] = verifier

    def unregister_contract(self, verifier) -> None:
        stamps = getattr(self, "_contract_stamps", None)
        if stamps:
            for key in [k for k, v in stamps.items() if v is verifier]:
                del stamps[key]

    # -- monitor plane (accl_tpu.monitor) ------------------------------------
    def register_skew(self, comm_id: int, rank: int, tracker) -> None:
        """Arm outbound straggler-skew stamping for (communicator,
        sending rank): the send path piggybacks ``tracker.stamp(
        comm_id)`` — the latest completed (window, mean_wait) — onto
        every message that rank sends on that communicator, exactly
        like the contract digest stamp."""
        stamps = getattr(self, "_skew_stamps", None)
        if stamps is None:
            stamps = self._skew_stamps = {}
        stamps[(comm_id, rank)] = tracker

    def unregister_skew(self, tracker) -> None:
        stamps = getattr(self, "_skew_stamps", None)
        if stamps:
            for key in [k for k, v in stamps.items() if v is tracker]:
                del stamps[key]

    # -- causal trace plane (accl_tpu.telemetry flows) ------------------------
    def register_trace(self, comm_id: int, rank: int, provider) -> None:
        """Arm outbound trace-id stamping for (communicator, sending
        rank): the send path piggybacks ``provider.trace_stamp(
        comm_id)`` — the id assigned to that rank's latest collective
        intake — onto every message it sends on the communicator,
        exactly like the contract/skew stamps.  Best-effort by design:
        a message of call k+1 racing call k's tail is window-grade
        attribution, same as the skew stamp."""
        stamps = getattr(self, "_trace_stamps", None)
        if stamps is None:
            stamps = self._trace_stamps = {}
        stamps[(comm_id, rank)] = provider

    def unregister_trace(self, provider) -> None:
        stamps = getattr(self, "_trace_stamps", None)
        if stamps:
            for key in [k for k, v in stamps.items() if v is provider]:
                del stamps[key]

    def attach(self, address: str, endpoint: Endpoint) -> None:
        raise NotImplementedError

    def send(self, address: str, msg: Message) -> None:
        if _WIRE_LOG.level >= LogLevel.TRACE:
            _WIRE_LOG.trace(
                f"send {msg.msg_type.name} comm={msg.comm_id} "
                f"src={msg.src} dst={msg.dst} tag={msg.tag} "
                f"seqn={msg.seqn} bytes={len(msg.payload)} -> {address}"
            )
        stamps = getattr(self, "_contract_stamps", None)
        if stamps:
            # contract plane piggyback: stamp the sending rank's latest
            # completed digest window onto the outgoing message (one
            # dict probe when verification is armed, one getattr when
            # not — the ~0%-off budget)
            verifier = stamps.get((msg.comm_id, msg.src))
            if verifier is not None:
                msg.vfy_gen, msg.vfy_window, msg.vfy_digest = (
                    verifier.stamp(msg.comm_id)
                )
        skews = getattr(self, "_skew_stamps", None)
        if skews:
            # monitor plane piggyback: the sending rank's latest
            # completed skew window rides the same one-probe-per-send
            # discipline as the contract stamp above, plus the send
            # timestamp receivers measure arrival latency from
            tracker = skews.get((msg.comm_id, msg.src))
            if tracker is not None:
                msg.skw_window, msg.skw_mean_us = tracker.stamp(msg.comm_id)
                msg.sent_ns = time.time_ns()
        traces = getattr(self, "_trace_stamps", None)
        if traces:
            # causal trace piggyback: the sending rank's current
            # collective trace id (one dict probe when armed)
            provider = traces.get((msg.comm_id, msg.src))
            if provider is not None:
                msg.trc = provider.trace_stamp(msg.comm_id)
        self._pace(msg)  # modeled link rate (no-op when unpaced)
        inj = self._injector
        if inj is None:
            self._transmit(address, msg)
            return
        # checksums only matter when someone can corrupt the wire: the
        # fault-free hot path skips both the stamp and the verify
        # (delivery checks csum only when non-zero)
        if msg.payload and msg.csum == 0:
            msg.csum = zlib.crc32(msg.payload)
        v = inj.on_send(msg)
        if v.dead_dst:
            raise PeerDeadError(address)
        if v.drop:
            return
        if v.corrupt:
            # the csum keeps the ORIGINAL digest: the receiving dataplane
            # detects the bit error and discards the segment
            msg = dataclasses.replace(
                msg, payload=inj.corrupt_payload(msg.payload)
            )
        copies = 2 if v.duplicate else 1
        if v.delay_s > 0:
            self._delay_enqueue(address, msg, copies, v.delay_s)
        elif not self._delay_enqueue_if_pending(address, msg, copies):
            self._transmit_copies(address, msg, copies, False)

    # -- ordered delayed transmit --------------------------------------------
    # A congested link delays everything BEHIND the stalled frame — it
    # does not reorder.  The old Timer-per-message path let every later
    # send to the same peer overtake the delayed one, which on the
    # multi-rank socket tier (strictly seqn-consuming receivers, one
    # recv thread per link) wedged ranks into RECEIVE_TIMEOUT (the PR 8
    # pre-existing issue).  Delayed sends now park in a per-address FIFO
    # drained by one worker in order; while the queue exists, later
    # undelayed sends to that address queue behind it instead of
    # overtaking.  Other addresses are unaffected (per-peer ordering is
    # the wire's contract; cross-peer ordering never was).

    def _delay_state(self):
        # created by install_fault_plan (the only way an injector — and
        # so a delay verdict — can exist); never lazily on the send path
        return self._delay_lock, self._delayed

    def _delay_enqueue(self, address: str, msg: Message, copies: int,
                       delay_s: float) -> None:
        lock, delayed = self._delay_state()
        with lock:
            q = delayed.get(address)
            fresh = q is None
            if fresh:
                q = delayed[address] = []
            q.append((time.monotonic() + float(delay_s), msg, copies))
        if fresh:
            t = threading.Thread(
                target=self._drain_delayed, args=(address,),
                name=f"accl-fabric-delay-{address}", daemon=True,
            )
            t.start()

    def _delay_enqueue_if_pending(self, address: str, msg: Message,
                                  copies: int) -> bool:
        """Queue an UNDELAYED send behind the address's pending delayed
        frames (due immediately — no extra delay beyond head-of-line
        blocking); False when nothing is pending and the caller should
        transmit directly.  The probe and the append are one locked
        step, so a send can never observe the queue draining away and
        then append to an orphaned list."""
        lock, delayed = self._delay_state()
        with lock:
            q = delayed.get(address)
            if q is None:
                return False
            q.append((time.monotonic(), msg, copies))
            return True

    def _drain_delayed(self, address: str) -> None:
        """One worker per delayed address: transmit the FIFO in order,
        sleeping out each frame's residual delay; exits (and removes the
        queue, restoring the direct-send fast path) once empty.  Frames
        are popped only AFTER their transmit, so the queue stays
        non-empty — and later sends keep queuing behind — until the last
        pending frame is really on the wire."""
        lock, delayed = self._delay_state()
        while True:
            with lock:
                q = delayed.get(address)
                if not q:
                    delayed.pop(address, None)
                    return
                due, msg, copies = q[0]
            wait = due - time.monotonic()
            if wait > 0:
                time.sleep(wait)
            try:
                try:
                    self._transmit_copies(address, msg, copies, False)
                except Exception as e:
                    # a queued frame has no caller to raise into, but
                    # the failure must not vanish silently: the sender
                    # believed the send succeeded.  Log loudly; the
                    # transports' own dead-marking (SocketFabric) makes
                    # the NEXT direct send fail fast.
                    print(
                        f"[accl fabric] delayed-queue transmit to "
                        f"{address} failed: {type(e).__name__}: {e}",
                        file=sys.stderr,
                    )
            finally:
                with lock:
                    q.pop(0)

    def _transmit_copies(
        self, address: str, msg: Message, copies: int, swallow: bool
    ) -> None:
        for _ in range(copies):
            try:
                self._transmit(address, msg)
            except Exception:
                if not swallow:  # delayed delivery has no caller to tell
                    raise

    def _transmit(self, address: str, msg: Message) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InProcFabric(Fabric):
    """All ranks in one process; delivery is a direct endpoint call."""

    def __init__(self, fault_plan: Optional[FaultPlan] = None):
        self._endpoints: Dict[str, Endpoint] = {}
        self._dead: set = set()
        self._lock = threading.Lock()
        if fault_plan is not None:
            self.install_fault_plan(fault_plan)

    def attach(self, address: str, endpoint: Endpoint) -> None:
        with self._lock:
            if address in self._endpoints:
                raise ValueError(f"address {address} already attached")
            self._dead.discard(address)
            self._endpoints[address] = endpoint

    def detach(self, address: str) -> None:
        """Tear an endpoint out of the fabric (engine shutdown / simulated
        rank death): later sends to it fail fast with PeerDeadError instead
        of being silently dropped."""
        with self._lock:
            self._endpoints.pop(address, None)
            self._dead.add(address)

    def _transmit(self, address: str, msg: Message) -> None:
        with self._lock:
            if address in self._dead:
                raise PeerDeadError(address)
            ep = self._endpoints.get(address)
        if ep is None:
            raise KeyError(f"no endpoint at {address}")
        ep.deliver(msg)


class SocketFabric(Fabric):
    """One process per rank; messages are pickled with a u32 length prefix.

    Address format: ``"host:port"``.  Each fabric instance owns one listening
    socket (this rank's address) and lazily opened client connections to
    peers.  Mirrors the per-rank ZMQ endpoints of the reference emulator
    (``test/model/emulator/run.py``).
    """

    def __init__(self, bind_address: str):
        self._bind_address = bind_address
        self._endpoint: Optional[Endpoint] = None
        # the one-process-per-rank tier inherits its chaos plan from the
        # environment (FaultPlan.to_env -> ACCL_FAULT_PLAN in the spawner)
        env_plan = FaultPlan.from_env()
        if env_plan is not None:
            self.install_fault_plan(env_plan)
        # peers that had a live connection and then died: sends fail fast
        # with PeerDeadError instead of silently vanishing (or re-dialing
        # through the full startup grace period)
        self._dead: set = set()
        self._ever_connected: set = set()
        host, port = bind_address.rsplit(":", 1)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(64)
        self._conns: Dict[str, socket.socket] = {}
        self._accepted: list = []  # inbound conns; torn down on close()
        self._conn_lock = threading.Lock()
        # peers' dials succeed the moment listen() is up — BEFORE this
        # rank's engine exists.  Messages that land in that window must
        # be parked and replayed at attach(), not dropped (a dropped
        # first eager chunk wedges the whole ring: every rank times out
        # in its first collective — caught by the multi-process soak)
        self._attach_lock = threading.Lock()
        self._pre_attach: list = []
        self._closing = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop,
            name=f"accl-fabric-accept-{bind_address}", daemon=True,
        )
        self._accept_thread.start()

    def attach(self, address: str, endpoint: Endpoint) -> None:
        if address != self._bind_address:
            raise ValueError("socket fabric serves exactly its bind address")
        with self._attach_lock:
            # replay the backlog while still holding the lock: a message
            # arriving concurrently must not overtake a parked one (stream
            # bytes are order-sensitive; deliver only appends to the
            # endpoint inbox, so holding the lock here cannot deadlock)
            self._endpoint = endpoint
            backlog, self._pre_attach = self._pre_attach, []
            for msg in backlog:
                endpoint.deliver(msg)

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            with self._conn_lock:
                if self._closing:
                    conn.close()
                    return
                self._accepted.append(conn)
            threading.Thread(
                target=self._recv_loop, args=(conn,),
                name="accl-fabric-recv", daemon=True,
            ).start()

    def _recv_loop(self, conn: socket.socket) -> None:
        try:
            while True:
                hdr = self._recv_exact(conn, 4)
                if hdr is None:
                    return
                (n,) = struct.unpack("<I", hdr)
                body = self._recv_exact(conn, n)
                if body is None:
                    return
                msg: Message = pickle.loads(body)
                with self._attach_lock:
                    endpoint = self._endpoint
                    if endpoint is None:
                        self._pre_attach.append(msg)
                if endpoint is not None:
                    try:
                        endpoint.deliver(msg)
                    except Exception:
                        # a poisoned message must not kill this link: the
                        # recv thread owns the peer's ONLY path in, and
                        # its death silently drops every later message
                        # (wedging collectives ranks downstream).  Log
                        # loudly, keep receiving.
                        import traceback

                        print(
                            f"[accl fabric {self._bind_address}] deliver "
                            f"failed for {msg.msg_type!r} src={msg.src} "
                            f"comm={msg.comm_id} seqn={msg.seqn} "
                            f"vaddr={msg.vaddr:#x}:",
                            file=sys.stderr,
                        )
                        traceback.print_exc()
        finally:
            conn.close()

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            try:
                chunk = conn.recv(n - len(buf))
            except OSError:
                return None  # connection torn down under us (close())
            if not chunk:
                return None
            buf += chunk
        return buf

    def _connect(self, address: str, grace_s: float = 15.0) -> socket.socket:
        """Dial a peer, retrying until its listener is up (peers start
        concurrently; the reference leans on MPI barriers for this,
        fixture.hpp:124-132 — we self-synchronize instead).  Re-dials of a
        peer that was ALREADY connected get no grace period: its process is
        gone and the caller needs a fast failure, not a 15 s stall."""
        import time as _time

        host, port = address.rsplit(":", 1)
        deadline = _time.monotonic() + grace_s
        while True:
            try:
                conn = socket.create_connection((host, int(port)), 2.0)
                break
            except OSError:
                if _time.monotonic() > deadline:
                    raise
                _time.sleep(0.05)
        conn.settimeout(None)  # connect timeout must not outlive the dial
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return conn

    def _mark_dead(self, address: str) -> None:
        with self._conn_lock:
            self._dead.add(address)
            conn = self._conns.pop(address, None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def _transmit(self, address: str, msg: Message) -> None:
        with self._conn_lock:
            if address in self._dead:
                raise PeerDeadError(address)
            conn = self._conns.get(address)
        if conn is None:
            # dial OUTSIDE the lock so a slow-starting peer doesn't stall
            # sends to already-connected peers
            try:
                grace = 0.0 if address in self._ever_connected else 15.0
                conn = self._connect(address, grace_s=grace)
            except OSError:
                self._mark_dead(address)
                raise PeerDeadError(address) from None
            with self._conn_lock:
                self._ever_connected.add(address)
                winner = self._conns.setdefault(address, conn)
            if winner is not conn:
                conn.close()
                conn = winner
        body = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
        try:
            with self._conn_lock:
                conn.sendall(struct.pack("<I", len(body)) + body)
        except OSError:
            # the peer process died under an established connection: fail
            # the send fast (the engine converts this to SEND_TIMEOUT)
            # instead of silently dropping every later message
            self._mark_dead(address)
            raise PeerDeadError(address) from None

    def close(self) -> None:
        self._closing = True
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conn_lock:
            # accepted (inbound) connections must die too: leaving them
            # open keeps peers' sends "succeeding" into a rank that no
            # longer exists — the silent-drop failure mode.  Closing them
            # gives peers a prompt RST -> PeerDeadError -> SEND_TIMEOUT.
            for c in self._conns.values():
                try:
                    c.close()
                except OSError:
                    pass
            self._conns.clear()
            for c in self._accepted:
                try:
                    c.close()
                except OSError:
                    pass
            self._accepted.clear()
