"""The emulated collective engine: a cooperative scheduler running collective
algorithms over the fake wire.

This is the TPU-build analog of the reference's control-plane firmware main
loop (``ccl_offload_control.c:2308-2483``): calls arrive on a command queue,
each executes as a *generator* that yields wait-conditions (see
``engine_conditions.py``); calls whose condition is unmet are parked and
re-polled round-robin — the same cooperative retry-queue semantics the
firmware implements with ``NOT_READY_ERROR`` recirculation and
``current_step`` resume state (``:2460-2478``), expressed idiomatically as
Python coroutines instead of a hand-rolled step machine.

One engine == one rank.  Data lives in numpy "device" memory; the dataplane
(RX pool, reductions, casts, streams) is in ``dataplane.py``; the wire in
``fabric.py``; the algorithms in ``algorithms.py``.
"""

from __future__ import annotations

import itertools
import threading
import time
import traceback
from typing import Callable, List, Optional

from ...communicator import Communicator
from ...constants import (
    ConfigFunction,
    DEFAULT_RX_BUFFER_COUNT,
    DEFAULT_RX_BUFFER_SIZE,
    DEFAULT_TIMEOUT_S,
    EAGER_THRESHOLD_DEFAULT,
    ErrorCode,
    MAX_EAGER_SIZE_LIMIT,
    TUNING_DEFAULTS,
)
from ...request import CommandQueue, Request
from ..base import BaseEngine, CallOptions
from . import algorithms
from .dataplane import RxBuffer, RxBufferPool, RxStatus, StreamPorts
from .engine_conditions import WaitCondition
from .fabric import Endpoint, Fabric, Message, MsgType


class _CallTask:
    __slots__ = ("request", "gen", "cond", "deadline", "started_ns")

    def __init__(self, request: Request, gen, timeout_s: float):
        self.request = request
        self.gen = gen
        self.cond: Optional[WaitCondition] = None
        self.deadline = time.monotonic() + timeout_s
        self.started_ns = time.perf_counter_ns()


class EmuEngine(BaseEngine):
    def __init__(
        self,
        fabric: Fabric,
        address: str,
        rx_buffer_count: int = DEFAULT_RX_BUFFER_COUNT,
        rx_buffer_size: int = DEFAULT_RX_BUFFER_SIZE,
    ):
        self.fabric = fabric
        self.address = address
        self.endpoint = Endpoint()
        fabric.attach(address, self.endpoint)
        self.rx_pool = RxBufferPool(rx_buffer_count, rx_buffer_size)
        self.streams = StreamPorts()
        self.timeout_s = DEFAULT_TIMEOUT_S
        self.max_eager_size = EAGER_THRESHOLD_DEFAULT
        self.max_rendezvous_size = MAX_EAGER_SIZE_LIMIT
        self.tuning = dict(TUNING_DEFAULTS)
        self.transport_enabled = False

        self._rndzv_inits: List[Message] = []
        self._rndzv_done: List[Message] = []
        self._notif_lock = threading.Lock()
        self._vaddr_counter = itertools.count(1)

        self._queue = CommandQueue()
        self._wake = threading.Event()
        self.endpoint.on_activity = self._wake.set
        self._stop = False
        self._thread = threading.Thread(
            target=self._run, name=f"accl-engine-{address}", daemon=True
        )
        self._thread.start()

    # -- public API ---------------------------------------------------------
    def start(self, options: CallOptions) -> Request:
        req = Request(op_name=options.op.name)
        self._queue.push((req, options))
        self._wake.set()
        return req

    def shutdown(self) -> None:
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=5)
        self.fabric.close()

    def stream_push(self, stream_id: int, data: bytes) -> None:
        self.streams.push(stream_id, data)
        self._wake.set()

    def stream_pop(self, stream_id: int, timeout: Optional[float] = None) -> bytes:
        return self.streams.pop(stream_id, timeout=timeout)

    def new_vaddr(self) -> int:
        return next(self._vaddr_counter)

    # -- wire helpers used by algorithms ------------------------------------
    def post(self, comm: Communicator, dst: int, msg: Message) -> None:
        self.fabric.send(comm.ranks[dst].address, msg)

    def take_rndzv_init(self, pred: Callable[[Message], bool]):
        with self._notif_lock:
            for i, m in enumerate(self._rndzv_inits):
                if pred(m):
                    return self._rndzv_inits.pop(i)
        return None

    def take_rndzv_done(self, pred: Callable[[Message], bool]):
        with self._notif_lock:
            for i, m in enumerate(self._rndzv_done):
                if pred(m):
                    return self._rndzv_done.pop(i)
        return None

    def rx_seek_overflow(self, comm_id: int, src: int, tag: int, seqn: int):
        """Head-of-line escape for a fully parked pool.  When every rx slot
        holds eager segments for OTHER signatures — e.g. a rank that isn't
        a member of the current subcommunicator op racing ahead into the
        next collective and fire-hosing its segments first — the segment
        the CURRENT op needs waits in the unbounded inbox and could never
        be parked: a deadlock the multi-process soak caught.  Consume it
        straight from the inbox instead.  The pool stays the normal path
        (the gate below) so slot-lifecycle accounting keeps meaning; the
        reference's single shared link cannot reorder like this, but its
        seek loop + retry queue serve the same role of decoupling match
        order from arrival order (rxbuf_seek, dma_mover.cpp:587-611)."""
        used, total = self.rx_pool.occupancy()
        if used < total:
            return None  # pool has room: routing will park it normally
        msg = self.endpoint.take_matching(
            lambda m: (
                m.msg_type == MsgType.EAGER
                and m.comm_id == comm_id
                and m.src == src
                and m.tag == tag
                and m.seqn == seqn
            )
        )
        if msg is None:
            return None
        return RxBuffer(-1, len(msg.payload), RxStatus.CLAIMED, msg)

    # -- debug dumps (ref ACCL::dump_eager_rx_buffers) -----------------------
    def dump_rx_buffers(self) -> str:
        return "\n".join(self.rx_pool.dump())

    # -- scheduler ----------------------------------------------------------
    def _route_inbox(self) -> None:
        """Move arrived messages to their stations (the rxbuf_enqueue/dequeue
        + depacketizer-routing roles).  EAGER messages stay in the inbox while
        the pool is exhausted — backpressure, not drop."""
        while True:
            routed_any = False
            msg = self.endpoint.take_matching(
                lambda m: m.msg_type != MsgType.EAGER
            )
            if msg is not None:
                routed_any = True
                if msg.msg_type == MsgType.RNDZV_INIT:
                    with self._notif_lock:
                        self._rndzv_inits.append(msg)
                elif msg.msg_type == MsgType.RNDZV_WR_DONE:
                    with self._notif_lock:
                        self._rndzv_done.append(msg)
                elif msg.msg_type == MsgType.STREAM:
                    self.streams.push(msg.strm, msg.payload)
            used, total = self.rx_pool.occupancy()
            if used < total:
                emsg = self.endpoint.take_matching(
                    lambda m: m.msg_type == MsgType.EAGER
                )
                if emsg is not None:
                    routed_any = True
                    self.rx_pool.fill(emsg, timeout=0)
            if not routed_any:
                return

    def _run(self) -> None:
        active: List[_CallTask] = []
        while not self._stop:
            while True:
                item = self._queue.pop(timeout=0)
                if item is None:
                    break
                req, options = item
                req.mark_executing()
                gen = algorithms.dispatch(self, options)
                active.append(_CallTask(req, gen, self.timeout_s))

            self._route_inbox()

            progressed = False
            now = time.monotonic()
            for task in list(active):
                value = None
                if task.cond is not None:
                    value = task.cond.poll(self)
                    if value is None:
                        if now > task.deadline:
                            task.request.complete(
                                task.cond.timeout_code,
                                time.perf_counter_ns() - task.started_ns,
                            )
                            active.remove(task)
                            progressed = True
                        continue
                    task.cond = None
                try:
                    task.cond = task.gen.send(value)
                    progressed = True
                except StopIteration as stop:
                    ret = stop.value if stop.value is not None else ErrorCode.OK
                    task.request.complete(
                        ret, time.perf_counter_ns() - task.started_ns
                    )
                    active.remove(task)
                    progressed = True
                except Exception:
                    traceback.print_exc()
                    task.request.complete(
                        ErrorCode.INVALID_OPERATION,
                        time.perf_counter_ns() - task.started_ns,
                    )
                    active.remove(task)
                    progressed = True

            if not progressed:
                self._wake.wait(timeout=0.001 if active else 0.05)
                self._wake.clear()

        self._queue.close()

    # -- config ops (Operation.CONFIG) --------------------------------------
    def apply_config(self, options: CallOptions) -> ErrorCode:
        fn = ConfigFunction(options.cfg_function)
        val = options.cfg_value
        if fn == ConfigFunction.RESET:
            with self._notif_lock:
                self._rndzv_inits.clear()
                self._rndzv_done.clear()
            self.transport_enabled = False
        elif fn == ConfigFunction.ENABLE_TRANSPORT:
            self.transport_enabled = True
        elif fn == ConfigFunction.SET_TIMEOUT:
            if val <= 0:
                return ErrorCode.CONFIG_ERROR
            self.timeout_s = float(val)
        elif fn == ConfigFunction.SET_MAX_EAGER_SIZE:
            if not 0 < val <= MAX_EAGER_SIZE_LIMIT:
                return ErrorCode.CONFIG_ERROR
            self.max_eager_size = int(val)
        elif fn == ConfigFunction.SET_MAX_RENDEZVOUS_SIZE:
            if val <= 0:
                return ErrorCode.CONFIG_ERROR
            self.max_rendezvous_size = int(val)
        elif fn == ConfigFunction.SET_TUNING:
            from ...constants import (
                ALGORITHM_TUNING_KEYS,
                AllreduceAlgorithm,
                ROOTED_ALGORITHMS,
                TUNING_KEY_NAMES,
                TuningKey,
            )

            try:
                key = TuningKey(int(options.cfg_key))
            except ValueError:
                return ErrorCode.CONFIG_ERROR
            if val < 0:
                return ErrorCode.CONFIG_ERROR
            # per-key validation matches the XLA/native tiers so code
            # validated against the emulator doesn't skew on device
            if key == TuningKey.GATHER_FLAT_TREE_MAX_FANIN and val < 1:
                return ErrorCode.CONFIG_ERROR
            if key == TuningKey.RING_SEGMENTS and val < 1:
                return ErrorCode.CONFIG_ERROR
            if key in ALGORITHM_TUNING_KEYS:
                try:
                    algo = AllreduceAlgorithm(int(val))
                except ValueError:
                    return ErrorCode.CONFIG_ERROR
                if (
                    key != TuningKey.ALLREDUCE_ALGORITHM
                    and algo not in ROOTED_ALGORITHMS
                ):
                    return ErrorCode.CONFIG_ERROR
            # device-tier registers (algorithm select) are accepted and
            # stored but don't affect the emulated firmware algorithms
            self.tuning[TUNING_KEY_NAMES[key]] = int(val)
        else:
            return ErrorCode.CONFIG_ERROR
        return ErrorCode.OK
