"""The emulated collective engine: a cooperative scheduler running collective
algorithms over the fake wire.

This is the TPU-build analog of the reference's control-plane firmware main
loop (``ccl_offload_control.c:2308-2483``): calls arrive on a command queue,
each executes as a *generator* that yields wait-conditions (see
``engine_conditions.py``); calls whose condition is unmet are parked and
re-polled round-robin — the same cooperative retry-queue semantics the
firmware implements with ``NOT_READY_ERROR`` recirculation and
``current_step`` resume state (``:2460-2478``), expressed idiomatically as
Python coroutines instead of a hand-rolled step machine.

One engine == one rank.  Data lives in numpy "device" memory; the dataplane
(RX pool, reductions, casts, streams) is in ``dataplane.py``; the wire in
``fabric.py``; the algorithms in ``algorithms.py``.
"""

from __future__ import annotations

import itertools
import sys
import threading
import time
import traceback
import weakref
from typing import Callable, Dict, List, Optional

from ...communicator import Communicator
from ...constants import (
    ConfigFunction,
    DEFAULT_RETRY_BACKOFF_S,
    DEFAULT_RX_BUFFER_COUNT,
    DEFAULT_RX_BUFFER_SIZE,
    DEFAULT_TIMEOUT_S,
    EAGER_THRESHOLD_DEFAULT,
    ErrorCode,
    MAX_EAGER_SIZE_LIMIT,
    MAX_RETRY_LIMIT,
    Operation,
    TUNING_DEFAULTS,
)
from ...contract import verdict_context
from ...faults import PeerDeadError, SeqnLedger
from ...request import CommandQueue, Request
from ..base import BaseEngine, CallOptions
from . import algorithms
from .dataplane import RxBuffer, RxBufferPool, RxStatus, StreamPorts
from .engine_conditions import WaitCondition
from .fabric import Endpoint, Fabric, Message, MsgType

# Scheduler threads that outlived their shutdown join: a leak here means an
# engine wedged mid-call and the process is carrying a zombie scheduler.
# Registered by EmuEngine.shutdown, reaped as threads actually exit —
# exposed so soak/churn tests can assert none leaked.
_leaked_threads: List[weakref.ref] = []
_leaked_lock = threading.Lock()


def leaked_scheduler_threads() -> List[str]:
    """Names of engine scheduler threads that failed to join at shutdown
    and are STILL alive."""
    with _leaked_lock:
        alive = []
        live_refs = []
        for ref in _leaked_threads:
            t = ref()
            if t is not None and t.is_alive():
                alive.append(t.name)
                live_refs.append(ref)
        _leaked_threads[:] = live_refs
        return alive


#: operations that talk to peers (fail-fast candidates against a dead rank)
_COMM_OPS = frozenset((
    Operation.SEND, Operation.RECV, Operation.BCAST, Operation.SCATTER,
    Operation.GATHER, Operation.ALLGATHER, Operation.REDUCE,
    Operation.ALLREDUCE, Operation.REDUCE_SCATTER, Operation.ALLTOALL,
    Operation.BARRIER,
))


class _RetransEntry:
    __slots__ = ("msg", "address", "attempts", "due")

    def __init__(self, msg: Message, address: str, due: float):
        self.msg = msg
        self.address = address
        self.attempts = 0
        self.due = due


class _CallTask:
    __slots__ = ("request", "gen", "cond", "deadline", "started_ns",
                 "options")

    def __init__(self, request: Request, gen, timeout_s: float,
                 options: Optional[CallOptions] = None):
        self.request = request
        self.gen = gen
        self.cond: Optional[WaitCondition] = None
        self.deadline = time.monotonic() + timeout_s
        self.started_ns = time.perf_counter_ns()
        self.options = options


class EmuEngine(BaseEngine):
    def __init__(
        self,
        fabric: Fabric,
        address: str,
        rx_buffer_count: int = DEFAULT_RX_BUFFER_COUNT,
        rx_buffer_size: int = DEFAULT_RX_BUFFER_SIZE,
    ):
        self.fabric = fabric
        self.address = address
        self.endpoint = Endpoint()
        fabric.attach(address, self.endpoint)
        self.rx_pool = RxBufferPool(rx_buffer_count, rx_buffer_size)
        self.streams = StreamPorts()
        self.timeout_s = DEFAULT_TIMEOUT_S
        self.max_eager_size = EAGER_THRESHOLD_DEFAULT
        self.max_rendezvous_size = MAX_EAGER_SIZE_LIMIT
        self.tuning = dict(TUNING_DEFAULTS)
        self.transport_enabled = False
        # retry policy (ConfigFunction.SET_RETRY_LIMIT / SET_RETRY_BACKOFF,
        # ACCL.set_retry_policy): limit 0 = the classic fire-and-forget
        # eager send; limit > 0 arms per-segment ACKs + retransmit with
        # exponential backoff (receiver-side seqn dedup keeps duplicates
        # value-correct)
        self.retry_limit = 0
        self.retry_backoff_s = DEFAULT_RETRY_BACKOFF_S
        # overlap-plane parity knob (ConfigFunction.SET_INFLIGHT_WINDOW):
        # this tier completes requests from its own scheduler threads —
        # launches never block on completion — so the window depth is
        # accepted + reported for portability, not enforced as a bound
        from ...overlap import default_window_depth

        self.inflight_window = default_window_depth()
        # QoS arbiter plane: engine-side mirror of SET_TENANT_* writes
        # (comm id -> {class, weight, window_share, ring_slots, rate})
        self.tenants: Dict[int, dict] = {}

        # contract plane (accl_tpu.contract, ACCL_VERIFY=1): armed by the
        # facade via set_contract_verifier — intake screens and active
        # calls fail fast on a standing cross-rank divergence verdict
        self.contract_verifier = None

        self._rndzv_inits: List[Message] = []
        self._rndzv_done: List[Message] = []
        self._notif_lock = threading.Lock()
        self._vaddr_counter = itertools.count(1)
        # retransmit window (engine-thread only):
        # (comm, peer, epoch, seqn) -> entry
        self._retrans: Dict[tuple, _RetransEntry] = {}
        # receiver-side duplicate detection (engine-thread only)
        self._ledger = SeqnLedger()
        # per-peer-address health: timeout/retry accounting feeding the
        # graceful-degradation map (capabilities()["health"]); a peer
        # marked "dead" fails new collectives fast at call intake
        self._health: Dict[str, dict] = {}
        # telemetry counters (accl_tpu.telemetry snapshot): recovery-
        # protocol event totals the metrics registry absorbs
        self._retransmits_total = 0
        self._dedup_discards_total = 0
        # membership plane: pre-shrink straggler frames discarded by
        # the epoch screen (see Message.mbr).  The fence is COMM-scoped
        # (_mbr_floor: comm id -> minimum accepted epoch, written at
        # cutover): traffic on communicators that never shrank must
        # keep flowing whatever the sender's global epoch says.
        self._mbr_drops = 0
        self._mbr_floor: Dict[int, int] = {}
        # cutover purges queued by the facade thread, applied ON the
        # scheduler thread (the rx pool / ledger / retransmit window /
        # health map are scheduler-owned state; a cross-thread mutation
        # races _route_inbox mid-iteration)
        self._mbr_cutovers: List[tuple] = []
        self.leaked_scheduler_thread = False

        self._queue = CommandQueue()
        self._wake = threading.Event()
        self.endpoint.on_activity = self._wake.set
        self._stop = False
        self._thread = threading.Thread(
            target=self._run, name=f"accl-engine-{address}", daemon=True
        )
        self._thread.start()

    # -- public API ---------------------------------------------------------
    def start(self, options: CallOptions) -> Request:
        req = Request(op_name=options.op.name)
        self._queue.push((req, options))
        self._wake.set()
        return req

    def shutdown(self, join_timeout: float = 5.0) -> None:
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=join_timeout)
        if self._thread.is_alive():
            # the scheduler thread is wedged (a call stuck in non-yielding
            # work): don't mask it — log loudly and register the zombie so
            # soak/churn tests can assert no leaked scheduler threads
            self.leaked_scheduler_thread = True
            with _leaked_lock:
                _leaked_threads.append(weakref.ref(self._thread))
            print(
                f"[accl engine {self.address}] LEAK: scheduler thread "
                f"{self._thread.name!r} did not exit within "
                f"{join_timeout}s of shutdown — a call is wedged; the "
                "thread is now a daemon zombie",
                file=sys.stderr,
            )
        detach = getattr(self.fabric, "detach", None)
        if detach is not None:
            # leave the fabric honestly: later sends to this rank fail
            # fast with SEND_TIMEOUT instead of being silently dropped
            detach(self.address)
        self.fabric.close()

    def stream_push(self, stream_id: int, data: bytes) -> None:
        self.streams.push(stream_id, data)
        self._wake.set()

    def stream_pop(self, stream_id: int, timeout: Optional[float] = None) -> bytes:
        return self.streams.pop(stream_id, timeout=timeout)

    def new_vaddr(self) -> int:
        return next(self._vaddr_counter)

    # -- contract plane (accl_tpu.contract) ----------------------------------
    def contract_anchor(self):
        """The object the contract plane's in-process exchange board
        anchors on: the InProc fabric — shared by every InProc rank
        engine, so their verifiers meet on one board.  A SocketFabric
        serves exactly one rank per process: no board (single-poster
        boards only cost ring copies), the wire piggyback does the
        comparing."""
        from .fabric import InProcFabric

        return self.fabric if isinstance(self.fabric, InProcFabric) else None

    def set_contract_verifier(self, verifier) -> None:
        """Arm (or with ``None`` disarm) cross-rank contract checks on
        this engine: inbound digest claims are observed at delivery, and
        a standing divergence verdict fails queued + active calls fast
        (CONTRACT_VIOLATION) instead of letting them time out."""
        self.contract_verifier = verifier
        if verifier is None:
            self.endpoint.contract_hook = None
            return

        def observe(msg, v=verifier):
            if msg.msg_type == MsgType.VERIFY:
                # a peer convicted a divergence and relayed the verdict:
                # adopt it so this rank's in-flight calls fail fast too
                import json as _json

                try:
                    verdict = _json.loads(msg.payload.decode())
                except (ValueError, UnicodeDecodeError):
                    return
                v.adopt_verdict(msg.comm_id, verdict, src_rank=msg.src)
                return
            v.observe_claim(
                msg.comm_id, msg.src, msg.vfy_gen, msg.vfy_window,
                msg.vfy_digest,
            )

        self.endpoint.contract_hook = observe
        verifier.add_verdict_listener(lambda _vd: self._wake.set())

    # -- monitor plane (accl_tpu.monitor) ------------------------------------
    def set_skew_tracker(self, tracker) -> None:
        """Arm straggler-skew exchange: peers' piggybacked (window,
        mean_wait) claims are observed at delivery — same cadence and
        hook shape as the contract digest piggyback.  On the InProc
        fabric the shared judge already exchanges in-process; the hook
        is still wired so the one mechanism covers both fabrics."""
        self.skew_tracker = tracker
        if tracker is None:
            self.endpoint.skew_hook = None
            return

        def observe(msg, tracker=tracker):
            if msg.sent_ns:
                tracker.on_message(
                    msg.comm_id, msg.src, time.time_ns() - msg.sent_ns
                )
            tracker.observe_claim(
                msg.comm_id, msg.src, msg.skw_window, msg.skw_mean_us
            )

        self.endpoint.skew_hook = observe

    def skew_exchange_mode(self) -> str:
        from .fabric import InProcFabric

        return "board" if isinstance(self.fabric, InProcFabric) else "wire"

    # -- postmortem plane (accl_tpu.monitor.BlackBox) -------------------------
    def set_postmortem(self, handler) -> None:
        """Route POSTMORTEM solicitation frames to the facade's
        BlackBox handler at delivery — the wire half of the bundle
        solicitation on one-process-per-rank fabrics (the board tiers
        solicit in process and never send frames)."""
        self.postmortem_handler = handler
        self.endpoint.postmortem_hook = handler

    # -- membership plane (accl_tpu.membership) ------------------------------
    def set_membership(self, view) -> None:
        """Arm (or with ``None`` disarm) the membership plane: MEMBER
        agreement frames are observed at delivery (the wire exchange on
        socket fabrics; harmless duplicate tallies on InProc where the
        board already exchanged), and a confirmed eviction wakes the
        scheduler so in-flight calls against the evicted rank fail
        fast instead of burning their deadline."""
        self.membership = view
        if view is None:
            self.endpoint.membership_hook = None
            return

        def observe(msg, v=view):
            from ...membership import member_payload

            payload = member_payload(msg.payload)
            if payload is not None:
                v.observe_wire(payload, msg.src)

        self.endpoint.membership_hook = observe
        view.add_listener(lambda _evt: self._wake.set())

    def _membership_failure(self, options: Optional[CallOptions],
                            peer_rank: Optional[int],
                            default_code: ErrorCode) -> tuple:
        """(code, extra_context) for a failed call against ``peer_rank``
        (comm-relative): RANK_EVICTED + agreement evidence when the
        membership plane holds a confirmed (or applied) eviction
        covering that peer — the structured terminal the shrink
        protocol promises for in-flight work — else the tier's own
        timeout code."""
        mv = self.membership
        if (
            mv is None or options is None or options.comm is None
            or peer_rank is None
        ):
            return default_code, {}
        try:
            session = options.comm.ranks[peer_rank].session
        except IndexError:
            return default_code, {}
        if mv.plan_covers(session):
            return ErrorCode.RANK_EVICTED, {"membership": mv.evidence()}
        return default_code, {}

    def _evicted_peer_for(self, options: CallOptions) -> Optional[int]:
        """Comm-relative rank of a participating peer under a confirmed
        eviction, or None — the active-task sweep's screen (mirrors
        ``_dead_peer_for`` but consults the agreed plan, which can
        land while the health map still says ``suspect``)."""
        mv = self.membership
        comm = options.comm
        if mv is None or comm is None or options.op not in _COMM_OPS:
            return None
        if not (mv.cutover_ready() or mv.evicted):
            return None
        if options.op == Operation.SEND:
            candidates = [options.root_dst]
        elif options.op == Operation.RECV:
            candidates = [options.root_src]
        else:
            candidates = [
                r for r in range(comm.size) if r != comm.local_rank
            ]
        for r in candidates:
            if mv.plan_covers(comm.ranks[r].session):
                return r
        return None

    def on_membership_cutover(self, plan: dict, addresses: tuple = (),
                              comm_ids: tuple = ()) -> None:
        """Queue the post-shrink purge for the SCHEDULER thread (the rx
        pool, dedup ledger, retransmit window and health map are
        scheduler-owned; mutating them from the facade thread races
        _route_inbox mid-iteration) and raise the shrunk comms'
        stale-frame fence floors.  The scheduler drains the queue
        before popping any later intake item, so the purge strictly
        precedes the first post-shrink collective."""
        mv = self.membership
        if mv is not None:
            for cid in comm_ids:
                self._mbr_floor[cid] = mv.epoch
        with self._notif_lock:
            self._mbr_cutovers.append(
                (tuple(addresses), tuple(comm_ids))
            )
        self._wake.set()

    def _apply_membership_purge(self, addresses: tuple,
                                comm_ids: tuple) -> None:
        """The purge itself (scheduler thread only), the per-comm
        analog of the soft-reset full flush: drop the shrunk comms'
        STALE parked rx segments, inbox frames, retransmit and
        rendezvous entries of the ABORTED pre-shrink collective (its
        chunk geometry differs from the post-shrink one, and seqn
        matching ignores epochs, so a stale chunk would corrupt the
        first shrunk collective).  Epoch-aware via the fence floors: a
        fast peer that cut over first may already have POST-shrink
        frames parked here — those carry the new membership epoch and
        survive.  The dedup ledger is deliberately NOT purged: its
        keys carry the sender's communicator-instance epoch, which the
        shrink refreshed, so post-shrink segments never collide with
        pre-shrink floors (the PR 2 epoch design).  Also drops the
        evicted peers' health entries and clears the suspect strikes
        the failure cascade accrued against the SURVIVORS (a rank
        stalled behind the dead one is not sick)."""
        ids = set(comm_ids)
        if ids:
            floors = {c: self._mbr_floor.get(c, 0) for c in ids}

            def stale(m, floors=floors):
                floor = floors.get(m.comm_id)
                return floor is not None and m.mbr < floor

            self.rx_pool.purge(floors)
            while self.endpoint.take_matching(stale) is not None:
                pass
            # retransmit entries for the shrunk comms are pre-cutover
            # by construction (this engine's own post-cutover sends
            # cannot precede the drain that runs this purge)
            for key in [k for k in self._retrans if k[0] in ids]:
                del self._retrans[key]
            with self._notif_lock:
                self._rndzv_inits = [
                    m for m in self._rndzv_inits if not stale(m)
                ]
                self._rndzv_done = [
                    m for m in self._rndzv_done if not stale(m)
                ]
        for a in addresses:
            self._health.pop(a, None)
        for h in self._health.values():
            if h["state"] == "suspect":
                h["state"] = "ok"
                h["timeouts"] = 0

    def _drain_membership_cutovers(self) -> None:
        """Apply queued cutover purges (scheduler thread).  Called
        before every intake pop: the cutover marker is queued strictly
        before the facade issues its first post-shrink collective, so
        draining here orders purge-before-serve."""
        if not self._mbr_cutovers:
            return
        with self._notif_lock:
            cutovers, self._mbr_cutovers = self._mbr_cutovers, []
        for addresses, comm_ids in cutovers:
            self._apply_membership_purge(addresses, comm_ids)

    def _contract_verdict_for(self, options: Optional[CallOptions]):
        v = self.contract_verifier
        if (
            v is None or not v.has_verdict or options is None
            or options.comm is None or options.op not in _COMM_OPS
        ):
            return None
        return v.check(options.comm.id)

    # -- wire helpers used by algorithms ------------------------------------
    def post(self, comm: Communicator, dst: int, msg: Message) -> None:
        addr = comm.ranks[dst].address
        mv = self.membership
        if mv is not None:
            # membership-epoch stamp: globally aligned by the eviction
            # agreement, so receivers can discard stale pre-shrink
            # frames (see Message.mbr)
            msg.mbr = mv.epoch
        try:
            self.fabric.send(addr, msg)
        except PeerDeadError:
            self._health_note(addr, "peer_dead", dead=True)
            raise

    def post_eager(self, comm: Communicator, dst: int, msg: Message) -> None:
        """Post an eager segment; with a retry policy armed (retry_limit >
        0) the segment requests an ACK and enters the retransmit window —
        unacked segments are re-sent with exponential backoff up to the
        retry limit (the recovery loop the reference's NOT_READY_ERROR
        stream plays for its transports)."""
        if self.retry_limit > 0:
            msg.ack = 1
            msg.reply_to = self.address
        self.post(comm, dst, msg)
        if self.retry_limit > 0:
            key = (msg.comm_id, dst, msg.epoch, msg.seqn)
            self._retrans[key] = _RetransEntry(
                msg,
                comm.ranks[dst].address,
                time.monotonic() + self.retry_backoff_s,
            )

    # -- peer health (graceful degradation) ----------------------------------
    def _health_note(self, addr: str, event: str, dead: bool = False) -> None:
        h = self._health.setdefault(
            addr, {"state": "ok", "timeouts": 0, "failures": 0,
                   "last_event": ""}
        )
        old = h["state"]
        if event == "timeout":
            h["timeouts"] += 1
        else:
            h["failures"] += 1
        h["last_event"] = event
        # one timeout makes a peer suspect; repeated timeouts (2 strikes,
        # matching the XLA gang watchdog policy) or a hard failure mark it
        # dead — later collectives addressing it fail fast until a
        # soft_reset clears the verdict
        if dead or h["timeouts"] >= 2:
            h["state"] = "dead"
        elif h["state"] != "dead":
            h["state"] = "suspect"
        hook = self.on_health_transition
        if hook is not None and h["state"] != old:
            # the facade's transition hook: health-event ring + counter
            # and, under elastic membership, the dead->propose edge
            try:
                hook(addr, old, h["state"])
            except Exception:  # pragma: no cover - must never fail a call
                pass

    def health_report(self, comm: Communicator) -> Dict[int, dict]:
        """Per-peer health for ``comm``'s members, keyed by comm-relative
        rank (the graceful-degradation map of capabilities()["health"])."""
        report: Dict[int, dict] = {}
        for i, r in enumerate(comm.ranks):
            if i == comm.local_rank:
                continue
            h = self._health.get(r.address)
            report[i] = dict(h) if h else {
                "state": "ok", "timeouts": 0, "failures": 0, "last_event": ""
            }
        return report

    def _dead_peer_for(self, options: CallOptions) -> Optional[tuple]:
        """(rank, address) of a participating peer already marked dead, or
        None.  Only communicating ops are screened, and only against the
        peers the op actually addresses — local copy/combine/config must
        keep working next to a dead neighbor."""
        comm = options.comm
        if comm is None or options.op not in _COMM_OPS or not self._health:
            return None
        if options.op == Operation.SEND:
            candidates = [options.root_dst]
        elif options.op == Operation.RECV:
            candidates = [options.root_src]
        else:
            candidates = [r for r in range(comm.size) if r != comm.local_rank]
        for r in candidates:
            addr = comm.ranks[r].address
            h = self._health.get(addr)
            if h is not None and h["state"] == "dead":
                return r, addr
        return None

    def take_rndzv_init(self, pred: Callable[[Message], bool]):
        with self._notif_lock:
            for i, m in enumerate(self._rndzv_inits):
                if pred(m):
                    return self._rndzv_inits.pop(i)
        return None

    def take_rndzv_done(self, pred: Callable[[Message], bool]):
        with self._notif_lock:
            for i, m in enumerate(self._rndzv_done):
                if pred(m):
                    return self._rndzv_done.pop(i)
        return None

    def rx_seek_overflow(self, comm_id: int, src: int, tag: int, seqn: int):
        """Head-of-line escape for a fully parked pool.  When every rx slot
        holds eager segments for OTHER signatures — e.g. a rank that isn't
        a member of the current subcommunicator op racing ahead into the
        next collective and fire-hosing its segments first — the segment
        the CURRENT op needs waits in the unbounded inbox and could never
        be parked: a deadlock the multi-process soak caught.  Consume it
        straight from the inbox instead.  The pool stays the normal path
        (the gate below) so slot-lifecycle accounting keeps meaning; the
        reference's single shared link cannot reorder like this, but its
        seek loop + retry queue serve the same role of decoupling match
        order from arrival order (rxbuf_seek, dma_mover.cpp:587-611)."""
        used, total = self.rx_pool.occupancy()
        if used < total:
            return None  # pool has room: routing will park it normally
        msg = self.endpoint.take_matching(
            lambda m: (
                m.msg_type == MsgType.EAGER
                and m.comm_id == comm_id
                and m.src == src
                and m.tag == tag
                and m.seqn == seqn
            )
        )
        if msg is None:
            return None
        # inbox-consumed segments still join the dedup ledger and get
        # acked, exactly like the pool path, so retransmits/duplicates of
        # them are discarded instead of leaking into the pool later
        self._ledger.seen((msg.comm_id, msg.src, msg.epoch), msg.seqn)
        self._maybe_ack(msg)
        return RxBuffer(-1, len(msg.payload), RxStatus.CLAIMED, msg)

    def _maybe_ack(self, msg: Message) -> None:
        """ACK a delivered eager segment when the sender asked for one
        (retransmit protocol).  Duplicates are re-acked — the original ACK
        may have been the thing the network lost."""
        if not msg.ack or not msg.reply_to:
            return
        ack = Message(
            MsgType.ACK, msg.comm_id, msg.dst, msg.src, msg.tag,
            seqn=msg.seqn, epoch=msg.epoch,
        )
        try:
            self.fabric.send(msg.reply_to, ack)
        except Exception:
            pass  # a dead/fault-dropped ack path: the sender's backoff rules

    # -- debug dumps (ref ACCL::dump_eager_rx_buffers) -----------------------
    def dump_rx_buffers(self) -> str:
        return "\n".join(self.rx_pool.dump())

    def telemetry_report(self) -> dict:
        """Emulator-tier counters for the telemetry snapshot: rx-pool
        depth, inbox backlog, the recovery protocol's live window and
        event totals, and the armed fault plan's fire counters."""
        used, total = self.rx_pool.occupancy()
        inj = getattr(self.fabric, "fault_injector", None)
        return {
            "device_interactions": None,
            "rx_pool": {"used": used, "total": total},
            "inbox_depth": self.endpoint.pending(),
            "retransmit_window": len(self._retrans),
            "retransmits_total": self._retransmits_total,
            "dedup_discards_total": self._dedup_discards_total,
            "membership_drops_total": self._mbr_drops,
            "retry_limit": self.retry_limit,
            "inflight_window": self.inflight_window,
            # QoS arbiter plane: the engine-side tenant quota mirror
            "tenants": {str(k): dict(v) for k, v in
                        sorted(self.tenants.items())},
            "faults": inj.stats() if inj is not None else None,
            # monitor plane: how this rank's straggler samples reach
            # its peers (board = shared in-process judge, wire = the
            # per-message piggyback on the socket fabric)
            "skew_exchange": self.skew_exchange_mode(),
            # topology plane: per-link-class byte/message counters +
            # modeled rates (shared across ranks on the in-proc
            # fabric; None until a topology registers)
            "wire_classes": (
                self.fabric.wire_class_stats()
                if getattr(self.fabric, "_topologies", None)
                else None
            ),
        }

    # -- scheduler ----------------------------------------------------------
    def _route_inbox(self) -> None:
        """Move arrived messages to their stations (the rxbuf_enqueue/dequeue
        + depacketizer-routing roles).  EAGER messages stay in the inbox while
        the pool is exhausted — backpressure, not drop."""
        while True:
            routed_any = False
            msg = self.endpoint.take_matching(
                lambda m: m.msg_type != MsgType.EAGER
            )
            if msg is not None:
                routed_any = True
                if msg.msg_type == MsgType.RNDZV_INIT:
                    with self._notif_lock:
                        self._rndzv_inits.append(msg)
                elif msg.msg_type == MsgType.RNDZV_WR_DONE:
                    with self._notif_lock:
                        self._rndzv_done.append(msg)
                elif msg.msg_type == MsgType.STREAM:
                    self.streams.push(msg.strm, msg.payload)
                elif msg.msg_type == MsgType.ACK:
                    # a peer confirmed an eager segment: retire it from
                    # the retransmit window (ack.src is the acking peer)
                    self._retrans.pop(
                        (msg.comm_id, msg.src, msg.epoch, msg.seqn), None
                    )
            used, total = self.rx_pool.occupancy()
            if used < total:
                emsg = self.endpoint.take_matching(
                    lambda m: m.msg_type == MsgType.EAGER
                )
                if emsg is not None:
                    routed_any = True
                    floor = self._mbr_floor.get(emsg.comm_id)
                    if floor is not None and emsg.mbr < floor:
                        # a pre-shrink straggler frame on a SHRUNK comm
                        # (the sender's membership epoch lags the
                        # cutover floor): discard — its chunk geometry
                        # belongs to the aborted collective and seqn
                        # matching would hand it to the first
                        # post-shrink receive.  Comm-scoped: traffic on
                        # communicators that never shrank keeps flowing
                        # whatever the sender's global epoch says.
                        self._mbr_drops += 1
                        continue
                    self._maybe_ack(emsg)
                    if not self._ledger.seen(
                        (emsg.comm_id, emsg.src, emsg.epoch), emsg.seqn
                    ):
                        self.rx_pool.fill(emsg, timeout=0)
                    else:
                        # duplicate (fault-injected or a retransmit whose
                        # original arrived) — re-acked above, then
                        # discarded so it can never occupy a pool slot
                        self._dedup_discards_total += 1
            if not routed_any:
                return

    @staticmethod
    def _rank_of_address(options: Optional[CallOptions],
                         addr: Optional[str]) -> Optional[int]:
        """Comm-relative rank behind a transport address, or None."""
        if options is None or options.comm is None or addr is None:
            return None
        for i, r in enumerate(options.comm.ranks):
            if r.address == addr:
                return i
        return None

    def _task_context(self, task: _CallTask, peer=None, attempts=None) -> dict:
        """Structured ACCLError context for a failed call (op, comm, peer,
        attempts, elapsed) — the diagnosable trail the chaos tests assert."""
        ctx = {
            "op": task.request.op_name,
            "elapsed_s": round(
                (time.perf_counter_ns() - task.started_ns) / 1e9, 3
            ),
        }
        if task.options is not None and task.options.comm is not None:
            ctx["comm"] = task.options.comm.id
        if peer is not None:
            ctx["peer"] = peer
        if attempts is not None:
            ctx["attempts"] = attempts
        return ctx

    def _service_retransmits(self, now: float) -> None:
        """Re-send unacked eager segments past their backoff deadline;
        exponential backoff doubles per attempt.  Retry exhaustion marks
        the peer dead — the graceful-degradation path that turns a
        blackholed link into fast failures instead of hangs."""
        if not self._retrans:
            return
        for key, ent in list(self._retrans.items()):
            if now < ent.due:
                continue
            if ent.attempts >= self.retry_limit:
                del self._retrans[key]
                self._health_note(ent.address, "retry_exhausted", dead=True)
                continue
            ent.attempts += 1
            ent.due = now + self.retry_backoff_s * (2 ** ent.attempts)
            self._retransmits_total += 1
            try:
                self.fabric.send(ent.address, ent.msg)
            except (PeerDeadError, KeyError, OSError):
                del self._retrans[key]
                self._health_note(ent.address, "peer_dead", dead=True)

    def _run(self) -> None:
        active: List[_CallTask] = []
        while not self._stop:
            while True:
                # cutover purges strictly precede any intake item
                # queued after them (the marker is appended before the
                # facade returns from _apply_cutover, hence before its
                # first post-shrink collective is queued)
                self._drain_membership_cutovers()
                item = self._queue.pop(timeout=0)
                if item is None:
                    break
                req, options = item
                req.mark_executing()
                verdict = self._contract_verdict_for(options)
                if verdict is not None:
                    # the contract verifier proved this communicator's
                    # ranks diverged: fail at intake instead of burning
                    # the call deadline on traffic that cannot match
                    req.complete(
                        ErrorCode.CONTRACT_VIOLATION, 0,
                        context=verdict_context(verdict, options.op.name),
                    )
                    continue
                mv = self.membership
                if (
                    mv is not None and mv.self_evicted
                    and options.op in _COMM_OPS and options.comm is not None
                ):
                    # this rank was voted out of the group: every comm
                    # op fails fast with the agreement evidence (local
                    # copy/combine/config keep working)
                    req.complete(ErrorCode.RANK_EVICTED, 0, context={
                        "op": options.op.name,
                        "comm": options.comm.id,
                        "membership": mv.evidence(),
                        "elapsed_s": 0.0,
                    })
                    continue
                dead = self._dead_peer_for(options)
                if dead is not None:
                    # fail fast: the peer is already known dead — don't
                    # burn the full call deadline discovering it again
                    rank_d, addr = dead
                    code = (
                        ErrorCode.RECEIVE_TIMEOUT
                        if options.op == Operation.RECV
                        else ErrorCode.SEND_TIMEOUT
                    )
                    code, extra = self._membership_failure(
                        options, rank_d, code
                    )
                    h = self._health.get(addr, {})
                    req.complete(code, 0, context=dict({
                        "op": options.op.name,
                        "comm": options.comm.id,
                        "peer": addr,
                        "attempts": h.get("failures", 0),
                        "elapsed_s": 0.0,
                    }, **extra))
                    continue
                evicted = (
                    self._evicted_peer_for(options)
                    if mv is not None else None
                )
                if evicted is not None:
                    # the surviving majority agreed this peer is out
                    # (possibly before local health caught up): the
                    # structured terminal, carrying the evidence
                    req.complete(ErrorCode.RANK_EVICTED, 0, context={
                        "op": options.op.name,
                        "comm": options.comm.id,
                        "peer": options.comm.ranks[evicted].address,
                        "membership": mv.evidence(),
                        "elapsed_s": 0.0,
                    })
                    continue
                gen = algorithms.dispatch(self, options)
                active.append(_CallTask(req, gen, self.timeout_s, options))

            self._route_inbox()
            self._service_retransmits(time.monotonic())

            cv = self.contract_verifier
            if cv is not None and cv.has_verdict and active:
                # a divergence verdict landed (boundary exchange or a
                # peer's piggybacked claim) while calls are in flight:
                # those calls' traffic can never match — fail them fast
                # instead of letting each burn its full deadline
                for task in list(active):
                    verdict = self._contract_verdict_for(task.options)
                    if verdict is None:
                        continue
                    task.gen.close()
                    task.request.complete(
                        ErrorCode.CONTRACT_VIOLATION,
                        time.perf_counter_ns() - task.started_ns,
                        context=verdict_context(
                            verdict, task.request.op_name
                        ),
                    )
                    active.remove(task)

            mv = self.membership
            if mv is not None and active and (
                mv.cutover_ready() or mv.self_evicted
            ):
                # a confirmed eviction landed while calls are in
                # flight: work addressing the evicted rank can never
                # complete — fail it fast with the agreement evidence
                # instead of letting each call burn its deadline
                for task in list(active):
                    if task.options is None:
                        continue
                    hit = (
                        mv.self_evicted
                        and task.options.op in _COMM_OPS
                        and task.options.comm is not None
                    ) or self._evicted_peer_for(task.options) is not None
                    if not hit:
                        continue
                    task.gen.close()
                    task.request.complete(
                        ErrorCode.RANK_EVICTED,
                        time.perf_counter_ns() - task.started_ns,
                        context=dict(
                            self._task_context(task),
                            membership=mv.evidence(),
                        ),
                    )
                    active.remove(task)

            progressed = False
            now = time.monotonic()
            for task in list(active):
                value = None
                if task.cond is not None:
                    value = task.cond.poll(self)
                    if value is None:
                        if now > task.deadline:
                            peer = getattr(task.cond, "peer_addr", None)
                            if peer is not None:
                                self._health_note(peer, "timeout")
                            code = task.cond.timeout_code
                            ctx = self._task_context(task, peer=peer)
                            peer_rank = self._rank_of_address(
                                task.options, peer
                            )
                            code, extra = self._membership_failure(
                                task.options, peer_rank, code
                            )
                            ctx.update(extra)
                            task.request.complete(
                                code,
                                time.perf_counter_ns() - task.started_ns,
                                context=ctx,
                            )
                            active.remove(task)
                            progressed = True
                        continue
                    task.cond = None
                try:
                    task.cond = task.gen.send(value)
                    progressed = True
                except StopIteration as stop:
                    ret = stop.value if stop.value is not None else ErrorCode.OK
                    task.request.complete(
                        ret, time.perf_counter_ns() - task.started_ns
                    )
                    active.remove(task)
                    progressed = True
                except PeerDeadError as dead_exc:
                    # a send hit a dead/detached endpoint: fast, diagnosable
                    # SEND_TIMEOUT (the silent-drop fix of fabric.py:222) —
                    # or RANK_EVICTED when the group already agreed the
                    # peer is out (membership plane)
                    ctx = self._task_context(task, peer=dead_exc.address)
                    code, extra = self._membership_failure(
                        task.options,
                        self._rank_of_address(
                            task.options, dead_exc.address
                        ),
                        ErrorCode.SEND_TIMEOUT,
                    )
                    ctx.update(extra)
                    task.request.complete(
                        code,
                        time.perf_counter_ns() - task.started_ns,
                        context=ctx,
                    )
                    active.remove(task)
                    progressed = True
                except Exception:
                    traceback.print_exc()
                    task.request.complete(
                        ErrorCode.INVALID_OPERATION,
                        time.perf_counter_ns() - task.started_ns,
                    )
                    active.remove(task)
                    progressed = True

            if not progressed:
                timeout = 0.001 if active else 0.05
                if self._retrans:
                    timeout = min(timeout, self.retry_backoff_s / 2)
                self._wake.wait(timeout=timeout)
                self._wake.clear()

        self._queue.close()

    # -- config ops (Operation.CONFIG) --------------------------------------
    def apply_config(self, options: CallOptions) -> ErrorCode:
        fn = ConfigFunction(options.cfg_function)
        val = options.cfg_value
        if fn == ConfigFunction.RESET:
            with self._notif_lock:
                self._rndzv_inits.clear()
                self._rndzv_done.clear()
            self.transport_enabled = False
            if val >= 1:
                # FULL reset (soft_reset recovery, never plain init — a
                # flush at init would race the socket tier's pre-attach
                # replay and drop fast peers' first segments): abandon all
                # stale wire state so a group that lost a collective to a
                # fault can realign
                self.rx_pool.reset()
                self.endpoint.clear()
                self._retrans.clear()
                self._ledger.clear()
                self._health.clear()
                # membership restore rides soft_reset: the stale-frame
                # fence floors belong to the pre-reset epochs (runs on
                # the scheduler thread, like the rest of the flush)
                self._mbr_floor.clear()
                with self._notif_lock:
                    self._mbr_cutovers.clear()
        elif fn == ConfigFunction.ENABLE_TRANSPORT:
            self.transport_enabled = True
        elif fn == ConfigFunction.SET_RETRY_LIMIT:
            if not 0 <= val <= MAX_RETRY_LIMIT:
                return ErrorCode.CONFIG_ERROR
            self.retry_limit = int(val)
        elif fn == ConfigFunction.SET_RETRY_BACKOFF:
            if val <= 0:
                return ErrorCode.CONFIG_ERROR
            self.retry_backoff_s = float(val)
        elif fn == ConfigFunction.SET_TIMEOUT:
            if val <= 0:
                return ErrorCode.CONFIG_ERROR
            self.timeout_s = float(val)
        elif fn == ConfigFunction.SET_MAX_EAGER_SIZE:
            if not 0 < val <= MAX_EAGER_SIZE_LIMIT:
                return ErrorCode.CONFIG_ERROR
            self.max_eager_size = int(val)
        elif fn == ConfigFunction.SET_MAX_RENDEZVOUS_SIZE:
            if val <= 0:
                return ErrorCode.CONFIG_ERROR
            self.max_rendezvous_size = int(val)
        elif fn == ConfigFunction.SET_INFLIGHT_WINDOW:
            from ...constants import MAX_INFLIGHT_WINDOW

            if not 1 <= val <= MAX_INFLIGHT_WINDOW:
                return ErrorCode.CONFIG_ERROR
            self.inflight_window = int(val)
        elif fn in (
            ConfigFunction.SET_TENANT_CLASS,
            ConfigFunction.SET_TENANT_WEIGHT,
            ConfigFunction.SET_TENANT_WINDOW_SHARE,
            ConfigFunction.SET_TENANT_RING_SLOTS,
            ConfigFunction.SET_TENANT_RATE,
        ):
            # QoS arbiter plane: this tier has no device window or ring
            # — enforcement lives in the facade's shared arbiter, which
            # bounds a tenant's outstanding admissions by its window
            # share.  ONE shared validator (arbiter.tenant_config_valid)
            # so a write accepted here can never be CONFIG_ERROR on
            # another tier.
            from ...arbiter import tenant_config_field, tenant_config_valid

            if not tenant_config_valid(fn, val):
                return ErrorCode.CONFIG_ERROR
            self.tenants.setdefault(
                int(options.cfg_key), {}
            )[tenant_config_field(fn)] = val
        elif fn == ConfigFunction.SET_TUNING:
            from ...constants import (
                ALGORITHM_TUNING_KEYS,
                AllreduceAlgorithm,
                ROOTED_ALGORITHMS,
                TUNING_KEY_NAMES,
                TuningKey,
            )

            try:
                key = TuningKey(int(options.cfg_key))
            except ValueError:
                return ErrorCode.CONFIG_ERROR
            if val < 0:
                return ErrorCode.CONFIG_ERROR
            # per-key validation matches the XLA/native tiers so code
            # validated against the emulator doesn't skew on device
            if key == TuningKey.GATHER_FLAT_TREE_MAX_FANIN and val < 1:
                return ErrorCode.CONFIG_ERROR
            if key == TuningKey.RING_SEGMENTS and val < 1:
                return ErrorCode.CONFIG_ERROR
            if key in (
                TuningKey.WIRE_DTYPE,
                TuningKey.WIRE_DTYPE_ICI,
                TuningKey.WIRE_DTYPE_DCN,
            ) and int(val) != 0:
                from ...wire import is_wire_dtype

                if not is_wire_dtype(int(val)):
                    return ErrorCode.CONFIG_ERROR
            if key == TuningKey.HIERARCHICAL and int(val) > 1:
                return ErrorCode.CONFIG_ERROR
            if key == TuningKey.CMDRING_RUN_WINDOWS:
                from ...constants import CMDRING_MAX_RUN_WINDOWS

                if int(val) > CMDRING_MAX_RUN_WINDOWS:
                    return ErrorCode.CONFIG_ERROR
            if key == TuningKey.CMDRING_LINGER_US and int(val) > 1_000_000:
                return ErrorCode.CONFIG_ERROR
            if key in ALGORITHM_TUNING_KEYS:
                try:
                    algo = AllreduceAlgorithm(int(val))
                except ValueError:
                    return ErrorCode.CONFIG_ERROR
                if (
                    key != TuningKey.ALLREDUCE_ALGORITHM
                    and algo not in ROOTED_ALGORITHMS
                ):
                    return ErrorCode.CONFIG_ERROR
            # device-tier registers (algorithm select) are accepted and
            # stored but don't affect the emulated firmware algorithms
            self.tuning[TUNING_KEY_NAMES[key]] = int(val)
        else:
            return ErrorCode.CONFIG_ERROR
        return ErrorCode.OK
