"""Emulator dataplane: eager RX buffers, reduction arithmetic, dtype casts,
device stream ports.

Role models in the reference:
* RX buffer lifecycle + tag/src/seqn matching — ``kernels/cclo/hls/
  rxbuf_offload/`` (enqueue/dequeue/seek/session).
* Reduction arithmetic — ``kernels/plugins/reduce_ops/reduce_ops.cpp``
  (SIMD SUM/MAX over {fp16, fp32, fp64, i32, i64}).
* fp32<->fp16 wire compression — ``kernels/plugins/hp_compression/``.
* Device stream ports — the CCLO's external-kernel AXIS ports used by
  ``stream_put`` (``driver/hls/accl_hls.h``).

Arithmetic is dispatched through the optional native C++ library
(``accl_tpu.native``) when built, with a numpy fallback.
"""

from __future__ import annotations

import dataclasses
import enum
import queue
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ...constants import DataType, ReduceFunction, dtype_to_numpy
from .fabric import Message


# ---------------------------------------------------------------------------
# Eager RX buffer pool
# ---------------------------------------------------------------------------


class RxStatus(enum.IntEnum):
    IDLE = 0
    FILLED = 1  # payload landed, awaiting seek
    CLAIMED = 2  # matched by a seek, being consumed


@dataclasses.dataclass
class RxBuffer:
    index: int
    size: int
    status: RxStatus = RxStatus.IDLE
    msg: Optional[Message] = None


class RxBufferPool:
    """Fixed pool of eager buffers with signature matching.

    ``fill`` parks an arriving eager segment in an idle buffer (the role of
    rxbuf_session + rxbuf_enqueue); ``seek`` matches {comm, src, tag, seqn}
    against filled buffers (rxbuf_seek); ``release`` recycles.  When the pool
    is exhausted the fill blocks — emulating link-level backpressure rather
    than dropping, which is what the reference's dummy stacks do.

    Signature matching runs in the native C++ matcher when the library is
    built (the rxbuf_seek hardware role); payloads always stay here.
    """

    def __init__(self, count: int, size: int):
        self._buffers = [RxBuffer(i, size) for i in range(count)]
        self._cv = threading.Condition()
        self._matcher = None
        if _native is not None and _native.available():
            try:
                self._matcher = _native.NativeRxMatcher(count)
            except Exception:
                self._matcher = None

    def fill(self, msg: Message, timeout: Optional[float] = None) -> bool:
        with self._cv:
            ok = self._cv.wait_for(
                lambda: any(b.status == RxStatus.IDLE for b in self._buffers),
                timeout,
            )
            if not ok:
                return False
            if self._matcher is not None:
                slot = self._matcher.fill(msg.comm_id, msg.src, msg.tag, msg.seqn)
                if slot >= 0:
                    b = self._buffers[slot]
                    b.status = RxStatus.FILLED
                    b.msg = msg
                    self._cv.notify_all()
                    return True
                return False  # pragma: no cover - cv guard keeps slots free
            for b in self._buffers:
                if b.status == RxStatus.IDLE:
                    b.status = RxStatus.FILLED
                    b.msg = msg
                    self._cv.notify_all()
                    return True
        return False  # pragma: no cover

    def seek(
        self, comm_id: int, src: int, tag: int, seqn: int
    ) -> Optional[RxBuffer]:
        with self._cv:
            if self._matcher is not None:
                slot = self._matcher.seek(comm_id, src, tag, seqn)
                if slot < 0:
                    return None
                b = self._buffers[slot]
                b.status = RxStatus.CLAIMED
                return b
            for b in self._buffers:
                m = b.msg
                if (
                    b.status == RxStatus.FILLED
                    and m is not None
                    and m.comm_id == comm_id
                    and m.src == src
                    and m.tag == tag
                    and m.seqn == seqn
                ):
                    b.status = RxStatus.CLAIMED
                    return b
        return None

    def release(self, buf: RxBuffer) -> None:
        if buf.index < 0:
            # overflow-consumed message (Engine.rx_seek_overflow): never
            # occupied a pool slot, nothing to recycle
            buf.status = RxStatus.IDLE
            buf.msg = None
            return
        with self._cv:
            if self._matcher is not None:
                self._matcher.release(buf.index)
            buf.status = RxStatus.IDLE
            buf.msg = None
            self._cv.notify_all()

    def purge(self, floors) -> int:
        """Release every FILLED slot holding a STALE segment for a
        shrunk communicator (``floors``: comm id -> minimum accepted
        membership epoch, the cutover fence) — the membership-plane
        cutover flush: a shrunk communicator's seqn space restarted,
        and a stale chunk of the aborted pre-shrink collective would
        match (and corrupt) the first post-shrink collective's
        receives.  Epoch-aware: a fast peer's POST-shrink frames may
        already be parked when this rank's purge runs — those carry
        ``msg.mbr >= floor`` and must survive.  CLAIMED slots are left
        alone (a consumer owns them).  Returns slots released."""
        with self._cv:
            n = 0
            for b in self._buffers:
                m = b.msg
                if b.status != RxStatus.FILLED or m is None:
                    continue
                floor = floors.get(m.comm_id)
                if floor is None or m.mbr >= floor:
                    continue
                n += 1
                if self._matcher is not None:
                    self._matcher.release(b.index)
                b.status = RxStatus.IDLE
                b.msg = None
            if n:
                self._cv.notify_all()
            return n

    def reset(self) -> int:
        """Force every slot back to IDLE (soft-reset recovery: stale
        segments from a faulted collective must not leak slots).  Returns
        the number of slots that were occupied."""
        with self._cv:
            n = 0
            for b in self._buffers:
                if b.status != RxStatus.IDLE:
                    n += 1
                    if self._matcher is not None:
                        self._matcher.release(b.index)
                    b.status = RxStatus.IDLE
                    b.msg = None
            if n:
                self._cv.notify_all()
            return n

    def occupancy(self) -> Tuple[int, int]:
        with self._cv:
            used = sum(1 for b in self._buffers if b.status != RxStatus.IDLE)
            return used, len(self._buffers)

    def dump(self) -> List[str]:
        with self._cv:
            out = []
            for b in self._buffers:
                desc = f"rxbuf[{b.index}] {b.status.name}"
                if b.msg is not None:
                    m = b.msg
                    desc += (
                        f" comm={m.comm_id} src={m.src} tag={m.tag}"
                        f" seqn={m.seqn} bytes={len(m.payload)}"
                    )
                out.append(desc)
            return out


# ---------------------------------------------------------------------------
# Reduction arithmetic + casts (numpy fallback; native C++ when available)
# ---------------------------------------------------------------------------

try:
    from ... import native as _native
except Exception:  # pragma: no cover - native lib is optional
    _native = None


def reduce_inplace(
    fn: ReduceFunction, dst: np.ndarray, operand: np.ndarray
) -> None:
    """dst = dst (+|max) operand, elementwise, in place."""
    if _native is not None and _native.available() and _native.reduce_inplace(
        fn, dst, operand
    ):
        return
    if fn == ReduceFunction.SUM:
        np.add(dst, operand, out=dst)
    elif fn == ReduceFunction.MAX:
        np.maximum(dst, operand, out=dst)
    else:
        raise ValueError(f"unsupported reduce function {fn}")


#: DataType -> (native lane name, wire bit-pattern dtype)
_NATIVE_CAST_NAMES = {
    DataType.FLOAT16: ("float16", np.uint16),
    DataType.BFLOAT16: ("bfloat16", np.uint16),
    DataType.FLOAT8_E4M3: ("float8_e4m3", np.uint8),
    DataType.FLOAT8_E5M2: ("float8_e5m2", np.uint8),
}


def cast_array(arr: np.ndarray, dst_dt: DataType) -> np.ndarray:
    """Elementwise dtype cast (wire compression/decompression stage); the
    f32<->f16/bf16/fp8 pairs go through the native hp_compression-role
    lanes."""
    npdt = dtype_to_numpy(dst_dt)
    if arr.dtype == npdt:
        return arr
    if _native is not None and _native.available() and arr.flags.c_contiguous:
        lane = _NATIVE_CAST_NAMES.get(dst_dt)
        if lane is not None and arr.dtype == np.float32:
            return _native.cast_f32(arr, lane[0]).view(npdt)
        from ...constants import numpy_to_dtype

        try:
            src_dt = numpy_to_dtype(arr.dtype)
        except ValueError:
            src_dt = None
        if dst_dt == DataType.FLOAT32 and src_dt in _NATIVE_CAST_NAMES:
            wire, bits = _NATIVE_CAST_NAMES[src_dt]
            return _native.uncast_f32(arr.view(bits), wire)
    return arr.astype(npdt)


def cast_bytes(raw: bytes, src_dt: DataType, dst_dt: DataType) -> bytes:
    """Decode raw element bytes in src_dt, re-encode in dst_dt."""
    if src_dt == dst_dt:
        return raw
    arr = np.frombuffer(raw, dtype=dtype_to_numpy(src_dt))
    return cast_array(arr, dst_dt).tobytes()


# ---------------------------------------------------------------------------
# Device stream ports
# ---------------------------------------------------------------------------


class StreamPorts:
    """Named FIFO ports standing in for the CCLO's external-kernel AXIS
    streams.  ``stream_put`` payloads arriving with MsgType.STREAM bypass the
    RX buffer pool and land here; local device "kernels" push operand data the
    engine pulls when OP0_STREAM is set."""

    def __init__(self):
        self._ports: Dict[int, "queue.Queue[bytes]"] = {}
        self._lock = threading.Lock()

    def _port(self, stream_id: int) -> "queue.Queue[bytes]":
        with self._lock:
            if stream_id not in self._ports:
                self._ports[stream_id] = queue.Queue()
            return self._ports[stream_id]

    def push(self, stream_id: int, data: bytes) -> None:
        self._port(stream_id).put(data)

    def pop(self, stream_id: int, timeout: Optional[float] = None) -> bytes:
        return self._port(stream_id).get(timeout=timeout)

    def try_pop(self, stream_id: int) -> Optional[bytes]:
        try:
            return self._port(stream_id).get_nowait()
        except queue.Empty:
            return None
