"""Collective algorithms for the emulated engine.

This module is the TPU-build counterpart of the reference's control-plane
firmware (``kernels/cclo/fw/sw_apps/ccl_offload_control/src/
ccl_offload_control.c``) — every algorithm here names the firmware routine it
re-implements.  Algorithms are Python generators: they ``yield`` wait
conditions (see ``engine.py``) instead of recirculating through a retry queue,
and return an ``ErrorCode``.

Protocol selection matches the firmware rule (``send`` c:587, ``recv`` c:667,
``broadcast`` c:808): rendezvous iff the transfer is larger than the eager
threshold AND uses no compression AND no streams; otherwise eager (segmented,
tag/seqn-matched through the RX buffer pool).
"""

from __future__ import annotations

import dataclasses
from typing import Generator, List, Optional

import numpy as np

from ...communicator import Communicator
from ...constants import (
    CompressionFlags,
    DataType,
    ErrorCode,
    Operation,
    ReduceFunction,
    StreamFlags,
    dtype_to_numpy,
)
from ... import wire as wirecodec
from ..base import CallOptions
from .dataplane import cast_array, cast_bytes, reduce_inplace
from .fabric import Message, MsgType
from .engine_conditions import (
    SeekRx,
    WaitRndzvDone,
    WaitRndzvInit,
    WaitStream,
    Yield,
)

# NOTE on imports: engine.py imports this module; the wait-condition classes
# live in engine_conditions.py to avoid a cycle.


# ---------------------------------------------------------------------------
# dtype / view helpers
# ---------------------------------------------------------------------------


def _wire_dtype(call: CallOptions) -> DataType:
    cfg = call.arithcfg
    if cfg is None:
        return DataType.FLOAT32
    if call.compression & CompressionFlags.ETH_COMPRESSED:
        return cfg.compressed
    return cfg.uncompressed


def _wire_seed(call: CallOptions) -> int:
    """This rank's SR seed for the call's wire lane (0 = deterministic
    — every uncompressed call and the f16/bf16 lanes): the ONE shared
    derivation rule (wire.options_rank_seed), mirroring the sequencer
    decode loop's on-device rank mixing."""
    return wirecodec.options_rank_seed(call)


def _encode_chunk(call: CallOptions, data: np.ndarray) -> bytes:
    """One logical chunk's wire bytes: the shared quantized-wire codec
    for the scaled (int8) and stochastic lanes, the classic cast lane
    (native hp_compression acceleration included) otherwise — both
    produce ``wire_nbytes`` bytes the receive side sizes with."""
    wire_dt = _wire_dtype(call)
    seed = _wire_seed(call)
    if wirecodec.is_scaled(wire_dt) or seed:
        return wirecodec.encode_bytes(data, wire_dt, seed)
    return cast_array(np.asarray(data), wire_dt).tobytes()


def _decode_chunk(call: CallOptions, raw: bytes, n: int, out_dt: DataType):
    """Inverse of :func:`_encode_chunk` for ``n`` elements (seed-free:
    SR is an encode-side property)."""
    wire_dt = _wire_dtype(call)
    if wirecodec.is_scaled(wire_dt):
        return wirecodec.decode_bytes(
            raw, wire_dt, n, dtype_to_numpy(out_dt)
        )
    arr = np.frombuffer(raw, dtype=dtype_to_numpy(wire_dt))[:n]
    return cast_array(arr, out_dt)


def _wire_chunk_nbytes(call: CallOptions, n: int) -> int:
    """Wire bytes a chunk of ``n`` elements occupies — the codec's ONE
    sizing rule (scale sidecars included), shared with the send side."""
    return wirecodec.wire_nbytes(n, _wire_dtype(call))


def _acc_dtype(call: CallOptions) -> DataType:
    """Accumulation dtype for reductions: always the uncompressed dtype."""
    return call.arithcfg.uncompressed if call.arithcfg else DataType.FLOAT32


def _op0_view(call: CallOptions, count: Optional[int] = None) -> np.ndarray:
    n = call.count if count is None else count
    return call.op0.device_view()[:n]


def _op1_view(call: CallOptions, count: Optional[int] = None) -> np.ndarray:
    n = call.count if count is None else count
    return call.op1.device_view()[:n]


def _res_view(call: CallOptions, count: Optional[int] = None) -> np.ndarray:
    n = call.count if count is None else count
    return call.res.device_view()[:n]


def _seg_size(comm: Communicator, rank: int) -> int:
    return comm.ranks[rank].max_segment_size


def _tun(eng, call: CallOptions, name: str):
    """One tuning-register read, honoring the call's per-size-bucket
    TuningPlan overlay (CallOptions.tuning) over the engine's global
    table — per-size algorithm selection at dispatch."""
    if call.tuning is not None and name in call.tuning:
        return call.tuning[name]
    return eng.tuning[name]


def _use_rendezvous(eng, call: CallOptions, nbytes: int) -> bool:
    """Protocol verdict for one chunk (``nbytes`` = UNCOMPRESSED chunk
    size, the symmetric input both ends derive from their own call).
    The reference rule is rendezvous iff large AND uncompressed AND
    unstreamed (``send`` c:587); the quantized wire plane RELAXES the
    compression clause for the pure wire lane (ETH_COMPRESSED only):
    the one-sided write moves the ENCODED frame, so a large compressed
    transfer pays one quarter the bytes instead of falling back to the
    segmented eager path whose per-segment matching would bury the
    saving — the halve-the-wire-bytes lever, applied to the protocol
    tier too.  Operand/result-compression flags and streams keep the
    eager path (their lanes live in the rx/stream machinery)."""
    if nbytes <= call.eager_limit(eng.max_eager_size):
        return False
    if call.stream != StreamFlags.NO_STREAM:
        return False
    return (
        call.compression & ~CompressionFlags.ETH_COMPRESSED
    ) == CompressionFlags.NO_COMPRESSION


# ---------------------------------------------------------------------------
# Point-to-point primitives
# ---------------------------------------------------------------------------


def eager_send(
    eng, comm: Communicator, peer: int, tag: int, payload: bytes
) -> Generator:
    """Segmented eager send (ref firmware ``send`` eager path c:611-649:
    pipelined segment moves with per-segment sequence numbers)."""
    seg = _seg_size(comm, peer)
    off, total = 0, len(payload)
    first = True
    while first or off < total:
        first = False
        chunk = payload[off : off + seg]
        seqn = comm.next_outbound_seq(peer)
        eng.post_eager(
            comm,
            peer,
            Message(
                MsgType.EAGER,
                comm.id,
                comm.local_rank,
                peer,
                tag,
                seqn=seqn,
                count=len(chunk),
                payload=chunk,
                epoch=comm.epoch,
            ),
        )
        off += seg
        yield Yield()


@dataclasses.dataclass
class RecvHandle:
    protocol: str  # "eager" | "rndzv"
    peer: int
    tag: int
    nbytes: int  # wire bytes expected
    nseg: int = 0  # eager: number of segments to match
    vaddr: int = 0  # rndzv: registered write token
    raw: Optional[bytearray] = None
    # compressed rendezvous: the one-sided write lands the ENCODED wire
    # frame here; the waiter decodes into the real destination
    staging: Optional[np.ndarray] = None


def eager_recv_post(
    eng, comm: Communicator, peer: int, tag: int, wire_nbytes: int
) -> RecvHandle:
    """Plan a segmented eager receive.  Matching is strictly ordered per
    peer: each segment seeks the communicator's *current* inbound sequence
    number, which advances only on match (dma_mover.cpp:587-611)."""
    seg = _seg_size(comm, comm.local_rank)
    nseg = max(1, -(-wire_nbytes // seg))
    return RecvHandle("eager", peer, tag, wire_nbytes, nseg=nseg)


def eager_recv_wait(eng, comm: Communicator, handle: RecvHandle) -> Generator:
    """Complete a posted eager receive; returns the raw wire bytes."""
    out = bytearray()
    for _ in range(handle.nseg):
        buf = yield SeekRx(comm, handle.peer, handle.tag)
        out += buf.msg.payload
        eng.rx_pool.release(buf)
    handle.raw = out
    return bytes(out)


def rndzv_recv_post(
    eng, comm: Communicator, peer: int, tag: int, dst: np.ndarray
) -> RecvHandle:
    """Announce a writable address to the sender (ref ``recv`` rendezvous
    path: ``rendezvous_send_addr`` c:142-150 + RNDZVS_INIT on the wire)."""
    vaddr = eng.new_vaddr()
    mem = dst.view(np.uint8).data
    eng.endpoint.register_write_target(vaddr, mem)
    eng.post(
        comm,
        peer,
        Message(
            MsgType.RNDZV_INIT,
            comm.id,
            comm.local_rank,
            peer,
            tag,
            vaddr=vaddr,
            count=dst.nbytes,
        ),
    )
    return RecvHandle("rndzv", peer, tag, dst.nbytes, vaddr=vaddr)


def rndzv_recv_wait(eng, comm: Communicator, handle: RecvHandle) -> Generator:
    """Wait for the one-sided write completion (ref ``get_completion``
    c:280-339)."""
    yield WaitRndzvDone(
        comm.id, handle.peer, handle.tag, handle.vaddr,
        peer_addr=comm.ranks[handle.peer].address,
    )
    return None


def rndzv_send(
    eng, comm: Communicator, peer: int, tag: int, payload: bytes
) -> Generator:
    """Wait for the peer's address announcement, then perform the one-sided
    write (ref ``send`` rendezvous path c:587-610: ``rendezvous_get_addr`` +
    RDMA WRITE via the packetizer)."""
    init = yield WaitRndzvInit(
        comm.id, peer, tag, peer_addr=comm.ranks[peer].address
    )
    eng.post(
        comm,
        peer,
        Message(
            MsgType.RNDZV_DATA,
            comm.id,
            comm.local_rank,
            peer,
            tag,
            vaddr=init.vaddr,
            count=len(payload),
            payload=payload,
        ),
    )
    return None


# -- protocol-agnostic chunk send/recv --------------------------------------


def send_chunk(
    eng,
    call: CallOptions,
    comm: Communicator,
    peer: int,
    tag: int,
    data: np.ndarray,
) -> Generator:
    """Send one logical chunk, choosing eager/rendezvous like the firmware."""
    if _use_rendezvous(eng, call, data.nbytes):
        if call.compression & CompressionFlags.ETH_COMPRESSED:
            # compressed rendezvous: the one-sided write moves the
            # encoded frame (the receiver registered a staging region
            # of exactly wire_nbytes — see recv_chunk_post)
            yield from rndzv_send(
                eng, comm, peer, tag, _encode_chunk(call, data)
            )
        else:
            yield from rndzv_send(eng, comm, peer, tag, data.tobytes())
    else:
        yield from eager_send(
            eng, comm, peer, tag, _encode_chunk(call, data)
        )
    return None


def recv_chunk_post(
    eng,
    call: CallOptions,
    comm: Communicator,
    peer: int,
    tag: int,
    dst: np.ndarray,
) -> RecvHandle:
    if _use_rendezvous(eng, call, dst.nbytes):
        if call.compression & CompressionFlags.ETH_COMPRESSED:
            staging = np.empty(
                _wire_chunk_nbytes(call, dst.size), np.uint8
            )
            handle = rndzv_recv_post(eng, comm, peer, tag, staging)
            handle.staging = staging
            return handle
        return rndzv_recv_post(eng, comm, peer, tag, dst)
    return eager_recv_post(
        eng, comm, peer, tag, _wire_chunk_nbytes(call, dst.size)
    )


def recv_chunk_wait(
    eng,
    call: CallOptions,
    comm: Communicator,
    handle: RecvHandle,
    dst: np.ndarray,
) -> Generator:
    if handle.protocol == "rndzv":
        yield from rndzv_recv_wait(eng, comm, handle)
        if handle.staging is not None:
            np.copyto(
                dst,
                _decode_chunk(
                    call, handle.staging.tobytes(), dst.size,
                    call_res_dtype_of(dst),
                ),
            )
    else:
        raw = yield from eager_recv_wait(eng, comm, handle)
        np.copyto(
            dst,
            _decode_chunk(call, raw, dst.size, call_res_dtype_of(dst)),
        )
    return None


def call_res_dtype_of(dst: np.ndarray) -> DataType:
    from ...constants import numpy_to_dtype

    return numpy_to_dtype(dst.dtype)


def recv_chunk(
    eng,
    call: CallOptions,
    comm: Communicator,
    peer: int,
    tag: int,
    dst: np.ndarray,
) -> Generator:
    handle = recv_chunk_post(eng, call, comm, peer, tag, dst)
    yield from recv_chunk_wait(eng, call, comm, handle, dst)
    return None


def recv_reduce_chunk(
    eng,
    call: CallOptions,
    comm: Communicator,
    peer: int,
    tag: int,
    acc: np.ndarray,
) -> Generator:
    """Receive a chunk and reduce it into ``acc`` (ref ``fused_recv_reduce``
    c:716-749).  Rendezvous lands in a spare buffer first (ref TMP1-3)."""
    if _use_rendezvous(eng, call, acc.nbytes):
        # recv_chunk_post/_wait own the protocol plumbing (incl. the
        # compressed-rendezvous staging + frame decode): land in a
        # spare, then fold — ONE copy of the frame logic
        tmp = np.empty_like(acc)
        handle = recv_chunk_post(eng, call, comm, peer, tag, tmp)
        yield from recv_chunk_wait(eng, call, comm, handle, tmp)
        reduce_inplace(call.reduce_function, acc, tmp)
    else:
        handle = eager_recv_post(
            eng, comm, peer, tag, _wire_chunk_nbytes(call, acc.size)
        )
        raw = yield from eager_recv_wait(eng, comm, handle)
        reduce_inplace(
            call.reduce_function, acc,
            np.asarray(_decode_chunk(
                call, raw, acc.size, call_res_dtype_of(acc)
            )),
        )
    return None


# ---------------------------------------------------------------------------
# Operations
# ---------------------------------------------------------------------------


def op_nop(eng, call: CallOptions) -> Generator:
    yield Yield()
    return ErrorCode.OK


def op_config(eng, call: CallOptions) -> Generator:
    yield Yield()
    return eng.apply_config(call)


def _read_op0(eng, call: CallOptions) -> Generator:
    """Operand 0 as a device array — from buffer or local stream port
    (OP0_STREAM, the streaming-operand feature of ref ``accl_hls.h``)."""
    if call.stream & StreamFlags.OP0_STREAM:
        src_dt = (
            call.arithcfg.compressed
            if call.compression & CompressionFlags.OP0_COMPRESSED
            else call.arithcfg.uncompressed
        )
        nbytes = call.count * dtype_to_numpy(src_dt).itemsize
        raw = yield WaitStream(call.stream_id, nbytes)
        return np.frombuffer(raw, dtype=dtype_to_numpy(src_dt))[: call.count]
    return _op0_view(call)


def _write_res(eng, call: CallOptions, data: np.ndarray) -> None:
    """Result to buffer or local stream port (RES_STREAM)."""
    if call.stream & StreamFlags.RES_STREAM:
        res_dt = (
            call.arithcfg.compressed
            if call.compression & CompressionFlags.RES_COMPRESSED
            else call.arithcfg.uncompressed
        )
        eng.streams.push(call.stream_id, cast_array(data, res_dt).tobytes())
    else:
        dst = _res_view(call)
        np.copyto(dst, cast_array(data, call_res_dtype_of(dst)))


def op_copy(eng, call: CallOptions) -> Generator:
    """ref firmware ``copy`` c:531-547."""
    data = yield from _read_op0(eng, call)
    _write_res(eng, call, data)
    return ErrorCode.OK


def op_combine(eng, call: CallOptions) -> Generator:
    """ref firmware ``combine`` c:551-569: res = fn(op0, op1)."""
    if not call.arithcfg.supports(call.reduce_function):
        return ErrorCode.ARITH_ERROR
    a = yield from _read_op0(eng, call)
    b = _op1_view(call)
    acc_dt = _acc_dtype(call)
    acc = cast_array(a, acc_dt).copy()
    reduce_inplace(call.reduce_function, acc, cast_array(b, acc_dt))
    _write_res(eng, call, acc)
    return ErrorCode.OK


def op_send(eng, call: CallOptions) -> Generator:
    """ref firmware ``send`` c:573-649.  With RES_STREAM set this is
    ``stream_put``: the payload is routed to the remote stream port
    identified by ``stream_id`` instead of tag-matched RX buffers."""
    comm, peer = call.comm, call.root_dst
    data = yield from _read_op0(eng, call)
    if call.stream & StreamFlags.RES_STREAM:
        wire_dt = _wire_dtype(call)
        payload = cast_array(data, wire_dt).tobytes()
        seg = _seg_size(comm, peer)
        for off in range(0, max(1, len(payload)), seg):
            eng.post(
                comm,
                peer,
                Message(
                    MsgType.STREAM,
                    comm.id,
                    comm.local_rank,
                    peer,
                    call.tag,
                    strm=call.stream_id,
                    count=len(payload[off : off + seg]),
                    payload=payload[off : off + seg],
                ),
            )
            yield Yield()
        return ErrorCode.OK
    yield from send_chunk(eng, call, comm, peer, call.tag, np.asarray(data))
    return ErrorCode.OK


def op_recv(eng, call: CallOptions) -> Generator:
    """ref firmware ``recv`` c:653-710."""
    comm, peer = call.comm, call.root_src
    if call.stream & StreamFlags.RES_STREAM:
        # recv-to-stream: eager only; forward matched payloads to the port
        handle = eager_recv_post(
            eng,
            comm,
            peer,
            call.tag,
            call.count * dtype_to_numpy(_wire_dtype(call)).itemsize,
        )
        raw = yield from eager_recv_wait(eng, comm, handle)
        eng.streams.push(call.stream_id, raw)
        return ErrorCode.OK
    dst = _res_view(call)
    yield from recv_chunk(eng, call, comm, peer, call.tag, dst)
    return ErrorCode.OK


# -- collectives ------------------------------------------------------------


def op_bcast(eng, call: CallOptions) -> Generator:
    """ref firmware ``broadcast`` c:796-988: binomial-tree doubling for large
    rendezvous worlds (c:815-867), flat root-fanout otherwise (c:869-987)."""
    comm, root = call.comm, call.root_src
    r, size = comm.local_rank, comm.size
    if size == 1:
        yield Yield()
        return ErrorCode.OK
    data_nbytes = call.count * dtype_to_numpy(_acc_dtype(call)).itemsize
    use_tree = (
        _use_rendezvous(eng, call, data_nbytes)
        and size > _tun(eng, call, "bcast_flat_tree_max_ranks")
    )
    if not use_tree:
        if r == root:
            data = _op0_view(call)
            for peer in range(size):
                if peer != root:
                    yield from send_chunk(eng, call, comm, peer, call.tag, data)
        else:
            dst = _res_view(call)
            yield from recv_chunk(eng, call, comm, root, call.tag, dst)
        return ErrorCode.OK
    # binomial tree on root-relative ranks: node rel receives from its parent
    # (rel with its highest bit cleared), then forwards to rel + 2^k for
    # k = bit_length(rel).. while in range — the doubling scheme of c:815-867.
    rel = (r - root) % size
    buf = _op0_view(call) if r == root else _res_view(call)
    if rel != 0:
        parent_rel = rel - (1 << (rel.bit_length() - 1))
        parent = (parent_rel + root) % size
        yield from recv_chunk(eng, call, comm, parent, call.tag, buf)
        k = rel.bit_length()
    else:
        k = 0
    while rel + (1 << k) < size:
        child = ((rel + (1 << k)) + root) % size
        yield from send_chunk(eng, call, comm, child, call.tag, buf)
        k += 1
    return ErrorCode.OK


def op_scatter(eng, call: CallOptions) -> Generator:
    """ref firmware ``scatter`` c:992-1123: root fans out per-rank chunks
    (MOVE_INCREMENT), non-roots receive one chunk."""
    comm, root = call.comm, call.root_src
    r, size, count = comm.local_rank, comm.size, call.count
    if r == root:
        src = _op0_view(call, size * count)
        for peer in range(size):
            chunk = src[peer * count : (peer + 1) * count]
            if peer == root:
                dst = _res_view(call)
                np.copyto(dst, cast_array(chunk, call_res_dtype_of(dst)))
                yield Yield()
            else:
                yield from send_chunk(eng, call, comm, peer, call.tag, chunk)
    else:
        dst = _res_view(call)
        yield from recv_chunk(eng, call, comm, root, call.tag, dst)
    return ErrorCode.OK


def op_gather(eng, call: CallOptions) -> Generator:
    """ref firmware ``gather`` c:1128-1294.  Eager tier: ring relay toward
    the root (non-root sends its own block then relays everything arriving
    from the next rank, c:1205-1293).  Rendezvous tier: flat fan-in with the
    tuned window (c:1142-1204)."""
    comm, root = call.comm, call.root_src
    r, size, count = comm.local_rank, comm.size, call.count
    if size == 1:
        dst = _res_view(call)
        np.copyto(dst, cast_array(_op0_view(call), call_res_dtype_of(dst)))
        yield Yield()
        return ErrorCode.OK
    data_nbytes = count * dtype_to_numpy(_acc_dtype(call)).itemsize
    if _use_rendezvous(eng, call, data_nbytes):
        if r == root:
            dst_all = _res_view(call, size * count)
            np.copyto(
                dst_all[root * count : (root + 1) * count], _op0_view(call)
            )
            window = (
                _tun(eng, call, "gather_flat_tree_max_fanin")
                if data_nbytes > _tun(eng, call, "gather_flat_tree_max_count")
                else size
            )
            peers = [p for p in range(size) if p != root]
            for i in range(0, len(peers), window):
                # recv_chunk_post/_wait own the protocol plumbing
                # (incl. the compressed-rendezvous staging + frame
                # decode — a raw receive would skip the wire lane)
                batch = [
                    (p, dst_all[p * count : (p + 1) * count])
                    for p in peers[i : i + window]
                ]
                handles = [
                    (recv_chunk_post(eng, call, comm, p, call.tag, dst),
                     dst)
                    for p, dst in batch
                ]
                for h, dst in handles:
                    yield from recv_chunk_wait(eng, call, comm, h, dst)
        else:
            yield from send_chunk(
                eng, call, comm, root, call.tag, _op0_view(call)
            )
        return ErrorCode.OK
    # eager ring relay toward root
    rel = (r - root) % size
    if rel == 0:
        dst_all = _res_view(call, size * count)
        np.copyto(dst_all[root * count : (root + 1) * count], _op0_view(call))
        src_peer = (root + 1) % size
        for i in range(size - 1):
            origin = (root + 1 + i) % size
            dst = dst_all[origin * count : (origin + 1) * count]
            yield from recv_chunk(eng, call, comm, src_peer, call.tag, dst)
    else:
        fwd_peer = (r - 1) % size  # one hop closer to root
        yield from send_chunk(
            eng, call, comm, fwd_peer, call.tag, _op0_view(call)
        )
        relay_dt = _acc_dtype(call)
        tmp = np.empty(count, dtype_to_numpy(relay_dt))
        for _ in range(size - 1 - rel):
            yield from recv_chunk(eng, call, comm, (r + 1) % size, call.tag, tmp)
            yield from send_chunk(eng, call, comm, fwd_peer, call.tag, tmp)
    return ErrorCode.OK


def op_allgather(eng, call: CallOptions) -> Generator:
    """ref firmware ``allgather`` c:1297-1503: ring store-and-relay with
    strided placement (eager c:1402-1500; rendezvous ring c:1314-1401)."""
    comm = call.comm
    r, size, count = comm.local_rank, comm.size, call.count
    dst_all = _res_view(call, size * count)
    own = dst_all[r * count : (r + 1) * count]
    np.copyto(own, cast_array(_op0_view(call), call_res_dtype_of(dst_all)))
    if size == 1:
        yield Yield()
        return ErrorCode.OK
    nxt, prv = comm.next_rank(), comm.prev_rank()
    for step in range(size - 1):
        send_origin = (r - step) % size
        recv_origin = (r - step - 1) % size
        recv_dst = dst_all[recv_origin * count : (recv_origin + 1) * count]
        handle = recv_chunk_post(eng, call, comm, prv, call.tag, recv_dst)
        yield from send_chunk(
            eng,
            call,
            comm,
            nxt,
            call.tag,
            dst_all[send_origin * count : (send_origin + 1) * count],
        )
        yield from recv_chunk_wait(eng, call, comm, handle, recv_dst)
    return ErrorCode.OK


def op_reduce(eng, call: CallOptions) -> Generator:
    """ref firmware ``reduce`` c:1507-1744: size-1 shortcut (c:1520);
    flat-tree accumulate for small comms/messages (c:1531-1602); binomial
    tree for large rendezvous transfers (c:1603-1728); eager ring pipeline of
    fused recv-reduce-send otherwise (c:1730-1743)."""
    comm, root = call.comm, call.root_dst
    r, size, count = comm.local_rank, comm.size, call.count
    if not call.arithcfg.supports(call.reduce_function):
        return ErrorCode.ARITH_ERROR
    acc_dt = _acc_dtype(call)
    npdt = dtype_to_numpy(acc_dt)
    # operand via the stream-capable reader: reduce accepts a streaming
    # operand like the reference's stream reduce overloads (accl.hpp:514-590)
    op0 = yield from _read_op0(eng, call)
    if size == 1:
        _write_res(eng, call, op0)
        return ErrorCode.OK
    data_nbytes = count * npdt.itemsize
    rndzv = _use_rendezvous(eng, call, data_nbytes)
    flat = size <= _tun(eng, call, "reduce_flat_tree_max_ranks") or (
        data_nbytes <= _tun(eng, call, "reduce_flat_tree_max_count")
    )
    if rndzv and flat:
        # flat tree: root accumulates everyone into spares
        if r == root:
            acc = cast_array(op0, acc_dt).copy()
            for peer in range(size):
                if peer != root:
                    yield from recv_reduce_chunk(
                        eng, call, comm, peer, call.tag, acc
                    )
            _write_res(eng, call, acc)
        else:
            yield from send_chunk(eng, call, comm, root, call.tag, op0)
        return ErrorCode.OK
    if rndzv:
        # binomial reduction tree on root-relative ranks (c:1603-1728)
        rel = (r - root) % size
        acc = cast_array(op0, acc_dt).copy()
        k = 0
        while (1 << k) < size:
            if rel & (1 << k):
                parent = ((rel - (1 << k)) + root) % size
                yield from send_chunk(eng, call, comm, parent, call.tag, acc)
                break
            child_rel = rel + (1 << k)
            if child_rel < size:
                child = (child_rel + root) % size
                yield from recv_reduce_chunk(eng, call, comm, child, call.tag, acc)
            k += 1
        if rel == 0:
            _write_res(eng, call, acc)
        return ErrorCode.OK
    # eager ring pipeline: partials flow from the farthest rank toward root,
    # fused recv-reduce-send at every hop (c:1730-1743)
    rel = (r - root) % size
    acc = cast_array(op0, acc_dt).copy()
    if rel == size - 1:
        yield from send_chunk(
            eng, call, comm, (r - 1) % size, call.tag, acc
        )
    else:
        yield from recv_reduce_chunk(eng, call, comm, (r + 1) % size, call.tag, acc)
        if rel != 0:
            yield from send_chunk(eng, call, comm, (r - 1) % size, call.tag, acc)
    if rel == 0:
        _write_res(eng, call, acc)
    return ErrorCode.OK


def _block_bounds(total: int, parts: int) -> List[tuple]:
    """Split ``total`` elements into ``parts`` contiguous blocks with the
    tail spread over the leading blocks (ref allreduce tail handling
    c:1900-1912)."""
    base, tail = divmod(total, parts)
    bounds = []
    off = 0
    for i in range(parts):
        n = base + (1 if i < tail else 0)
        bounds.append((off, off + n))
        off += n
    return bounds


def op_reduce_scatter(eng, call: CallOptions) -> Generator:
    """ref firmware ``reduce_scatter`` c:1748-1852: eager ring with strided
    reads + fused recv-reduce (c:1782-1851); rendezvous composes reduce then
    scatter (c:1768-1781)."""
    comm = call.comm
    r, size, count = comm.local_rank, comm.size, call.count
    if not call.arithcfg.supports(call.reduce_function):
        return ErrorCode.ARITH_ERROR
    acc_dt = _acc_dtype(call)
    npdt = dtype_to_numpy(acc_dt)
    if size == 1:
        dst = _res_view(call)
        np.copyto(dst, cast_array(_op0_view(call), call_res_dtype_of(dst)))
        yield Yield()
        return ErrorCode.OK
    acc = cast_array(_op0_view(call, size * count), acc_dt).copy()
    nxt, prv = comm.next_rank(), comm.prev_rank()
    for s in range(1, size):
        send_c = (r - s) % size
        recv_c = (r - 1 - s) % size
        send_blk = acc[send_c * count : (send_c + 1) * count]
        recv_blk = acc[recv_c * count : (recv_c + 1) * count]
        if _use_rendezvous(eng, call, count * npdt.itemsize):
            # recv_chunk_post/_wait own the protocol plumbing (incl.
            # the compressed-rendezvous staging + frame decode —
            # receiving the peer's ENCODED frame into a raw npdt tmp
            # would fold reinterpreted wire bytes into the accumulator)
            tmp = np.empty(count, npdt)
            handle = recv_chunk_post(eng, call, comm, prv, call.tag, tmp)
            yield from send_chunk(eng, call, comm, nxt, call.tag, send_blk)
            yield from recv_chunk_wait(eng, call, comm, handle, tmp)
            reduce_inplace(call.reduce_function, recv_blk, tmp)
        else:
            yield from send_chunk(eng, call, comm, nxt, call.tag, send_blk)
            yield from recv_reduce_chunk(eng, call, comm, prv, call.tag, recv_blk)
    _write_res(eng, call, acc[r * count : (r + 1) * count])
    return ErrorCode.OK


def op_allreduce(eng, call: CallOptions) -> Generator:
    """ref firmware ``allreduce`` c:1855-2075.  Eager tier: segmented ring
    reduce-scatter followed by ring allgather over ``size`` blocks with tail
    handling (c:1888-2071).  Rendezvous tier: reduce to rank 0 + broadcast
    (c:1878-1887)."""
    comm = call.comm
    r, size, count = comm.local_rank, comm.size, call.count
    if not call.arithcfg.supports(call.reduce_function):
        return ErrorCode.ARITH_ERROR
    acc_dt = _acc_dtype(call)
    npdt = dtype_to_numpy(acc_dt)
    if size == 1:
        dst = _res_view(call)
        np.copyto(dst, cast_array(_op0_view(call), call_res_dtype_of(dst)))
        yield Yield()
        return ErrorCode.OK
    acc = cast_array(_op0_view(call), acc_dt).copy()
    bounds = _block_bounds(count, size)
    nxt, prv = comm.next_rank(), comm.prev_rank()

    def blk(i):
        lo, hi = bounds[i % size]
        return acc[lo:hi]

    # phase 1: ring reduce-scatter over blocks
    for s in range(1, size):
        send_b, recv_b = blk(r - s), blk(r - 1 - s)
        tmp = np.empty(recv_b.size, npdt)
        handle = recv_chunk_post(eng, call, comm, prv, call.tag, tmp)
        yield from send_chunk(eng, call, comm, nxt, call.tag, send_b)
        yield from recv_chunk_wait(eng, call, comm, handle, tmp)
        reduce_inplace(call.reduce_function, recv_b, tmp)
    # phase 2: ring allgather over blocks (rank r now owns reduced block r)
    for s in range(size - 1):
        send_b, recv_b = blk(r - s), blk(r - 1 - s)
        handle = recv_chunk_post(eng, call, comm, prv, call.tag, recv_b)
        yield from send_chunk(eng, call, comm, nxt, call.tag, send_b)
        yield from recv_chunk_wait(eng, call, comm, handle, recv_b)
    _write_res(eng, call, acc)
    return ErrorCode.OK


def op_barrier(eng, call: CallOptions) -> Generator:
    """ref firmware ``barrier`` c:2078-2120: zero-byte gather to a root
    then zero-byte broadcast back.  The root rides ``call.root_src``
    (default 0) — the membership plane re-routes it around demoted
    stragglers, SPMD-uniformly (every rank is handed the same root by
    the shared demotion ledger; the contract verifier folds it into the
    call fingerprint like any other root)."""
    comm = call.comm
    r, size = comm.local_rank, comm.size
    if size == 1:
        yield Yield()
        return ErrorCode.OK
    tag = call.tag
    root = call.root_src if 0 <= call.root_src < size else 0
    if r == root:
        for peer in range(size):
            if peer == root:
                continue
            h = eager_recv_post(eng, comm, peer, tag, 0)
            yield from eager_recv_wait(eng, comm, h)
        for peer in range(size):
            if peer == root:
                continue
            yield from eager_send(eng, comm, peer, tag, b"")
    else:
        yield from eager_send(eng, comm, root, tag, b"")
        h = eager_recv_post(eng, comm, root, tag, 0)
        yield from eager_recv_wait(eng, comm, h)
    return ErrorCode.OK


def op_alltoall(eng, call: CallOptions) -> Generator:
    """ref firmware ``all_to_all`` c:2123-2218: local copy + serve all peers,
    completions taken out of order."""
    comm = call.comm
    r, size, count = comm.local_rank, comm.size, call.count
    src_all = _op0_view(call, size * count)
    dst_all = _res_view(call, size * count)
    np.copyto(
        dst_all[r * count : (r + 1) * count],
        cast_array(src_all[r * count : (r + 1) * count], call_res_dtype_of(dst_all)),
    )
    if size == 1:
        yield Yield()
        return ErrorCode.OK
    # post all receive addresses first (out-of-order service), then send
    handles = {}
    for peer in range(size):
        if peer != r:
            dst = dst_all[peer * count : (peer + 1) * count]
            handles[peer] = recv_chunk_post(eng, call, comm, peer, call.tag, dst)
    for off in range(1, size):
        peer = (r + off) % size
        yield from send_chunk(
            eng,
            call,
            comm,
            peer,
            call.tag,
            src_all[peer * count : (peer + 1) * count],
        )
    for peer, handle in handles.items():
        dst = dst_all[peer * count : (peer + 1) * count]
        yield from recv_chunk_wait(eng, call, comm, handle, dst)
    return ErrorCode.OK


_DISPATCH = {
    Operation.NOP: op_nop,
    Operation.CONFIG: op_config,
    Operation.COPY: op_copy,
    Operation.COMBINE: op_combine,
    Operation.SEND: op_send,
    Operation.RECV: op_recv,
    Operation.BCAST: op_bcast,
    Operation.SCATTER: op_scatter,
    Operation.GATHER: op_gather,
    Operation.ALLGATHER: op_allgather,
    Operation.REDUCE: op_reduce,
    Operation.ALLREDUCE: op_allreduce,
    Operation.REDUCE_SCATTER: op_reduce_scatter,
    Operation.ALLTOALL: op_alltoall,
    Operation.BARRIER: op_barrier,
}


def dispatch(engine, options: CallOptions) -> Generator:
    fn = _DISPATCH.get(options.op)
    if fn is None:

        def _unimpl():
            yield Yield()
            return ErrorCode.COLLECTIVE_NOT_IMPLEMENTED

        return _unimpl()
    return fn(engine, options)
