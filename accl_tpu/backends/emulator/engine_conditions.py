"""Wait conditions yielded by algorithm generators to the engine scheduler.

These are the cooperative-scheduling analog of the reference firmware's
``NOT_READY_ERROR`` retry mechanism (``ccl_offload_control.c:2460-2478``): a
parked call re-polls its condition each scheduler round instead of being
recirculated through a hardware retry stream.
"""

from __future__ import annotations

from typing import Optional

from ...constants import ErrorCode
from .fabric import Message


class WaitCondition:
    """Polled by the scheduler; returns a value when satisfied, None if not.

    ``peer_addr`` (when set) names the peer the condition is waiting on —
    a deadline expiry then feeds that peer's entry in the engine's health
    map (timeout accounting for graceful degradation)."""

    timeout_code = ErrorCode.RECEIVE_TIMEOUT
    peer_addr: Optional[str] = None

    def poll(self, engine):
        raise NotImplementedError


class SeekRx(WaitCondition):
    """Match an eager segment {comm, src, tag, seqn} in the RX pool
    (ref rxbuf_seek + the DMP MOVE_ON_RECV seek loop, dma_mover.cpp:587-611).

    The expected sequence number is read from the communicator's inbound
    counter at poll time and advanced only on a successful match — exactly
    the reference semantics (seqn update at dma_mover.cpp:610), so a timed-
    out receive leaves per-peer matching state clean."""

    timeout_code = ErrorCode.RECEIVE_TIMEOUT

    def __init__(self, comm, src: int, tag: int):
        self.comm, self.src, self.tag = comm, src, tag
        self.peer_addr = comm.ranks[src].address

    def poll(self, engine):
        seqn = self.comm.peek_inbound_seq(self.src)
        buf = engine.rx_pool.seek(self.comm.id, self.src, self.tag, seqn)
        if buf is None:
            # pool fully parked with other signatures: emergency inbox
            # consume (head-of-line escape; see Engine.rx_seek_overflow)
            buf = engine.rx_seek_overflow(
                self.comm.id, self.src, self.tag, seqn
            )
        if buf is not None:
            self.comm.advance_inbound_seq(self.src)
        return buf


class WaitRndzvInit(WaitCondition):
    """Wait for a rendezvous address announcement from ``src`` (or any rank
    when src is None) — ref ``rendezvous_get_addr`` / ``get_any_addr``
    (ccl_offload_control.c:154-276)."""

    timeout_code = ErrorCode.RENDEZVOUS_TIMEOUT

    def __init__(self, comm_id: int, src: Optional[int], tag: int,
                 peer_addr: Optional[str] = None):
        self.comm_id, self.src, self.tag = comm_id, src, tag
        self.peer_addr = peer_addr

    def poll(self, engine):
        def pred(m: Message) -> bool:
            return (
                m.comm_id == self.comm_id
                and m.tag == self.tag
                and (self.src is None or m.src == self.src)
            )

        return engine.take_rndzv_init(pred)


class WaitRndzvDone(WaitCondition):
    """Wait for a write-completion notification — ref ``get_completion`` /
    ``get_any_completion`` (ccl_offload_control.c:280-408)."""

    timeout_code = ErrorCode.RENDEZVOUS_TIMEOUT

    def __init__(self, comm_id: int, src: Optional[int], tag: int, vaddr: int,
                 peer_addr: Optional[str] = None):
        self.comm_id, self.src, self.tag, self.vaddr = comm_id, src, tag, vaddr
        self.peer_addr = peer_addr

    def poll(self, engine):
        def pred(m: Message) -> bool:
            return (
                m.comm_id == self.comm_id
                and m.tag == self.tag
                and m.vaddr == self.vaddr
                and (self.src is None or m.src == self.src)
            )

        return engine.take_rndzv_done(pred)


class WaitStream(WaitCondition):
    """Accumulate ``nbytes`` from a local device stream port (OP0_STREAM)."""

    timeout_code = ErrorCode.DMA_TIMEOUT

    def __init__(self, stream_id: int, nbytes: int):
        self.stream_id, self.nbytes = stream_id, nbytes
        self._acc = b""

    def poll(self, engine):
        while len(self._acc) < self.nbytes:
            chunk = engine.streams.try_pop(self.stream_id)
            if chunk is None:
                return None
            self._acc += chunk
        return self._acc[: self.nbytes]


class Yield(WaitCondition):
    """Cooperative yield: always ready.  Lets long segmented loops interleave
    with other parked calls, like the firmware's bounded in-flight moves."""

    def poll(self, engine):
        return True
