from .engine import EmuEngine  # noqa: F401
from .fabric import InProcFabric  # noqa: F401
