"""Cross-version jax compatibility shims.

The codebase targets current jax — ``jax.shard_map`` with the
``check_vma`` switch, ``jax.lax.axis_size`` — but must keep running on
older installations (0.4.x) where those names either do not exist or
spell differently.  :func:`install` adds the missing public names once,
adapting drifted keyword arguments.

It is deliberately NOT invoked from ``accl_tpu/__init__``: importing the
package must stay jax-free (the emulator/native tiers run in processes
that never load jax — see ``ACCL.capabilities``'s platform note).
Instead, every module that binds the shimmed symbols calls ``install()``
right after its own ``import jax`` (and tests/conftest does the same
before test modules import), so each jax-binding call site resolves to
one consistent surface without the package import paying for it.

Shims are additive only: on a jax that already provides a name, install()
leaves it untouched.
"""

from __future__ import annotations

import inspect

_installed = False


def has_modern_vma() -> bool:
    """True when this jax provides the varying-manual-axes machinery
    (``lax.pvary``/``lax.pcast`` and the checked shard_map that places
    gradient psums from vma tracking).  Features whose CORRECTNESS
    depends on it — ZeRO's mixed replicated/sharded gradient placement,
    the composed pipeline's transpose bookkeeping — cannot be shimmed:
    on legacy jax the adapter runs shard_map unchecked, which silently
    misplaces those transposes.  Their test modules skip on this flag
    (a loud environment skip instead of minutes of wrong numerics)."""
    import jax

    return hasattr(jax.lax, "pvary") or hasattr(jax.lax, "pcast")


def has_pallas_interpret() -> bool:
    """True when jax ships the Pallas TPU interpreter
    (``pltpu.InterpretParams``) that lets the Mosaic kernels run
    off-chip.  Without it (legacy jax), the Pallas kernel suites and the
    ``pallas_ring`` tuning lowerings can only run on a real TPU — their
    tests skip on this flag off-chip instead of failing on the missing
    attribute."""
    try:
        import jax.experimental.pallas.tpu as pltpu
    except Exception:  # pragma: no cover - pallas absent entirely
        return False
    return hasattr(pltpu, "InterpretParams")


def install() -> None:
    global _installed
    if _installed:
        return
    _installed = True
    import jax
    from jax import lax

    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _legacy

        params = set(inspect.signature(_legacy).parameters)

        def shard_map(f=None, **kwargs):
            # Adapt modern kwargs onto the legacy signature.  check_vma
            # nominally maps onto the old replication checker's switch,
            # but that checker predates these programs and rejects valid
            # out_specs ("requires replication which can't be statically
            # inferred"), so on legacy jax it is disabled outright; the
            # modern varying-manual-axes checker runs wherever the real
            # jax.shard_map exists.
            kwargs.pop("check_vma", None)
            if "check_rep" in params:
                kwargs.setdefault("check_rep", False)
            if f is None:  # partial-application (decorator) form
                return lambda fn: _legacy(fn, **kwargs)
            return _legacy(f, **kwargs)

        jax.shard_map = shard_map

    if not hasattr(lax, "axis_size"):

        def axis_size(axis_name):
            # psum of the unit constant folds to the STATIC mapped-axis
            # size (a Python int at trace time) on every jax that lacks
            # lax.axis_size — callers can keep using it in shape math
            return lax.psum(1, axis_name)

        lax.axis_size = axis_size
