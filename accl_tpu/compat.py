"""Cross-version jax compatibility shims.

The codebase targets current jax — ``jax.shard_map`` with the
``check_vma`` switch, ``jax.lax.axis_size`` — but must keep running on
older installations (0.4.x) where those names either do not exist or
spell differently.  :func:`install` adds the missing public names once,
adapting drifted keyword arguments.

It is deliberately NOT invoked from ``accl_tpu/__init__``: importing the
package must stay jax-free (the emulator/native tiers run in processes
that never load jax — see ``ACCL.capabilities``'s platform note).
Instead, every module that binds the shimmed symbols calls ``install()``
right after its own ``import jax`` (and tests/conftest does the same
before test modules import), so each jax-binding call site resolves to
one consistent surface without the package import paying for it.

Shims are additive only: on a jax that already provides a name, install()
leaves it untouched.
"""

from __future__ import annotations

import inspect

_installed = False


def has_modern_vma() -> bool:
    """True when this jax provides the varying-manual-axes machinery
    (``lax.pvary``/``lax.pcast`` and the checked shard_map that places
    gradient psums from vma tracking).  Features whose CORRECTNESS
    depends on it — ZeRO's mixed replicated/sharded gradient placement,
    the composed pipeline's transpose bookkeeping — cannot be shimmed:
    on legacy jax the adapter runs shard_map unchecked, which silently
    misplaces those transposes.  Their test modules skip on this flag
    (a loud environment skip instead of minutes of wrong numerics)."""
    import jax

    return hasattr(jax.lax, "pvary") or hasattr(jax.lax, "pcast")


def has_profiler_options() -> bool:
    """True when this jax ships ``jax.profiler.ProfileOptions`` (the
    knob object ``utils.profiling.trace`` feeds ``start_trace``).
    Legacy jax (0.4.x) predates it — callers degrade to an optionless
    trace capture instead of raising AttributeError."""
    import jax

    return hasattr(jax.profiler, "ProfileOptions")


_fp8_cast_faithful: bool = None


def has_faithful_fp8_cast() -> bool:
    """True when XLA's f32 -> float8_e4m3fn cast rounds identically to
    ml_dtypes' numpy cast on this host.  Some jax/XLA versions round a
    small fraction of values to the other neighboring representable
    (observed: 1/512 on jaxlib 0.4.36 CPU), so a device-tier compressed
    transfer cannot be checked bit-exactly against the ml_dtypes
    reference — scenario suites gate their fp8 wire cases on this probe
    (a loud skip with a reason string, never a silent numeric fudge)."""
    global _fp8_cast_faithful
    if _fp8_cast_faithful is not None:
        return _fp8_cast_faithful
    import ml_dtypes
    import numpy as np

    import jax.numpy as jnp

    rng = np.random.default_rng(0xF8)
    x = (rng.standard_normal(4096) * 8.0).astype(np.float32)
    want = x.astype(ml_dtypes.float8_e4m3fn).view(np.uint8)
    got = np.asarray(
        jnp.asarray(x).astype(jnp.float8_e4m3fn)
    ).view(np.uint8)
    _fp8_cast_faithful = bool((want == got).all())
    return _fp8_cast_faithful


class KVNotFoundError(KeyError):
    """The legacy KV adapter's key-absent signal: renders with the same
    'no such key' vocabulary the dist engine's learned not-found
    signature expects, so polling loops treat it as 'nothing posted
    yet' and real transport failures keep raising loudly."""

    def __init__(self, key: str):
        super().__init__(f"NOT_FOUND: no such key: {key}")


class _LegacyKVAdapter:
    """jaxlib < 0.5 ``DistributedRuntimeClient`` adapter: provides the
    modern KV surface (``key_value_try_get_bytes`` /
    ``key_value_increment``) on top of the legacy one.

    * try-get rides ``key_value_dir_get_bytes`` over the key's directory
      (non-blocking, non-destructive) and raises :class:`KVNotFoundError`
      when absent — the modern method's contract.
    * increment is emulated with first-write-wins claim keys: the legacy
      ``key_value_set`` refuses to overwrite an existing key, so
      claiming ``<key>/<n>`` is atomic.  Within one process a local hint
      keeps the scan O(1); a cold start resumes past surviving claims
      (one directory list).  Claims older than a retained window are
      deleted so a long stream cannot grow the service unboundedly.
      Cross-process single-writer streams (the stream-port protocol's
      shape) stay correct, concurrent writers serialize on the claim.
    """

    def __init__(self, client):
        self._client = client
        self._hints = {}

    # passthroughs the dist engine also uses
    def key_value_set_bytes(self, key: str, value: bytes) -> None:
        self._client.key_value_set_bytes(key, value)

    def key_value_delete(self, key: str) -> None:
        self._client.key_value_delete(key)

    def key_value_try_get_bytes(self, key: str) -> bytes:
        # fast path: a ~zero-timeout blocking get probes ONE key per
        # poll (O(1)); the directory scan below transfers every pending
        # value per probe — quadratic traffic while a stream consumer
        # is behind — so it is only the fallback for clients without
        # the bytes getter
        getter = getattr(
            self._client, "blocking_key_value_get_bytes", None
        )
        if getter is not None:
            try:
                return getter(key, 1)  # timeout_in_ms
            except Exception as e:
                text = str(e).lower()
                if any(
                    sig in text
                    for sig in ("not found", "no such",
                                "does not exist", "not_found")
                ):
                    raise KVNotFoundError(key) from None
                if not any(
                    sig in text
                    for sig in ("deadline", "timeout", "timed out")
                ):
                    raise
                # a deadline on the ~zero-timeout probe is ambiguous: the
                # key may EXIST on a slow coordinator — fall through to
                # the directory scan, which distinguishes present from
                # absent (and keeps real transport failures loud)
        prefix = key.rsplit("/", 1)[0]
        try:
            entries = self._client.key_value_dir_get_bytes(prefix)
        except Exception as e:
            # directory absent renders as an error on some versions —
            # that (and only that) is 'nothing posted yet' for a poller;
            # transport/RPC failures must keep raising loudly
            text = str(e).lower()
            if any(
                sig in text
                for sig in ("not found", "no such", "does not exist",
                            "not_found")
            ):
                raise KVNotFoundError(key) from None
            raise
        for k, v in entries or ():
            # dir-get may return keys relative to the directory or fully
            # qualified, depending on the jaxlib vintage
            if k == key or key.endswith("/" + k) or k.endswith(key):
                return v
        raise KVNotFoundError(key)

    #: retained claim-key window: old claims beyond this are deleted so
    #: a long stream cannot grow the coordination service unboundedly
    _CLAIM_WINDOW = 64

    def key_value_increment(self, key: str, n: int = 1) -> int:
        if n != 1:  # the stream protocol only ever takes the next slot
            raise ValueError("legacy KV increment supports n=1 only")
        seq = self._hints.get(key, 0)
        if seq == 0:
            # cold start (fresh process): resume past any surviving
            # claims instead of linearly colliding up from 1 — also what
            # keeps the claim-window cleanup below restart-safe
            try:
                entries = self._client.key_value_dir_get_bytes(
                    f"{key}/claim"
                )
            except Exception:
                entries = ()
            for k, _ in entries or ():
                try:
                    seq = max(seq, int(str(k).rsplit("/", 1)[-1]))
                except ValueError:
                    pass
        while True:
            seq += 1
            try:
                self._client.key_value_set(f"{key}/claim/{seq}", "1")
            except Exception as e:
                if "exist" in str(e).lower():
                    continue  # another writer claimed it: take the next
                raise
            self._hints[key] = seq
            if seq > self._CLAIM_WINDOW:
                # bound the claim trail: drop the claim that just left
                # the retained window (best-effort; a failed delete only
                # leaves one extra key)
                try:
                    self._client.key_value_delete(
                        f"{key}/claim/{seq - self._CLAIM_WINDOW}"
                    )
                except Exception:
                    pass
            return seq


def kv_client(client):
    """The modern KV surface over whatever ``DistributedRuntimeClient``
    this jaxlib provides: the client itself when it already has try-get
    + increment, else a :class:`_LegacyKVAdapter` around it."""
    if hasattr(client, "key_value_try_get_bytes") and hasattr(
        client, "key_value_increment"
    ):
        return client
    return _LegacyKVAdapter(client)


_interpret_probe = None


def _probe_interpret_params():
    """(ok, reason) for the Pallas TPU interpreter on this host: the
    attribute must exist AND a trivial kernel must actually execute
    under it — some environments ship the name but fail at run time, so
    presence alone is not evidence."""
    try:
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu
    except Exception as e:  # pragma: no cover - pallas absent entirely
        return False, f"pallas unavailable: {type(e).__name__}: {e}"
    if not hasattr(pltpu, "InterpretParams"):
        return False, (
            f"jax {jax.__version__} has no pltpu.InterpretParams "
            "(TPU interpreter): Pallas kernels only run on real TPU here"
        )
    try:
        def k(x_ref, o_ref):
            o_ref[:] = x_ref[:]

        out = pl.pallas_call(
            k,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            interpret=pltpu.InterpretParams(),
        )(jnp.zeros((8, 128), jnp.float32))
        out.block_until_ready()
    except Exception as e:
        return False, (
            "pltpu.InterpretParams probe failed: "
            f"{type(e).__name__}: {e}"
        )
    return True, ""


def has_interpret_params() -> bool:
    """True when ``pltpu.InterpretParams`` exists and a trivial kernel
    RUNS under it (probed once, cached).  The Pallas interpret-mode test
    suites gate on this so the long-standing environment failures skip
    loudly with :func:`interpret_params_reason` instead of sitting in
    the failure set — the loud-skip convention ``has_faithful_fp8_cast``
    established."""
    global _interpret_probe
    if _interpret_probe is None:
        _interpret_probe = _probe_interpret_params()
    return _interpret_probe[0]


def interpret_params_reason() -> str:
    """Why :func:`has_interpret_params` is False ('' when it is True) —
    the skip reason string the gated suites surface."""
    global _interpret_probe
    if _interpret_probe is None:
        _interpret_probe = _probe_interpret_params()
    return _interpret_probe[1]


_replay_probe = None


def _probe_bitexact_replay():
    """(ok, reason) for bit-exact train-step replay on this host: run a
    TINY sharded trainer step twice from value-identical states — once
    chained on the donated step OUTPUT, once from a ``device_put`` clone
    (exactly what a checkpoint restore produces).  Some XLA builds
    execute a provenance-dependent program (donated/aliased inputs pick
    different in-place kernels with a different FP reduction order), so
    a resumed run cannot be bit-comparable to an uninterrupted one even
    though save/restore and the data stream are value-faithful.  Each
    path is individually repeatable — this is replay instability, not
    nondeterminism, which is why it must be PROBED, not assumed."""
    try:
        import numpy as np

        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh

        from .models import (
            TransformerConfig,
            init_params,
            make_sharded_train_step,
        )
    except Exception as e:  # pragma: no cover - broken env
        return False, f"replay probe unavailable: {type(e).__name__}: {e}"
    try:
        devs = jax.devices()
        tp = 2 if len(devs) >= 2 else 1
        dp = max(len(devs) // tp, 1)
        mesh = Mesh(np.array(devs[: dp * tp]).reshape(dp, tp), ("dp", "tp"))
        heads = max(2, tp)
        cfg = TransformerConfig(
            vocab=32, d_model=8 * heads, n_heads=heads, n_layers=1,
            d_ff=16 * heads, max_seq=8, dtype=jnp.float32,
        )
        step_fn, shard = make_sharded_train_step(cfg, mesh, lr=0.1)
        params = shard(init_params(jax.random.PRNGKey(0), cfg))
        rng = np.random.default_rng(7)
        toks = jnp.asarray(
            rng.integers(0, cfg.vocab, (2 * dp, cfg.max_seq)), jnp.int32
        )
        tgts = jnp.asarray(
            rng.integers(0, cfg.vocab, (2 * dp, cfg.max_seq)), jnp.int32
        )
        p1, _ = step_fn(params, toks, tgts)
        clone = jax.tree.map(
            lambda a: jax.device_put(np.asarray(a).copy(), a.sharding), p1
        )
        _, chained = step_fn(p1, toks, tgts)
        _, replayed = step_fn(clone, toks, tgts)
        chained, replayed = float(chained), float(replayed)
    except Exception as e:
        return False, f"replay probe failed: {type(e).__name__}: {e}"
    if chained != replayed:
        return False, (
            "XLA executes a provenance-dependent program: the same step "
            "on value-identical params gives "
            f"{chained!r} chained vs {replayed!r} from a device_put "
            "clone — checkpoint resume cannot be bit-exact on this "
            "platform (restore IS a device_put)"
        )
    return True, ""


def has_bitexact_replay() -> bool:
    """True when a donated train-step output and a value-identical
    ``device_put`` clone replay to bit-identical results (probed once,
    cached).  Checkpoint-resume bit-exactness tests gate on this and
    skip LOUDLY with :func:`bitexact_replay_reason` where the platform
    cannot deliver it — the loud-skip convention of
    ``has_interpret_params``."""
    global _replay_probe
    if _replay_probe is None:
        _replay_probe = _probe_bitexact_replay()
    return _replay_probe[0]


def bitexact_replay_reason() -> str:
    """Why :func:`has_bitexact_replay` is False ('' when it is True)."""
    global _replay_probe
    if _replay_probe is None:
        _replay_probe = _probe_bitexact_replay()
    return _replay_probe[1]


def has_pallas_interpret() -> bool:
    """True when jax ships the Pallas TPU interpreter
    (``pltpu.InterpretParams``) that lets the Mosaic kernels run
    off-chip.  Without it (legacy jax), the Pallas kernel suites and the
    ``pallas_ring`` tuning lowerings can only run on a real TPU — their
    tests skip on this flag off-chip instead of failing on the missing
    attribute."""
    try:
        import jax.experimental.pallas.tpu as pltpu
    except Exception:  # pragma: no cover - pallas absent entirely
        return False
    return hasattr(pltpu, "InterpretParams")


def install() -> None:
    global _installed
    if _installed:
        return
    _installed = True
    import jax
    from jax import lax

    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _legacy

        params = set(inspect.signature(_legacy).parameters)

        def shard_map(f=None, **kwargs):
            # Adapt modern kwargs onto the legacy signature.  check_vma
            # nominally maps onto the old replication checker's switch,
            # but that checker predates these programs and rejects valid
            # out_specs ("requires replication which can't be statically
            # inferred"), so on legacy jax it is disabled outright; the
            # modern varying-manual-axes checker runs wherever the real
            # jax.shard_map exists.
            kwargs.pop("check_vma", None)
            if "check_rep" in params:
                kwargs.setdefault("check_rep", False)
            if f is None:  # partial-application (decorator) form
                return lambda fn: _legacy(fn, **kwargs)
            return _legacy(f, **kwargs)

        jax.shard_map = shard_map

    if not hasattr(lax, "axis_size"):

        def axis_size(axis_name):
            # psum of the unit constant folds to the STATIC mapped-axis
            # size (a Python int at trace time) on every jax that lacks
            # lax.axis_size — callers can keep using it in shape math
            return lax.psum(1, axis_name)

        lax.axis_size = axis_size
