"""The membership plane: self-healing communicators.

The sensing machinery landed across the earlier robustness PRs — the
per-peer health state machine (PR 2), the contract plane's cross-rank
exchange paths (PR 7), the straggler judge (PR 8).  All of it *reports*:
a ``dead`` verdict makes every later collective fail fast at intake —
correct, loud, and terminal.  This module is the acting half: a
long-lived fabric that **shrinks the communicator and keeps serving**
when a rank dies, and **routes around** ranks the straggler judge
convicts instead of only reporting them.

Three coupled pieces:

* **Shrink protocol** (:class:`MembershipView` + :class:`MembershipBoard`)
  — on a ``dead`` health verdict (or an explicit ``ACCL.evict_rank()``)
  the surviving ranks run a bounded three-phase agreement over the
  contract plane's exchange paths (shared board on InProc/gang anchors,
  ``MEMBER`` wire frames on the socket tier):

  1. **propose** — the observing rank votes an eviction set (world
     sessions);
  2. **confirm** — peers *second* the proposal (a rank with no
     conflicting evidence adds its vote; votes from ranks inside the
     eviction set never count); a strict majority of the would-be
     survivors confirms the plan;
  3. **cutover** — each survivor atomically applies the confirmed plan
     at its next call boundary: drain the in-flight window, shrink
     every affected communicator to the survivors (fresh epoch — plans
     and tuning overlays re-key instead of silently mis-bucketing),
     fold a ``__shrink__`` marker into the contract digest stream (the
     PR 7 ``__begin__`` discipline: a rank that missed the cutover
     diverges within one window instead of hanging), and tear down /
     re-arm engine sessions over the survivors.

  Collectives in flight against the evicted rank complete with
  structured ``ErrorCode.RANK_EVICTED`` carrying the agreement
  evidence; collectives issued after cutover just run at the new world
  size.  ``soft_reset`` (collective, after the operator heals the
  fabric) restores full membership.

* **Straggler demotion** (:class:`DemotionLedger`) — a convicted
  ``slow_rank`` (PR 8: two-window arrival-skew dominance, exchanged
  cross-rank) is *demoted*: kept in the communicator, excluded from
  root/relay roles where topology allows (today: the barrier's
  internal gather root, plus the advisory ``ACCL.suggest_root()``),
  behind a circuit breaker (strike → open/demoted → half-open probe →
  restore) timed on the monotonic clock.  Demotion decisions are
  SPMD-uniform by construction: they derive from the *exchanged*
  verdict (the shared judge on board-anchored tiers), never from local
  observation, and every per-call decision is latched per (comm, call
  index) on the shared ledger — the first rank to a call index decides,
  every other rank reads the same decision (the sequencer-mailbox
  discipline).  On wire tiers, whose straggler verdicts are pairwise
  (correct only on the conforming side), demotion never alters routing
  — verdicts stay operator signals there.

* **Circuit breaker** (:class:`CircuitBreaker`) — the shared
  strike/open/half-open/closed machine, also used by the XLA command
  ring to degrade ring → inline → host dispatch per communicator when
  sequencer windows fail against a dying peer, re-probing after a
  cool-down (``backends/xla/cmdring.py``).

Opt-in: the *acting* behaviors (shrink, demotion routing) arm via
``ACCL_ELASTIC=1`` or ``ACCL.set_elastic(True)``; the sensing surface
(health transition events, the membership snapshot) is always on.
Everything here is monotonic-clock timed and every wait is bounded
(acclint: unbounded-wait, timer-discipline).

Zero dependencies (stdlib only): this module joins the jax-free import
closure next to ``faults``/``contract``/``monitor`` and is
machine-checked by acclint's jax-free-module pass.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, FrozenSet, List, Optional, Set

from .analysis.markers import spmd_uniform
from .contract import anchored

__all__ = [
    "CircuitBreaker",
    "DemotionLedger",
    "ELASTIC_ENV",
    "MembershipBoard",
    "MembershipView",
    "board_for",
    "env_elastic",
    "ledger_for",
]

ELASTIC_ENV = "ACCL_ELASTIC"
DEMOTE_COOLDOWN_ENV = "ACCL_DEMOTE_COOLDOWN_S"
EVICT_CONFIRM_ENV = "ACCL_EVICT_CONFIRM_S"

DEFAULT_DEMOTE_COOLDOWN_S = 30.0
DEFAULT_EVICT_CONFIRM_S = 5.0

#: cutover records retained per view (the eviction history the
#: determinism test replays)
_HISTORY_CAP = 32
#: latched per-(comm, seq) demotion decisions retained on the ledger
_DECISION_CAP = 256


def env_elastic(environ=None) -> bool:
    """The ``ACCL_ELASTIC`` opt-in (read at ACCL-handle construction):
    arms the acting half — communicator shrink on dead verdicts and
    straggler demotion routing."""
    return (environ or os.environ).get(ELASTIC_ENV, "0") not in ("0", "")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def env_confirm_s() -> float:
    """How long a failed collective waits for eviction confirmation
    before surfacing its raw timeout (bounded — the shrink deadline)."""
    return max(0.1, _env_float(EVICT_CONFIRM_ENV, DEFAULT_EVICT_CONFIRM_S))


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


class CircuitBreaker:
    """Strike / open / half-open / closed, monotonic-clock timed.

    * CLOSED — healthy; ``record_failure`` counts strikes and opens the
      breaker at ``threshold``.
    * OPEN — degraded; ``allow()`` answers ``"open"`` until
      ``cooldown_s`` elapses, then flips to HALF_OPEN.
    * HALF_OPEN — probing; ``allow()`` answers ``"probe"``.
      ``success()`` restores (CLOSED, strikes reset); ``record_failure``
      re-opens with a fresh cool-down.

    Thread-safe; the clock is injectable for deterministic tests.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, threshold: int = 2, cooldown_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self.state = self.CLOSED
        self.strikes = 0
        self.opened_at: Optional[float] = None
        self.opens_total = 0
        self.restores_total = 0
        self.reasons: Dict[str, int] = {}

    def allow(self) -> str:
        """``"closed"`` / ``"probe"`` / ``"open"`` — the routing verdict
        for the next unit of work (a window, a root role)."""
        with self._lock:
            if self.state == self.OPEN:
                if (
                    self.opened_at is not None
                    and self._clock() - self.opened_at >= self.cooldown_s
                ):
                    self.state = self.HALF_OPEN
            if self.state == self.CLOSED:
                return self.CLOSED
            return "probe" if self.state == self.HALF_OPEN else self.OPEN

    def record_failure(self, reason: str = "failure") -> bool:
        """One strike; True when this strike opened (or re-opened) the
        breaker."""
        with self._lock:
            self.reasons[reason] = self.reasons.get(reason, 0) + 1
            self.strikes += 1
            if self.state == self.HALF_OPEN or (
                self.state == self.CLOSED and self.strikes >= self.threshold
            ):
                self.state = self.OPEN
                self.opened_at = self._clock()
                self.opens_total += 1
                return True
            if self.state == self.OPEN:
                self.opened_at = self._clock()  # extend the cool-down
            return False

    def success(self) -> bool:
        """A probe (or closed-path unit) succeeded; True when this
        restored a half-open breaker to CLOSED."""
        with self._lock:
            restored = self.state == self.HALF_OPEN
            if restored:
                self.restores_total += 1
            if self.state != self.CLOSED:
                self.state = self.CLOSED
            self.strikes = 0
            self.opened_at = None
            return restored

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "strikes": self.strikes,
                "threshold": self.threshold,
                "cooldown_s": self.cooldown_s,
                "opens_total": self.opens_total,
                "restores_total": self.restores_total,
                "reasons": dict(self.reasons),
            }


# ---------------------------------------------------------------------------
# the shared agreement board (InProc fabric / XLA gang anchors)
# ---------------------------------------------------------------------------


def board_for(anchor) -> Optional["MembershipBoard"]:
    """The :class:`MembershipBoard` shared by every rank handle anchored
    on ``anchor`` (the engine's ``contract_anchor()`` — the same anchor
    discipline as the contract board); None on one-process-per-rank
    tiers, where ``MEMBER`` wire frames do the exchanging."""
    return anchored(anchor, "_accl_membership_board", MembershipBoard)


def ledger_for(anchor) -> Optional["DemotionLedger"]:
    """The shared :class:`DemotionLedger` for board-anchored tiers —
    demotion routing decisions must come from ONE shared state machine
    so every in-process rank reads the same verdict; None on wire
    tiers, where demotion never alters routing."""
    return anchored(anchor, "_accl_demotion_ledger", DemotionLedger)


class MembershipBoard:
    """Shared eviction-agreement state for rank handles in one process.

    Votes are keyed ``(epoch, eviction set)``; a post that completes a
    strict majority of the would-be survivors confirms the plan.
    Listeners observe both proposals (so elastic peers can second) and
    confirmations (so every handle cuts over).  Votes from ranks inside
    the eviction set never count toward the majority.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # (epoch, frozenset(evict)) -> set(voting world ranks)
        self._votes: Dict[tuple, Set[int]] = {}
        self._plans: Dict[int, dict] = {}  # epoch -> confirmed plan
        self._listeners: List[Callable[[dict], None]] = []

    def add_listener(self, fn: Callable[[dict], None]) -> None:
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    def standing(self, epoch: int) -> Optional[dict]:
        with self._lock:
            plan = self._plans.get(epoch)
            return dict(plan) if plan is not None else None

    def clear(self) -> None:
        """Recovery (soft_reset restore): drop votes and plans."""
        with self._lock:
            self._votes.clear()
            self._plans.clear()

    def post(self, epoch: int, evict: FrozenSet[int], rank: int,
             world: int,
             excluded: FrozenSet[int] = frozenset()) -> Optional[dict]:
        """One rank's vote for evicting ``evict`` (world sessions) at
        membership ``epoch``.  ``excluded`` carries the sessions
        evicted in EARLIER epochs: their votes never count and they
        leave the survivor base — a second eviction's majority is over
        the ranks actually still serving, matching the wire-mode tally
        (views share one cumulative evicted set after cutover, so every
        poster passes the same base).  Returns the confirmed plan once
        a strict majority of survivors voted; notifies listeners of
        both the proposal and (once) the confirmation — listeners are
        called OUTSIDE the board lock."""
        evict = frozenset(int(r) for r in evict)
        excluded = frozenset(int(r) for r in excluded)
        notify: List[tuple] = []
        plan = None
        with self._lock:
            stand = self._plans.get(epoch)
            if stand is not None:
                return dict(stand)
            if rank in evict or rank in excluded:
                return None  # the condemned/evicted don't vote
            votes = self._votes.setdefault((epoch, evict), set())
            fresh = rank not in votes
            votes.add(rank)
            survivors = world - len(excluded | evict)
            listeners = list(self._listeners)
            if len(votes) * 2 > survivors:
                plan = {
                    "kind": "evict",
                    "epoch": epoch,
                    "evict": sorted(evict),
                    "votes": sorted(votes),
                    "world": world,
                    "survivors": survivors,
                    "basis": "board",
                }
                self._plans[epoch] = plan
                notify.append(("confirmed", dict(plan)))
            elif fresh:
                notify.append(("propose", {
                    "epoch": epoch, "evict": sorted(evict),
                    "votes": sorted(votes), "world": world,
                }))
        for kind, payload in notify:
            for fn in listeners:
                try:
                    fn(dict(payload, type=kind))
                except Exception:  # a listener must never fail the vote
                    pass
        return dict(plan) if plan is not None else None


# ---------------------------------------------------------------------------
# straggler demotion (board tiers)
# ---------------------------------------------------------------------------


class DemotionLedger:
    """Shared per-(comm, rank) demotion breakers plus the per-call
    decision latch.  One instance serves every in-process rank handle
    (board anchor), so the routing decision for call index ``seq`` is
    computed exactly once and read identically by every rank — the
    SPMD-uniformity the barrier-root re-route depends on."""

    def __init__(self, cooldown_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.cooldown_s = (
            cooldown_s if cooldown_s is not None
            else _env_float(DEMOTE_COOLDOWN_ENV, DEFAULT_DEMOTE_COOLDOWN_S)
        )
        self._clock = clock
        self._lock = threading.Lock()
        self._breakers: Dict[tuple, CircuitBreaker] = {}
        self._decisions: Dict[tuple, dict] = {}
        self._order: List[tuple] = []  # decision-insertion FIFO (gc)
        self.demotions_total = 0
        self.restores_total = 0
        self.last_decision: Dict[int, dict] = {}  # comm -> latest

    def candidates(self, comm_id: int) -> Set[int]:
        """Ranks with demotion state on ``comm_id`` (for pre-computing
        recovery evidence OUTSIDE the ledger lock)."""
        with self._lock:
            return {r for (c, r) in self._breakers if c == comm_id}

    def decide(self, comm_id: int, world: int, seq: int,
               slow: List[int], recovered: Dict[int, bool]) -> dict:
        """The latched routing decision for call index ``seq`` on
        ``comm_id``: first caller computes (possibly transitioning
        breakers), every later caller reads the cached decision —
        identical on every rank by construction.  ``slow`` is the
        exchanged standing slow_rank verdict (shared judge);
        ``recovered`` maps candidate rank -> "its skew recovered"
        (pre-computed outside this lock)."""
        key = (comm_id, seq)
        with self._lock:
            cached = self._decisions.get(key)
            if cached is not None:
                return dict(cached)
            for r in slow:
                brk = self._breakers.get((comm_id, r))
                if brk is None:
                    brk = self._breakers[(comm_id, r)] = CircuitBreaker(
                        threshold=1, cooldown_s=self.cooldown_s,
                        clock=self._clock,
                    )
                if brk.state == CircuitBreaker.CLOSED:
                    brk.record_failure("slow_rank")
                    self.demotions_total += 1
            demoted: List[int] = []
            restored: List[int] = []
            for (c, r), brk in list(self._breakers.items()):
                if c != comm_id:
                    continue
                verdict = brk.allow()
                if verdict == CircuitBreaker.OPEN:
                    demoted.append(r)
                elif verdict == "probe":
                    # re-admission gates on the RECOVERY evidence (the
                    # judge's current EWMA back under the conviction
                    # bar) — the standing verdict itself is cleared by
                    # the caller on restore, so it cannot self-renew
                    if recovered.get(r, False):
                        brk.success()
                        restored.append(r)
                        self.restores_total += 1
                        del self._breakers[(c, r)]
                    else:
                        brk.record_failure("still_slow")
                        demoted.append(r)
            demoted = sorted(set(demoted))
            healthy = [r for r in range(world) if r not in demoted]
            decision = {
                "seq": seq,
                "demoted": demoted,
                "restored": sorted(restored),
                # the re-routed relay/root role: lowest healthy rank
                # (0 when nothing is demoted — the stock choice)
                "root": healthy[0] if healthy else 0,
            }
            self._decisions[key] = decision
            self._order.append(key)
            while len(self._order) > _DECISION_CAP:
                self._decisions.pop(self._order.pop(0), None)
            self.last_decision[comm_id] = decision
            return dict(decision)

    def demoted(self, comm_id: int) -> List[int]:
        """Currently-demoted ranks (OPEN breakers) on ``comm_id`` —
        the advisory view (``suggest_root``); no transitions."""
        with self._lock:
            return sorted(
                r for (c, r), brk in self._breakers.items()
                if c == comm_id and brk.state != CircuitBreaker.CLOSED
            )

    def reset(self) -> None:
        """soft_reset recovery: drop breakers and latched decisions."""
        with self._lock:
            self._breakers.clear()
            self._decisions.clear()
            self._order.clear()
            self.last_decision.clear()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "cooldown_s": self.cooldown_s,
                "demotions_total": self.demotions_total,
                "restores_total": self.restores_total,
                "breakers": {
                    f"{c}/{r}": brk.snapshot()
                    for (c, r), brk in sorted(self._breakers.items())
                },
                "last_decision": {
                    str(c): dict(d)
                    for c, d in sorted(self.last_decision.items())
                },
            }


# ---------------------------------------------------------------------------
# the per-handle view
# ---------------------------------------------------------------------------


class MembershipView:
    """One rank handle's end of the membership plane.

    Created by the ACCL facade unconditionally (sensing is always on);
    the *acting* half — shrink, demotion routing — arms via
    ``elastic`` (``ACCL_ELASTIC=1`` / ``ACCL.set_elastic``).  Exchange
    rides the board when one exists (InProc / gang anchors) and
    ``MEMBER`` wire frames otherwise (``send_fn``, wired by the
    facade over the fabric).
    """

    def __init__(self, rank: int, world: int,
                 board: Optional[MembershipBoard] = None,
                 ledger: Optional[DemotionLedger] = None,
                 send_fn: Optional[Callable[[dict, Set[int]], None]] = None):
        self.rank = int(rank)       # world session of this handle
        self.world = int(world)
        self.board = board
        self.ledger = ledger
        self._send = send_fn
        self.elastic = False
        self._lock = threading.Lock()
        self.epoch = 0
        # wire-mode agreement state for the CURRENT epoch
        self._votes: Dict[FrozenSet[int], Set[int]] = {}
        self._own_vote: Optional[FrozenSet[int]] = None
        self._announced = False
        self._plan: Optional[dict] = None   # confirmed, not yet applied
        self._confirmed = threading.Event()
        self.evicted: Set[int] = set()      # cumulative evicted sessions
        self.self_evicted = False
        self.history: List[dict] = []       # bounded cutover records
        self.proposals = 0
        self.evictions_total = 0
        self.restores_total = 0
        self._listeners: List[Callable[[dict], None]] = []
        if board is not None:
            board.add_listener(self._on_board_event)

    # -- wiring ---------------------------------------------------------------
    def add_listener(self, fn: Callable[[dict], None]) -> None:
        """Plan-event listener (the engine wires its scheduler wake
        here so in-flight calls against a freshly-confirmed eviction
        fail fast instead of burning their deadline)."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def close(self) -> None:
        if self.board is not None:
            self.board.remove_listener(self._on_board_event)

    def _notify(self, event: dict) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(event)
            except Exception:  # a listener must never fail the plane
                pass

    # -- agreement ------------------------------------------------------------
    def propose(self, evict, reason: str = "",
                evidence: Optional[dict] = None) -> Optional[dict]:
        """Phase 1: vote an eviction set (world sessions) at the
        current epoch.  Returns the confirmed plan when this vote (or
        earlier ones) completed the majority."""
        evict = frozenset(int(r) for r in evict)
        if not evict or self.rank in evict:
            # evicting self is a mark, not a vote: the group decides
            with self._lock:
                if self.rank in evict:
                    self.self_evicted = True
            return None
        with self._lock:
            if self._plan is not None:
                return dict(self._plan)
            epoch = self.epoch
            if self._own_vote is None:
                self._own_vote = evict
                self.proposals += 1
            elif self._own_vote != evict:
                # first-proposal-wins: hard PeerDead evidence lands
                # before cascade timeouts, so the genuine dead set wins
                # the race deterministically; conflicting later sets
                # are dropped (they re-propose at the next epoch)
                evict = self._own_vote
        plan = self._vote(epoch, evict, self.rank, reason, evidence)
        if plan is not None:
            return plan
        self._broadcast("propose", epoch, evict)
        return None

    def _vote(self, epoch: int, evict: FrozenSet[int], rank: int,
              reason: str = "", evidence: Optional[dict] = None
              ) -> Optional[dict]:
        """Register one vote (board post or local tally) and adopt the
        plan if it confirms."""
        if self.board is not None:
            with self._lock:
                excluded = frozenset(self.evicted)
            plan = self.board.post(
                epoch, evict, rank, self.world, excluded=excluded
            )
            if plan is not None:
                self._adopt_plan(plan, reason, evidence)
            return plan
        with self._lock:
            if self._plan is not None:
                return dict(self._plan)
            if (
                epoch != self.epoch or rank in evict
                or rank in self.evicted  # the evicted don't vote
            ):
                return None
            votes = self._votes.setdefault(evict, set())
            votes.add(rank)
            survivors = self.world - len(self.evicted | evict)
            if len(votes) * 2 <= survivors:
                return None
            plan = {
                "kind": "evict",
                "epoch": epoch,
                "evict": sorted(evict),
                "votes": sorted(votes),
                "world": self.world,
                "survivors": survivors,
                "basis": "wire",
            }
        self._adopt_plan(plan, reason, evidence)
        return plan

    def _adopt_plan(self, plan: dict, reason: str = "",
                    evidence: Optional[dict] = None) -> None:
        announce = False
        with self._lock:
            if self._plan is not None or plan.get("epoch") != self.epoch:
                return
            plan = dict(plan)
            if reason:
                plan.setdefault("reason", reason)
            if evidence:
                plan.setdefault("evidence", evidence)
            self._plan = plan
            if self.rank in plan["evict"]:
                self.self_evicted = True
            self._confirmed.set()
            announce = not self._announced
            self._announced = True
        if announce:
            self._broadcast(
                "confirm", plan["epoch"], frozenset(plan["evict"]),
                votes=plan.get("votes"),
            )
        self._notify(dict(plan, type="confirmed"))

    def _broadcast(self, phase: str, epoch: int, evict: FrozenSet[int],
                   votes=None) -> None:
        """Wire-tier exchange: one MEMBER frame per surviving peer.
        Board tiers skip — the shared board already told everyone."""
        if self._send is None or self.board is not None:
            return
        payload = {
            "phase": phase,
            "epoch": epoch,
            "evict": sorted(evict),
            "src_session": self.rank,
        }
        if votes is not None:
            payload["votes"] = sorted(votes)
        try:
            self._send(payload, set(evict) | set(self.evicted))
        except Exception:  # a dead peer mid-broadcast: nothing to tell
            pass

    def observe_wire(self, payload: dict, src: int = -1) -> None:
        """A peer's MEMBER frame (fabric delivery thread).  Elastic
        handles *second* proposals they cannot refute (phase 2 of the
        agreement); confirmed frames carry the full vote set and are
        adopted directly once the majority checks out locally."""
        try:
            phase = payload.get("phase")
            epoch = int(payload.get("epoch", -1))
            evict = frozenset(int(r) for r in payload.get("evict") or ())
            voter = int(payload.get("src_session", src))
        except (TypeError, ValueError):
            return
        if not evict or epoch != self.epoch:
            return
        if self.rank in evict:
            with self._lock:
                self.self_evicted = True
            return
        # tally the sender's vote (and, for confirm frames, the votes
        # it aggregated)
        voters = {voter}
        if phase == "confirm":
            try:
                voters |= {int(v) for v in payload.get("votes") or ()}
            except (TypeError, ValueError):
                pass
        plan = None
        for v in sorted(voters - evict):
            plan = self._vote(epoch, evict, v) or plan
        if plan is not None:
            return
        # phase 2: second a proposal we cannot refute (no conflicting
        # own vote).  Only elastic handles act; passive handles just
        # tally so their snapshot shows the attempt.
        if not self.elastic:
            return
        second = False
        with self._lock:
            if (
                self._own_vote is None and self._plan is None
                and not self.self_evicted
            ):
                self._own_vote = evict
                second = True
        if second:
            self._vote(epoch, evict, self.rank)
            self._broadcast("confirm" if self.confirmed() else "propose",
                            epoch, evict)

    def _on_board_event(self, event: dict) -> None:
        """Board listener: adopt confirmations; second proposals (the
        elastic handles' phase-2 vote)."""
        if event.get("type") == "confirmed":
            self._adopt_plan({k: v for k, v in event.items() if k != "type"})
            return
        if not self.elastic or event.get("type") != "propose":
            return
        try:
            epoch = int(event.get("epoch", -1))
            evict = frozenset(int(r) for r in event.get("evict") or ())
        except (TypeError, ValueError):
            return
        if epoch != self.epoch or not evict or self.rank in evict:
            return
        second = False
        with self._lock:
            if (
                self._own_vote is None and self._plan is None
                and not self.self_evicted
            ):
                self._own_vote = evict
                second = True
        if second:
            self._vote(epoch, evict, self.rank)

    # -- verdict surface ------------------------------------------------------
    def confirmed(self) -> Optional[dict]:
        with self._lock:
            return dict(self._plan) if self._plan is not None else None

    def cutover_ready(self) -> bool:
        return self._plan is not None  # racy read; take_cutover decides

    def proposing(self) -> bool:
        """Any votes (own or observed) pending at the current epoch —
        the failed-call path only waits for confirmation when an
        eviction is actually in flight."""
        with self._lock:
            return (
                self._plan is not None or self._own_vote is not None
                or bool(self._votes)
            )

    def wait_confirmed(self, timeout: float) -> Optional[dict]:
        """Bounded wait for a confirmed plan (the shrink deadline);
        None on timeout — the caller surfaces its raw failure."""
        self._confirmed.wait(timeout=max(0.0, float(timeout)))
        return self.confirmed()

    def plan_covers(self, session: int) -> bool:
        """Is ``session`` under a confirmed (or already applied)
        eviction?  The engine's intake/failure paths use this to
        complete with RANK_EVICTED instead of a bare timeout."""
        with self._lock:
            if session in self.evicted:
                return True
            return self._plan is not None and session in self._plan["evict"]

    def evidence(self) -> dict:
        """The agreement evidence attached to RANK_EVICTED errors."""
        with self._lock:
            return {
                "epoch": self.epoch,
                "evicted": sorted(self.evicted),
                "plan": dict(self._plan) if self._plan is not None else None,
                "self_evicted": self.self_evicted,
            }

    # -- cutover / restore ----------------------------------------------------
    def take_cutover(self) -> Optional[dict]:
        """Atomically consume the confirmed plan: bump the membership
        epoch, fold the eviction set into the cumulative record, reset
        the agreement state for the new epoch.  Exactly one non-None
        return per confirmed plan per view — the facade applies the
        communicator surgery on it."""
        with self._lock:
            plan = self._plan
            if plan is None:
                return None
            self._plan = None
            self._votes.clear()
            self._own_vote = None
            self._announced = False
            self._confirmed.clear()
            self.epoch += 1
            self.evicted |= set(plan["evict"])
            if self.rank in self.evicted:
                self.self_evicted = True
            else:
                self.evictions_total += 1
            record = dict(plan, applied_epoch=self.epoch)
            self.history.append(record)
            if len(self.history) > _HISTORY_CAP:
                self.history.pop(0)
            return dict(record)

    def restore(self) -> Optional[dict]:
        """soft_reset recovery (collective, after the operator healed
        the fabric): re-admit every evicted session, drop any pending
        agreement state, and return to membership epoch 0 — the GENESIS
        epoch, so a previously-evicted rank (which never advanced past
        0) realigns with the survivors without needing to have observed
        the shrink at all.  Returns the restore record, or None when
        there was nothing to restore."""
        with self._lock:
            pending = (
                self._plan is not None or self._own_vote is not None
                or bool(self._votes)
            )
            if not self.evicted and not self.self_evicted and not pending:
                return None
            record = {
                "kind": "restore",
                "readmitted": sorted(self.evicted),
                "epoch": 0,
            }
            had_evictions = bool(self.evicted)
            self.evicted.clear()
            self.self_evicted = False
            self._plan = None
            self._votes.clear()
            self._own_vote = None
            self._announced = False
            self._confirmed.clear()
            self.epoch = 0
            if had_evictions:
                self.restores_total += 1
                self.history.append(record)
                if len(self.history) > _HISTORY_CAP:
                    self.history.pop(0)
        if self.board is not None:
            self.board.clear()
        if self.ledger is not None:
            self.ledger.reset()
        return dict(record)

    # -- demotion -------------------------------------------------------------
    @spmd_uniform
    def demote_decision(self, comm_id: int, world: int, seq: int,
                        slow: List[int],
                        recovered: Dict[int, bool]) -> dict:
        """The SPMD-uniform routing decision for call index ``seq``:
        derived from the EXCHANGED slow_rank verdict (shared judge) and
        latched per (comm, seq) on the shared ledger — never from local
        observation.  ``{"demoted": [...], "restored": [...],
        "root": n}``; the stock decision when no ledger is shared
        (wire tiers: verdicts are pairwise, routing stays put)."""
        if self.ledger is None or not self.elastic:
            return {"seq": seq, "demoted": [], "restored": [], "root": 0}
        return self.ledger.decide(comm_id, world, seq, slow, recovered)

    def demoted(self, comm_id: int) -> List[int]:
        """Currently-demoted ranks on ``comm_id`` (advisory view)."""
        if self.ledger is None:
            return []
        return self.ledger.demoted(comm_id)

    # -- telemetry ------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            doc = {
                "elastic": self.elastic,
                "epoch": self.epoch,
                "world": self.world,
                "evicted": sorted(self.evicted),
                "self_evicted": self.self_evicted,
                "pending_plan": (
                    dict(self._plan) if self._plan is not None else None
                ),
                "proposals": self.proposals,
                "evictions_total": self.evictions_total,
                "restores_total": self.restores_total,
                "history": [dict(h) for h in self.history],
                "exchange": "board" if self.board is not None else "wire",
            }
        if self.ledger is not None:
            doc["demotion"] = self.ledger.snapshot()
        return doc


def member_payload(data: bytes) -> Optional[dict]:
    """Decode one MEMBER wire frame's JSON payload; None on garbage (a
    corrupt-fault frame must never poison the agreement)."""
    try:
        doc = json.loads(data.decode())
    except (ValueError, UnicodeDecodeError):
        return None
    return doc if isinstance(doc, dict) else None
