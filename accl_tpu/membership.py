"""The membership plane: self-healing communicators.

The sensing machinery landed across the earlier robustness PRs — the
per-peer health state machine (PR 2), the contract plane's cross-rank
exchange paths (PR 7), the straggler judge (PR 8).  All of it *reports*:
a ``dead`` verdict makes every later collective fail fast at intake —
correct, loud, and terminal.  This module is the acting half: a
long-lived fabric that **shrinks the communicator and keeps serving**
when a rank dies, and **routes around** ranks the straggler judge
convicts instead of only reporting them.

Three coupled pieces:

* **Shrink protocol** (:class:`MembershipView` + :class:`MembershipBoard`)
  — on a ``dead`` health verdict (or an explicit ``ACCL.evict_rank()``)
  the surviving ranks run a bounded three-phase agreement over the
  contract plane's exchange paths (shared board on InProc/gang anchors,
  ``MEMBER`` wire frames on the socket tier):

  1. **propose** — the observing rank votes an eviction set (world
     sessions);
  2. **confirm** — peers *second* the proposal (a rank with no
     conflicting evidence adds its vote; votes from ranks inside the
     eviction set never count); a strict majority of the would-be
     survivors confirms the plan;
  3. **cutover** — each survivor atomically applies the confirmed plan
     at its next call boundary: drain the in-flight window, shrink
     every affected communicator to the survivors (fresh epoch — plans
     and tuning overlays re-key instead of silently mis-bucketing),
     fold a ``__shrink__`` marker into the contract digest stream (the
     PR 7 ``__begin__`` discipline: a rank that missed the cutover
     diverges within one window instead of hanging), and tear down /
     re-arm engine sessions over the survivors.

  Collectives in flight against the evicted rank complete with
  structured ``ErrorCode.RANK_EVICTED`` carrying the agreement
  evidence; collectives issued after cutover just run at the new world
  size.  ``soft_reset`` (collective, after the operator heals the
  fabric) restores full membership.

* **Join protocol** (elastic expansion — the shrink discipline run in
  the GROW direction): a candidate rank *petitions* over the same
  agreement paths (board event on InProc/gang anchors,
  ``join_petition`` MEMBER frames on the socket tier).  The candidate
  never votes on its own admission — elastic members *second* the
  petition at their current epoch and a strict majority of the current
  members confirms a ``kind="join"`` plan.  Every member applies the
  cutover at its next call boundary (``Communicator.grow()`` in place,
  fresh epoch, ``__join__`` contract marker); the candidate applies it
  inside ``ACCL.join_rank()``, aligning its membership epoch and
  cumulative eviction record to the group's (it missed every bump
  since its previous life, if it had one).  The confirming member's
  **warm-handoff** artifacts (contract generation + per-comm digest
  baseline, tuning plan, plan-cache verdicts) ride the confirmed plan
  so the candidate's first verification window is contract-conformant.

* **Straggler demotion** (:class:`DemotionLedger`) — a convicted
  ``slow_rank`` (PR 8: two-window arrival-skew dominance, exchanged
  cross-rank) is *demoted*: kept in the communicator, excluded from
  root/relay roles where topology allows (today: the barrier's
  internal gather root, plus the advisory ``ACCL.suggest_root()``),
  behind a circuit breaker (strike → open/demoted → half-open probe →
  restore) timed on the monotonic clock.  Demotion decisions are
  SPMD-uniform by construction: they derive from the *exchanged*
  verdict (the shared judge on board-anchored tiers), never from local
  observation, and every per-call decision is latched per (comm, call
  index) on the shared ledger — the first rank to a call index decides,
  every other rank reads the same decision (the sequencer-mailbox
  discipline).  On wire tiers, whose straggler verdicts are pairwise
  (correct only on the conforming side), demotion never alters routing
  — verdicts stay operator signals there.

* **Circuit breaker** (:class:`CircuitBreaker`) — the shared
  strike/open/half-open/closed machine, also used by the XLA command
  ring to degrade ring → inline → host dispatch per communicator when
  sequencer windows fail against a dying peer, re-probing after a
  cool-down (``backends/xla/cmdring.py``).

Opt-in: the *acting* behaviors (shrink, demotion routing) arm via
``ACCL_ELASTIC=1`` or ``ACCL.set_elastic(True)``; the sensing surface
(health transition events, the membership snapshot) is always on.
Everything here is monotonic-clock timed and every wait is bounded
(acclint: unbounded-wait, timer-discipline).

Zero dependencies (stdlib only): this module joins the jax-free import
closure next to ``faults``/``contract``/``monitor`` and is
machine-checked by acclint's jax-free-module pass.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, FrozenSet, List, Optional, Set

from .analysis.markers import spmd_uniform
from .contract import anchored

__all__ = [
    "CircuitBreaker",
    "DemotionLedger",
    "ELASTIC_ENV",
    "JOIN_CONFIRM_ENV",
    "MembershipBoard",
    "MembershipView",
    "board_for",
    "env_elastic",
    "env_join_s",
    "ledger_for",
]

ELASTIC_ENV = "ACCL_ELASTIC"
DEMOTE_COOLDOWN_ENV = "ACCL_DEMOTE_COOLDOWN_S"
EVICT_CONFIRM_ENV = "ACCL_EVICT_CONFIRM_S"
JOIN_CONFIRM_ENV = "ACCL_JOIN_CONFIRM_S"

DEFAULT_DEMOTE_COOLDOWN_S = 30.0
DEFAULT_EVICT_CONFIRM_S = 5.0
DEFAULT_JOIN_CONFIRM_S = 5.0

#: cutover records retained per view (the eviction history the
#: determinism test replays)
_HISTORY_CAP = 32
#: latched per-(comm, seq) demotion decisions retained on the ledger
_DECISION_CAP = 256


def env_elastic(environ=None) -> bool:
    """The ``ACCL_ELASTIC`` opt-in (read at ACCL-handle construction):
    arms the acting half — communicator shrink on dead verdicts and
    straggler demotion routing."""
    return (environ or os.environ).get(ELASTIC_ENV, "0") not in ("0", "")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def env_confirm_s() -> float:
    """How long a failed collective waits for eviction confirmation
    before surfacing its raw timeout (bounded — the shrink deadline)."""
    return max(0.1, _env_float(EVICT_CONFIRM_ENV, DEFAULT_EVICT_CONFIRM_S))


def env_join_s() -> float:
    """How long a candidate's ``join_rank`` waits for admission before
    returning None (bounded — the grow deadline; the petition stands
    and a retry re-petitions)."""
    return max(0.1, _env_float(JOIN_CONFIRM_ENV, DEFAULT_JOIN_CONFIRM_S))


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


class CircuitBreaker:
    """Strike / open / half-open / closed, monotonic-clock timed.

    * CLOSED — healthy; ``record_failure`` counts strikes and opens the
      breaker at ``threshold``.
    * OPEN — degraded; ``allow()`` answers ``"open"`` until
      ``cooldown_s`` elapses, then flips to HALF_OPEN.
    * HALF_OPEN — probing; ``allow()`` answers ``"probe"``.
      ``success()`` restores (CLOSED, strikes reset); ``record_failure``
      re-opens with a fresh cool-down.

    Thread-safe; the clock is injectable for deterministic tests.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, threshold: int = 2, cooldown_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self.state = self.CLOSED
        self.strikes = 0
        self.opened_at: Optional[float] = None
        self.opens_total = 0
        self.restores_total = 0
        self.reasons: Dict[str, int] = {}

    def allow(self) -> str:
        """``"closed"`` / ``"probe"`` / ``"open"`` — the routing verdict
        for the next unit of work (a window, a root role)."""
        with self._lock:
            if self.state == self.OPEN:
                if (
                    self.opened_at is not None
                    and self._clock() - self.opened_at >= self.cooldown_s
                ):
                    self.state = self.HALF_OPEN
            if self.state == self.CLOSED:
                return self.CLOSED
            return "probe" if self.state == self.HALF_OPEN else self.OPEN

    def record_failure(self, reason: str = "failure") -> bool:
        """One strike; True when this strike opened (or re-opened) the
        breaker."""
        with self._lock:
            self.reasons[reason] = self.reasons.get(reason, 0) + 1
            self.strikes += 1
            if self.state == self.HALF_OPEN or (
                self.state == self.CLOSED and self.strikes >= self.threshold
            ):
                self.state = self.OPEN
                self.opened_at = self._clock()
                self.opens_total += 1
                return True
            if self.state == self.OPEN:
                self.opened_at = self._clock()  # extend the cool-down
            return False

    def success(self) -> bool:
        """A probe (or closed-path unit) succeeded; True when this
        restored a half-open breaker to CLOSED."""
        with self._lock:
            restored = self.state == self.HALF_OPEN
            if restored:
                self.restores_total += 1
            if self.state != self.CLOSED:
                self.state = self.CLOSED
            self.strikes = 0
            self.opened_at = None
            return restored

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "strikes": self.strikes,
                "threshold": self.threshold,
                "cooldown_s": self.cooldown_s,
                "opens_total": self.opens_total,
                "restores_total": self.restores_total,
                "reasons": dict(self.reasons),
            }


# ---------------------------------------------------------------------------
# the shared agreement board (InProc fabric / XLA gang anchors)
# ---------------------------------------------------------------------------


def board_for(anchor) -> Optional["MembershipBoard"]:
    """The :class:`MembershipBoard` shared by every rank handle anchored
    on ``anchor`` (the engine's ``contract_anchor()`` — the same anchor
    discipline as the contract board); None on one-process-per-rank
    tiers, where ``MEMBER`` wire frames do the exchanging."""
    return anchored(anchor, "_accl_membership_board", MembershipBoard)


def ledger_for(anchor) -> Optional["DemotionLedger"]:
    """The shared :class:`DemotionLedger` for board-anchored tiers —
    demotion routing decisions must come from ONE shared state machine
    so every in-process rank reads the same verdict; None on wire
    tiers, where demotion never alters routing."""
    return anchored(anchor, "_accl_demotion_ledger", DemotionLedger)


class MembershipBoard:
    """Shared membership-agreement state for rank handles in one process.

    Votes are keyed ``(epoch, kind, member set)`` — ``kind`` is
    ``"evict"`` (the shrink direction) or ``"join"`` (the grow
    direction); a post that completes a strict majority confirms the
    plan.  Listeners observe proposals and join petitions (so elastic
    peers can second) and confirmations (so every handle cuts over).
    Votes from ranks inside the eviction/admission set never count
    toward the majority — the condemned don't vote, and neither does
    the candidate petitioning its own admission.
    """

    #: confirmed plans retained (one per applied cutover; a bound only
    #: guards pathological epoch churn)
    _PLAN_CAP = 64

    def __init__(self):
        self._lock = threading.Lock()
        # (epoch, kind, frozenset(members)) -> set(voting world ranks)
        self._votes: Dict[tuple, Set[int]] = {}
        self._plans: Dict[tuple, dict] = {}  # (epoch, kind) -> plan
        self._plan_order: List[tuple] = []
        self._listeners: List[Callable[[dict], None]] = []

    def add_listener(self, fn: Callable[[dict], None]) -> None:
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    def standing(self, epoch: int, kind: str = "evict") -> Optional[dict]:
        with self._lock:
            plan = self._plans.get((epoch, kind))
            return dict(plan) if plan is not None else None

    def clear(self) -> None:
        """Recovery (soft_reset restore): drop votes and plans."""
        with self._lock:
            self._votes.clear()
            self._plans.clear()
            self._plan_order.clear()

    def _store_plan(self, key: tuple, plan: dict) -> None:
        # caller holds self._lock
        self._plans[key] = plan
        self._plan_order.append(key)
        while len(self._plan_order) > self._PLAN_CAP:
            self._plans.pop(self._plan_order.pop(0), None)

    def post(self, epoch: int, evict: FrozenSet[int], rank: int,
             world: int,
             excluded: FrozenSet[int] = frozenset()) -> Optional[dict]:
        """One rank's vote for evicting ``evict`` (world sessions) at
        membership ``epoch``.  ``excluded`` carries the sessions
        evicted in EARLIER epochs: their votes never count and they
        leave the survivor base — a second eviction's majority is over
        the ranks actually still serving, matching the wire-mode tally
        (views share one cumulative evicted set after cutover, so every
        poster passes the same base).  Returns the confirmed plan once
        a strict majority of survivors voted; notifies listeners of
        both the proposal and (once) the confirmation — listeners are
        called OUTSIDE the board lock."""
        evict = frozenset(int(r) for r in evict)
        excluded = frozenset(int(r) for r in excluded)
        notify: List[tuple] = []
        plan = None
        with self._lock:
            stand = self._plans.get((epoch, "evict"))
            if stand is not None:
                return dict(stand)
            if rank in evict or rank in excluded:
                return None  # the condemned/evicted don't vote
            votes = self._votes.setdefault((epoch, "evict", evict), set())
            fresh = rank not in votes
            votes.add(rank)
            survivors = world - len(excluded | evict)
            listeners = list(self._listeners)
            if len(votes) * 2 > survivors:
                plan = {
                    "kind": "evict",
                    "epoch": epoch,
                    "evict": sorted(evict),
                    "votes": sorted(votes),
                    "world": world,
                    "survivors": survivors,
                    "basis": "board",
                }
                self._store_plan((epoch, "evict"), plan)
                notify.append(("confirmed", dict(plan)))
            elif fresh:
                notify.append(("propose", {
                    "epoch": epoch, "evict": sorted(evict),
                    "votes": sorted(votes), "world": world,
                }))
        for kind, payload in notify:
            for fn in listeners:
                try:
                    fn(dict(payload, type=kind))
                except Exception:  # a listener must never fail the vote
                    pass
        return dict(plan) if plan is not None else None

    def petition(self, admit: FrozenSet[int], world: int) -> None:
        """The candidate's JOIN petition: NOT a vote — a listener event
        (type ``join_petition``) the elastic members answer by
        seconding (:meth:`post_join`).  A petition is idempotent and
        retryable; the candidate learns the outcome from the confirmed
        plan's listener event."""
        admit = frozenset(int(r) for r in admit)
        with self._lock:
            listeners = list(self._listeners)
        payload = {"admit": sorted(admit), "world": world}
        for fn in listeners:
            try:
                fn(dict(payload, type="join_petition"))
            except Exception:  # a listener must never fail the petition
                pass

    def post_join(self, epoch: int, admit: FrozenSet[int], rank: int,
                  world: int, excluded: FrozenSet[int] = frozenset(),
                  handoff: Optional[dict] = None) -> Optional[dict]:
        """One member's vote for ADMITTING ``admit`` (world sessions)
        at membership ``epoch`` — the grow mirror of :meth:`post`.  The
        candidate itself never votes (it petitions; the group decides);
        ``excluded`` is the voter's cumulative evicted set and the
        strict majority is over the CURRENT members (world minus
        excluded — the admitted are joining, not leaving, so they don't
        shrink the base).  The confirming voter's ``handoff`` (the
        warm-start artifacts its facade exported) rides the plan to the
        candidate, and ``excluded_after`` carries the post-join
        cumulative eviction record the candidate aligns to.  Returns
        the confirmed plan once the majority voted; notifies listeners
        OUTSIDE the board lock, like :meth:`post`."""
        admit = frozenset(int(r) for r in admit)
        excluded = frozenset(int(r) for r in excluded)
        notify: List[tuple] = []
        plan = None
        with self._lock:
            stand = self._plans.get((epoch, "join"))
            if stand is not None:
                return dict(stand)
            if rank in admit or rank in excluded:
                return None  # the candidate (and the evicted) don't vote
            votes = self._votes.setdefault((epoch, "join", admit), set())
            fresh = rank not in votes
            votes.add(rank)
            members = world - len(excluded)
            listeners = list(self._listeners)
            if len(votes) * 2 > members:
                plan = {
                    "kind": "join",
                    "epoch": epoch,
                    "admit": sorted(admit),
                    "votes": sorted(votes),
                    "world": world,
                    "survivors": members,
                    "excluded_after": sorted(excluded - admit),
                    "basis": "board",
                }
                if handoff:
                    plan["handoff"] = handoff
                self._store_plan((epoch, "join"), plan)
                notify.append(("confirmed", dict(plan)))
            elif fresh:
                notify.append(("join_propose", {
                    "epoch": epoch, "admit": sorted(admit),
                    "votes": sorted(votes), "world": world,
                }))
        for kind, payload in notify:
            for fn in listeners:
                try:
                    fn(dict(payload, type=kind))
                except Exception:  # a listener must never fail the vote
                    pass
        return dict(plan) if plan is not None else None


# ---------------------------------------------------------------------------
# straggler demotion (board tiers)
# ---------------------------------------------------------------------------


class DemotionLedger:
    """Shared per-(comm, rank) demotion breakers plus the per-call
    decision latch.  One instance serves every in-process rank handle
    (board anchor), so the routing decision for call index ``seq`` is
    computed exactly once and read identically by every rank — the
    SPMD-uniformity the barrier-root re-route depends on."""

    def __init__(self, cooldown_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.cooldown_s = (
            cooldown_s if cooldown_s is not None
            else _env_float(DEMOTE_COOLDOWN_ENV, DEFAULT_DEMOTE_COOLDOWN_S)
        )
        self._clock = clock
        self._lock = threading.Lock()
        self._breakers: Dict[tuple, CircuitBreaker] = {}
        self._decisions: Dict[tuple, dict] = {}
        self._order: List[tuple] = []  # decision-insertion FIFO (gc)
        self.demotions_total = 0
        self.restores_total = 0
        self.last_decision: Dict[int, dict] = {}  # comm -> latest

    def candidates(self, comm_id: int) -> Set[int]:
        """Ranks with demotion state on ``comm_id`` (for pre-computing
        recovery evidence OUTSIDE the ledger lock)."""
        with self._lock:
            return {r for (c, r) in self._breakers if c == comm_id}

    def decide(self, comm_id: int, world: int, seq: int,
               slow: List[int], recovered: Dict[int, bool]) -> dict:
        """The latched routing decision for call index ``seq`` on
        ``comm_id``: first caller computes (possibly transitioning
        breakers), every later caller reads the cached decision —
        identical on every rank by construction.  ``slow`` is the
        exchanged standing slow_rank verdict (shared judge);
        ``recovered`` maps candidate rank -> "its skew recovered"
        (pre-computed outside this lock)."""
        key = (comm_id, seq)
        with self._lock:
            cached = self._decisions.get(key)
            if cached is not None:
                return dict(cached)
            for r in slow:
                brk = self._breakers.get((comm_id, r))
                if brk is None:
                    brk = self._breakers[(comm_id, r)] = CircuitBreaker(
                        threshold=1, cooldown_s=self.cooldown_s,
                        clock=self._clock,
                    )
                if brk.state == CircuitBreaker.CLOSED:
                    brk.record_failure("slow_rank")
                    self.demotions_total += 1
            demoted: List[int] = []
            restored: List[int] = []
            for (c, r), brk in list(self._breakers.items()):
                if c != comm_id:
                    continue
                verdict = brk.allow()
                if verdict == CircuitBreaker.OPEN:
                    demoted.append(r)
                elif verdict == "probe":
                    # re-admission gates on the RECOVERY evidence (the
                    # judge's current EWMA back under the conviction
                    # bar) — the standing verdict itself is cleared by
                    # the caller on restore, so it cannot self-renew
                    if recovered.get(r, False):
                        brk.success()
                        restored.append(r)
                        self.restores_total += 1
                        del self._breakers[(c, r)]
                    else:
                        brk.record_failure("still_slow")
                        demoted.append(r)
            demoted = sorted(set(demoted))
            healthy = [r for r in range(world) if r not in demoted]
            decision = {
                "seq": seq,
                "demoted": demoted,
                "restored": sorted(restored),
                # the re-routed relay/root role: lowest healthy rank
                # (0 when nothing is demoted — the stock choice)
                "root": healthy[0] if healthy else 0,
            }
            self._decisions[key] = decision
            self._order.append(key)
            while len(self._order) > _DECISION_CAP:
                self._decisions.pop(self._order.pop(0), None)
            self.last_decision[comm_id] = decision
            return dict(decision)

    def demoted(self, comm_id: int) -> List[int]:
        """Currently-demoted ranks (OPEN breakers) on ``comm_id`` —
        the advisory view (``suggest_root``); no transitions."""
        with self._lock:
            return sorted(
                r for (c, r), brk in self._breakers.items()
                if c == comm_id and brk.state != CircuitBreaker.CLOSED
            )

    def reset(self) -> None:
        """soft_reset recovery: drop breakers and latched decisions."""
        with self._lock:
            self._breakers.clear()
            self._decisions.clear()
            self._order.clear()
            self.last_decision.clear()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "cooldown_s": self.cooldown_s,
                "demotions_total": self.demotions_total,
                "restores_total": self.restores_total,
                "breakers": {
                    f"{c}/{r}": brk.snapshot()
                    for (c, r), brk in sorted(self._breakers.items())
                },
                "last_decision": {
                    str(c): dict(d)
                    for c, d in sorted(self.last_decision.items())
                },
            }


# ---------------------------------------------------------------------------
# the per-handle view
# ---------------------------------------------------------------------------


class MembershipView:
    """One rank handle's end of the membership plane.

    Created by the ACCL facade unconditionally (sensing is always on);
    the *acting* half — shrink, demotion routing — arms via
    ``elastic`` (``ACCL_ELASTIC=1`` / ``ACCL.set_elastic``).  Exchange
    rides the board when one exists (InProc / gang anchors) and
    ``MEMBER`` wire frames otherwise (``send_fn``, wired by the
    facade over the fabric).
    """

    def __init__(self, rank: int, world: int,
                 board: Optional[MembershipBoard] = None,
                 ledger: Optional[DemotionLedger] = None,
                 send_fn: Optional[Callable[[dict, Set[int]], None]] = None):
        self.rank = int(rank)       # world session of this handle
        self.world = int(world)
        self.board = board
        self.ledger = ledger
        self._send = send_fn
        self.elastic = False
        self._lock = threading.Lock()
        self.epoch = 0
        # wire-mode agreement state for the CURRENT epoch
        self._votes: Dict[FrozenSet[int], Set[int]] = {}
        self._own_vote: Optional[FrozenSet[int]] = None
        self._announced = False
        self._plan: Optional[dict] = None   # confirmed, not yet applied
        self._confirmed = threading.Event()
        self.evicted: Set[int] = set()      # cumulative evicted sessions
        self.self_evicted = False
        self.history: List[dict] = []       # bounded cutover records
        self.proposals = 0
        self.evictions_total = 0
        self.restores_total = 0
        # join (grow) agreement state for the CURRENT epoch
        self._join_votes: Dict[FrozenSet[int], Set[int]] = {}
        self._own_join: Optional[FrozenSet[int]] = None
        self._last_join: Optional[dict] = None  # latest APPLIED join
        self.joins_total = 0
        self.petitions = 0
        # warm handoff: the facade's artifact exporter (contract
        # generation + digest baselines, tuning plan, plan verdicts) —
        # called by the vote that confirms an admission, so the
        # artifacts ride the plan to the candidate
        self.handoff_fn: Optional[Callable[[], dict]] = None
        self._listeners: List[Callable[[dict], None]] = []
        if board is not None:
            board.add_listener(self._on_board_event)

    # -- wiring ---------------------------------------------------------------
    def add_listener(self, fn: Callable[[dict], None]) -> None:
        """Plan-event listener (the engine wires its scheduler wake
        here so in-flight calls against a freshly-confirmed eviction
        fail fast instead of burning their deadline)."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def close(self) -> None:
        if self.board is not None:
            self.board.remove_listener(self._on_board_event)

    def _notify(self, event: dict) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(event)
            except Exception:  # a listener must never fail the plane
                pass

    # -- agreement ------------------------------------------------------------
    def propose(self, evict, reason: str = "",
                evidence: Optional[dict] = None) -> Optional[dict]:
        """Phase 1: vote an eviction set (world sessions) at the
        current epoch.  Returns the confirmed plan when this vote (or
        earlier ones) completed the majority."""
        evict = frozenset(int(r) for r in evict)
        if not evict or self.rank in evict:
            # evicting self is a mark, not a vote: the group decides
            with self._lock:
                if self.rank in evict:
                    self.self_evicted = True
            return None
        with self._lock:
            if self._plan is not None:
                return dict(self._plan)
            epoch = self.epoch
            if self._own_vote is None:
                self._own_vote = evict
                self.proposals += 1
            elif self._own_vote != evict:
                # first-proposal-wins: hard PeerDead evidence lands
                # before cascade timeouts, so the genuine dead set wins
                # the race deterministically; conflicting later sets
                # are dropped (they re-propose at the next epoch)
                evict = self._own_vote
        plan = self._vote(epoch, evict, self.rank, reason, evidence)
        if plan is not None:
            return plan
        self._broadcast("propose", epoch, evict)
        return None

    def petition_join(self) -> None:
        """The candidate's end of the GROW agreement (phase 1): ask the
        group to admit this session.  Clears any stale pending state
        from the previous life first (the eviction plan the condemned
        rank adopted but never applied would otherwise block the
        admission confirm from landing); the admission confirms via the
        normal plan surface (``wait_confirmed`` → ``take_cutover``).
        Idempotent and retryable — a petition that races an in-flight
        eviction agreement is simply ignored by busy members."""
        with self._lock:
            self._plan = None
            self._votes.clear()
            self._join_votes.clear()
            self._own_vote = None
            self._own_join = None
            self._announced = False
            self._confirmed.clear()
            self.petitions += 1
        admit = frozenset({self.rank})
        if self.board is not None:
            self.board.petition(admit, self.world)
            return
        self._send_frames({
            "phase": "join_petition",
            "admit": sorted(admit),
            "src_session": self.rank,
        }, exclude=set())

    def _vote(self, epoch: int, evict: FrozenSet[int], rank: int,
              reason: str = "", evidence: Optional[dict] = None
              ) -> Optional[dict]:
        """Register one vote (board post or local tally) and adopt the
        plan if it confirms."""
        if self.board is not None:
            with self._lock:
                excluded = frozenset(self.evicted)
            plan = self.board.post(
                epoch, evict, rank, self.world, excluded=excluded
            )
            if plan is not None:
                self._adopt_plan(plan, reason, evidence)
            return plan
        with self._lock:
            if self._plan is not None:
                return dict(self._plan)
            if (
                epoch != self.epoch or rank in evict
                or rank in self.evicted  # the evicted don't vote
            ):
                return None
            votes = self._votes.setdefault(evict, set())
            votes.add(rank)
            survivors = self.world - len(self.evicted | evict)
            if len(votes) * 2 <= survivors:
                return None
            plan = {
                "kind": "evict",
                "epoch": epoch,
                "evict": sorted(evict),
                "votes": sorted(votes),
                "world": self.world,
                "survivors": survivors,
                "basis": "wire",
            }
        self._adopt_plan(plan, reason, evidence)
        return plan

    def _vote_join(self, epoch: int, admit: FrozenSet[int],
                   rank: int) -> Optional[dict]:
        """Register one ADMISSION vote (board post or local wire tally)
        and adopt the join plan if it confirms.  The voter's handoff
        artifacts ride the board post (the confirming vote's land in
        the plan); on wire tiers the handoff attaches to the confirm
        broadcast instead."""
        if self.board is not None:
            with self._lock:
                excluded = frozenset(self.evicted)
            handoff = None
            if rank == self.rank and self.handoff_fn is not None:
                try:
                    handoff = self.handoff_fn()
                except Exception:  # an exporter must never fail the vote
                    handoff = None
            plan = self.board.post_join(
                epoch, admit, rank, self.world,
                excluded=excluded, handoff=handoff,
            )
            if plan is not None:
                self._adopt_plan(plan)
            return plan
        with self._lock:
            if self._plan is not None:
                if self._plan.get("kind") == "join":
                    return dict(self._plan)
                return None
            if (
                epoch != self.epoch or rank in admit
                or rank in self.evicted  # the evicted don't vote
            ):
                return None
            votes = self._join_votes.setdefault(admit, set())
            votes.add(rank)
            members = self.world - len(self.evicted)
            if len(votes) * 2 <= members:
                return None
            plan = {
                "kind": "join",
                "epoch": epoch,
                "admit": sorted(admit),
                "votes": sorted(votes),
                "world": self.world,
                "survivors": members,
                "excluded_after": sorted(self.evicted - admit),
                "basis": "wire",
            }
        self._adopt_plan(plan)
        return plan

    def _adopt_plan(self, plan: dict, reason: str = "",
                    evidence: Optional[dict] = None) -> None:
        if plan.get("kind") == "join":
            self._adopt_join(plan)
            return
        announce = False
        with self._lock:
            if self._plan is not None or plan.get("epoch") != self.epoch:
                return
            plan = dict(plan)
            if reason:
                plan.setdefault("reason", reason)
            if evidence:
                plan.setdefault("evidence", evidence)
            self._plan = plan
            if self.rank in plan["evict"]:
                self.self_evicted = True
            self._confirmed.set()
            announce = not self._announced
            self._announced = True
        if announce:
            self._broadcast(
                "confirm", plan["epoch"], frozenset(plan["evict"]),
                votes=plan.get("votes"),
            )
        self._notify(dict(plan, type="confirmed"))

    def _broadcast(self, phase: str, epoch: int, evict: FrozenSet[int],
                   votes=None) -> None:
        """Wire-tier exchange: one MEMBER frame per surviving peer.
        Board tiers skip — the shared board already told everyone."""
        if self._send is None or self.board is not None:
            return
        payload = {
            "phase": phase,
            "epoch": epoch,
            "evict": sorted(evict),
            "src_session": self.rank,
        }
        if votes is not None:
            payload["votes"] = sorted(votes)
        try:
            self._send(payload, set(evict) | set(self.evicted))
        except Exception:  # a dead peer mid-broadcast: nothing to tell
            pass

    def _send_frames(self, payload: dict, exclude: Set[int]) -> None:
        """Raw MEMBER frames to the world peers minus ``exclude`` —
        the join phases' exchange (which, unlike evictions, must REACH
        sessions currently outside the shrunk group: the candidate).
        Board tiers skip, like :meth:`_broadcast`."""
        if self._send is None or self.board is not None:
            return
        try:
            self._send(payload, set(exclude))
        except Exception:  # a dead peer mid-broadcast: nothing to tell
            pass

    def _adopt_join(self, plan: dict) -> None:
        """Adopt a confirmed JOIN plan.  Members require the plan at
        their current epoch (the evict discipline); the candidate — by
        definition desynced, it missed every epoch bump since its
        previous life — accepts any join covering it that is not older
        than its own record."""
        candidate = self.rank in set(plan.get("admit") or ())
        announce = False
        with self._lock:
            if self._plan is not None:
                return
            epoch = plan.get("epoch", -1)
            if candidate:
                if not isinstance(epoch, int) or epoch < self.epoch:
                    return  # a previous life's admission: stale
            elif epoch != self.epoch:
                return
            self._plan = dict(plan)
            self._confirmed.set()
            announce = not self._announced and not candidate
            self._announced = True
        if announce:
            self._broadcast_join_confirm(plan)
        self._notify(dict(plan, type="confirmed"))

    def _broadcast_join_confirm(self, plan: dict) -> None:
        """Wire-tier confirm for a JOIN: the announcing member attaches
        its warm-handoff artifacts so the candidate can align its
        contract stream before its first collective."""
        if self._send is None or self.board is not None:
            return
        payload = dict(plan)
        if "handoff" not in payload and self.handoff_fn is not None:
            try:
                payload["handoff"] = self.handoff_fn()
            except Exception:  # an exporter must never fail the confirm
                pass
        payload["phase"] = "join_confirm"
        payload["src_session"] = self.rank
        admit = set(plan.get("admit") or ())
        self._send_frames(payload, exclude=set(self.evicted) - admit)

    def observe_wire(self, payload: dict, src: int = -1) -> None:
        """A peer's MEMBER frame (fabric delivery thread).  Elastic
        handles *second* proposals they cannot refute (phase 2 of the
        agreement); confirmed frames carry the full vote set and are
        adopted directly once the majority checks out locally."""
        phase = payload.get("phase")
        if phase in ("join_petition", "join_propose", "join_confirm"):
            self._observe_join_wire(phase, payload, src)
            return
        try:
            epoch = int(payload.get("epoch", -1))
            evict = frozenset(int(r) for r in payload.get("evict") or ())
            voter = int(payload.get("src_session", src))
        except (TypeError, ValueError):
            return
        if not evict or epoch != self.epoch:
            return
        if self.rank in evict:
            with self._lock:
                self.self_evicted = True
            return
        # tally the sender's vote (and, for confirm frames, the votes
        # it aggregated)
        voters = {voter}
        if phase == "confirm":
            try:
                voters |= {int(v) for v in payload.get("votes") or ()}
            except (TypeError, ValueError):
                pass
        plan = None
        for v in sorted(voters - evict):
            plan = self._vote(epoch, evict, v) or plan
        if plan is not None:
            return
        # phase 2: second a proposal we cannot refute (no conflicting
        # own vote).  Only elastic handles act; passive handles just
        # tally so their snapshot shows the attempt.
        if not self.elastic:
            return
        second = False
        with self._lock:
            if (
                self._own_vote is None and self._plan is None
                and not self.self_evicted
            ):
                self._own_vote = evict
                second = True
        if second:
            self._vote(epoch, evict, self.rank)
            self._broadcast("confirm" if self.confirmed() else "propose",
                            epoch, evict)

    def _observe_join_wire(self, phase: str, payload: dict,
                           src: int) -> None:
        """The GROW agreement's wire phases.  ``join_petition`` (from
        the candidate): elastic members not mid-agreement second it at
        their current epoch and re-broadcast; a member that ALREADY
        applied an admission covering the candidate re-sends the
        confirm (a lost-confirm retry must converge, not re-vote).
        ``join_propose`` (member→member): tally the voter, second if
        fresh.  ``join_confirm``: adopt — the candidate from any epoch
        not older than its own record, members at their current one."""
        try:
            admit = frozenset(int(r) for r in payload.get("admit") or ())
            voter = int(payload.get("src_session", src))
        except (TypeError, ValueError):
            return
        if not admit:
            return
        if self.rank in admit:
            # frames about OUR OWN admission: only the confirm matters
            if phase == "join_confirm":
                plan = {
                    k: v for k, v in payload.items()
                    if k not in ("phase", "src_session")
                }
                plan.setdefault("kind", "join")
                plan.setdefault("basis", "wire")
                self._adopt_plan(plan)
            return
        if not self.elastic:
            return
        with self._lock:
            if self.self_evicted or self.rank in self.evicted:
                return
            busy = self._plan is not None or self._own_vote is not None
            applied = (
                dict(self._last_join)
                if self._last_join is not None else None
            )
        if phase == "join_petition":
            if (
                applied is not None
                and admit <= set(applied.get("admit") or ())
                and applied.get("applied_epoch", 0) >= self.epoch
            ):
                # already admitted; the candidate missed the confirm
                resend = dict(applied)
                resend.pop("applied_epoch", None)
                resend["phase"] = "join_confirm"
                resend["src_session"] = self.rank
                self._send_frames(resend, exclude=set(self.evicted) - admit)
                return
            if busy:
                return  # an agreement is in flight; the candidate retries
            with self._lock:
                if self._own_join is None:
                    self._own_join = admit
                vote_self = self._own_join == admit
                epoch = self.epoch
            if not vote_self:
                return  # already seconding a different admission
            self._send_frames({
                "phase": "join_propose", "epoch": epoch,
                "admit": sorted(admit), "src_session": self.rank,
            }, exclude=set(self.evicted) - admit)
            self._vote_join(epoch, admit, self.rank)
            return
        try:
            epoch = int(payload.get("epoch", -1))
        except (TypeError, ValueError):
            return
        if phase == "join_propose":
            if epoch != self.epoch:
                return
            self._vote_join(epoch, admit, voter)
            second = False
            vote_self = False
            with self._lock:
                if self._plan is None and self._own_vote is None:
                    if self._own_join is None:
                        self._own_join = admit
                        second = True
                    vote_self = self._own_join == admit
            if second:
                self._send_frames({
                    "phase": "join_propose", "epoch": epoch,
                    "admit": sorted(admit), "src_session": self.rank,
                }, exclude=set(self.evicted) - admit)
            if vote_self:
                self._vote_join(epoch, admit, self.rank)
            return
        if phase == "join_confirm":
            plan = {
                k: v for k, v in payload.items()
                if k not in ("phase", "src_session")
            }
            plan.setdefault("kind", "join")
            plan.setdefault("basis", "wire")
            # tally the aggregated votes so local state agrees, then
            # adopt (epoch-checked inside)
            try:
                voters = {int(v) for v in payload.get("votes") or ()}
            except (TypeError, ValueError):
                voters = set()
            for v in sorted((voters | {voter}) - admit):
                self._vote_join(epoch, admit, v)
            self._adopt_plan(plan)

    def _on_board_event(self, event: dict) -> None:
        """Board listener: adopt confirmations; second proposals and
        join petitions (the elastic handles' phase-2 vote)."""
        if event.get("type") == "confirmed":
            self._adopt_plan({k: v for k, v in event.items() if k != "type"})
            return
        if not self.elastic:
            return
        if event.get("type") == "join_petition":
            try:
                admit = frozenset(
                    int(r) for r in event.get("admit") or ()
                )
            except (TypeError, ValueError):
                return
            if not admit or self.rank in admit:
                return
            with self._lock:
                if (
                    self.self_evicted or self.rank in self.evicted
                    or self._plan is not None
                    or self._own_vote is not None
                ):
                    return
            self._vote_join(self.epoch, admit, self.rank)
            return
        if event.get("type") != "propose":
            return
        try:
            epoch = int(event.get("epoch", -1))
            evict = frozenset(int(r) for r in event.get("evict") or ())
        except (TypeError, ValueError):
            return
        if epoch != self.epoch or not evict or self.rank in evict:
            return
        second = False
        with self._lock:
            if (
                self._own_vote is None and self._plan is None
                and not self.self_evicted
            ):
                self._own_vote = evict
                second = True
        if second:
            self._vote(epoch, evict, self.rank)

    # -- verdict surface ------------------------------------------------------
    def confirmed(self) -> Optional[dict]:
        with self._lock:
            return dict(self._plan) if self._plan is not None else None

    def cutover_ready(self) -> bool:
        return self._plan is not None  # racy read; take_cutover decides

    def proposing(self) -> bool:
        """Any votes (own or observed) pending at the current epoch —
        the failed-call path only waits for confirmation when an
        eviction is actually in flight."""
        with self._lock:
            return (
                self._plan is not None or self._own_vote is not None
                or bool(self._votes)
            )

    def wait_confirmed(self, timeout: float) -> Optional[dict]:
        """Bounded wait for a confirmed plan (the shrink deadline);
        None on timeout — the caller surfaces its raw failure."""
        self._confirmed.wait(timeout=max(0.0, float(timeout)))
        return self.confirmed()

    def plan_covers(self, session: int) -> bool:
        """Is ``session`` under a confirmed (or already applied)
        eviction?  The engine's intake/failure paths use this to
        complete with RANK_EVICTED instead of a bare timeout."""
        with self._lock:
            if session in self.evicted:
                return True
            return (
                self._plan is not None
                and self._plan.get("kind", "evict") == "evict"
                and session in self._plan["evict"]
            )

    def evidence(self) -> dict:
        """The agreement evidence attached to RANK_EVICTED errors."""
        with self._lock:
            return {
                "epoch": self.epoch,
                "evicted": sorted(self.evicted),
                "plan": dict(self._plan) if self._plan is not None else None,
                "self_evicted": self.self_evicted,
            }

    # -- cutover / restore ----------------------------------------------------
    def take_cutover(self) -> Optional[dict]:
        """Atomically consume the confirmed plan: bump the membership
        epoch, fold the eviction/admission set into the cumulative
        record, reset the agreement state for the new epoch.  Exactly
        one non-None return per confirmed plan per view — the facade
        applies the communicator surgery on it.  For a JOIN plan the
        admitted side ALIGNS instead of bumping: its epoch becomes the
        group's post-join epoch and its cumulative eviction record
        becomes the plan's ``excluded_after`` (it missed every bump
        since its previous life)."""
        with self._lock:
            plan = self._plan
            if plan is None:
                return None
            self._plan = None
            self._votes.clear()
            self._join_votes.clear()
            self._own_vote = None
            self._own_join = None
            self._announced = False
            self._confirmed.clear()
            if plan.get("kind") == "join":
                admit = set(int(r) for r in plan.get("admit") or ())
                if self.rank in admit:
                    self.epoch = int(plan.get("epoch", self.epoch)) + 1
                    self.evicted = set(
                        int(r) for r in plan.get("excluded_after") or ()
                    )
                    self.self_evicted = False
                else:
                    self.epoch += 1
                    self.evicted -= admit
                self.joins_total += 1
                record = dict(plan, applied_epoch=self.epoch)
                self._last_join = dict(record)
                self.history.append(record)
                if len(self.history) > _HISTORY_CAP:
                    self.history.pop(0)
                return dict(record)
            self.epoch += 1
            self.evicted |= set(plan["evict"])
            if self.rank in self.evicted:
                self.self_evicted = True
            else:
                self.evictions_total += 1
            record = dict(plan, applied_epoch=self.epoch)
            self.history.append(record)
            if len(self.history) > _HISTORY_CAP:
                self.history.pop(0)
            return dict(record)

    def restore(self) -> Optional[dict]:
        """soft_reset recovery (collective, after the operator healed
        the fabric): re-admit every evicted session, drop any pending
        agreement state, and return to membership epoch 0 — the GENESIS
        epoch, so a previously-evicted rank (which never advanced past
        0) realigns with the survivors without needing to have observed
        the shrink at all.  Returns the restore record, or None when
        there was nothing to restore."""
        with self._lock:
            pending = (
                self._plan is not None or self._own_vote is not None
                or bool(self._votes)
            )
            if not self.evicted and not self.self_evicted and not pending:
                return None
            record = {
                "kind": "restore",
                "readmitted": sorted(self.evicted),
                "epoch": 0,
            }
            had_evictions = bool(self.evicted)
            self.evicted.clear()
            self.self_evicted = False
            self._plan = None
            self._votes.clear()
            self._join_votes.clear()
            self._own_vote = None
            self._own_join = None
            self._last_join = None
            self._announced = False
            self._confirmed.clear()
            self.epoch = 0
            if had_evictions:
                self.restores_total += 1
                self.history.append(record)
                if len(self.history) > _HISTORY_CAP:
                    self.history.pop(0)
        if self.board is not None:
            self.board.clear()
        if self.ledger is not None:
            self.ledger.reset()
        return dict(record)

    # -- demotion -------------------------------------------------------------
    @spmd_uniform
    def demote_decision(self, comm_id: int, world: int, seq: int,
                        slow: List[int],
                        recovered: Dict[int, bool]) -> dict:
        """The SPMD-uniform routing decision for call index ``seq``:
        derived from the EXCHANGED slow_rank verdict (shared judge) and
        latched per (comm, seq) on the shared ledger — never from local
        observation.  ``{"demoted": [...], "restored": [...],
        "root": n}``; the stock decision when no ledger is shared
        (wire tiers: verdicts are pairwise, routing stays put)."""
        if self.ledger is None or not self.elastic:
            return {"seq": seq, "demoted": [], "restored": [], "root": 0}
        return self.ledger.decide(comm_id, world, seq, slow, recovered)

    def demoted(self, comm_id: int) -> List[int]:
        """Currently-demoted ranks on ``comm_id`` (advisory view)."""
        if self.ledger is None:
            return []
        return self.ledger.demoted(comm_id)

    # -- admission ------------------------------------------------------------
    @spmd_uniform
    def join_decision(self) -> dict:
        """The latched admission-decision surface: the latest APPLIED
        join record — majority-confirmed and cutover-applied, so every
        member reads the same record (the ``demote_decision``
        discipline applied to admission; never derived from local
        observation).  The stock record when the group never grew."""
        with self._lock:
            if self._last_join is None:
                return {
                    "epoch": self.epoch, "admitted": [],
                    "world": self.world, "joins_total": 0,
                }
            return {
                "epoch": self._last_join.get("applied_epoch", self.epoch),
                "admitted": list(self._last_join.get("admit") or ()),
                "world": self._last_join.get("world", self.world),
                "joins_total": self.joins_total,
            }

    # -- telemetry ------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            doc = {
                "elastic": self.elastic,
                "epoch": self.epoch,
                "world": self.world,
                "evicted": sorted(self.evicted),
                "self_evicted": self.self_evicted,
                "pending_plan": (
                    dict(self._plan) if self._plan is not None else None
                ),
                "proposals": self.proposals,
                "petitions": self.petitions,
                "evictions_total": self.evictions_total,
                "joins_total": self.joins_total,
                "restores_total": self.restores_total,
                "last_join": (
                    dict(self._last_join)
                    if self._last_join is not None else None
                ),
                "history": [dict(h) for h in self.history],
                "exchange": "board" if self.board is not None else "wire",
            }
        if self.ledger is not None:
            doc["demotion"] = self.ledger.snapshot()
        return doc


def member_payload(data: bytes) -> Optional[dict]:
    """Decode one MEMBER wire frame's JSON payload; None on garbage (a
    corrupt-fault frame must never poison the agreement)."""
    try:
        doc = json.loads(data.decode())
    except (ValueError, UnicodeDecodeError):
        return None
    return doc if isinstance(doc, dict) else None
