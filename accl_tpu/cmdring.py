"""Command-ring host half: slot codec + the persistent-sequencer mailbox.

Role model: the reference's hostctrl path — the host writes fixed-width
commands into a hardware FIFO and reads completions from a status FIFO
while the CCLO firmware's ``run()`` loop *lives on the device*
(``ccl_offload_control.c``).  This module is everything the host owns
of that protocol, importable without jax (numpy only — the CI ring
smoke exercises it standalone):

* the **slot codec**: ``encode_slot``/``decode_slot``/``encode_window``
  pack a collective into ``CMDRING_SLOT_WORDS`` int32 words through the
  ONE layout table (:data:`accl_tpu.constants.CMDRING_FIELDS` — the
  acclint ``cmdring-slot-layout`` check keeps every reader honest);
* the **mailbox**: :class:`SequencerMailbox` is the host-visible region
  one persistent sequencer *run* drains.  A run is ONE long-running
  device program that pulls up to ``run_windows`` refill windows before
  returning; while it is live, a refill is a mailbox ``post`` (the
  doorbell becomes a memory write), NOT a program launch.  The pull
  side blocks the sequencer for at most ``linger_s`` on an empty
  mailbox, then HALTs the run so the device stream is never pinned by
  an idle sequencer (the parked posture stays no-spin *and* no-occupy).

The mailbox's decision protocol is SPMD-safe by construction: the first
rank to pull step ``s`` decides (window w / HALT) once, every other
rank's step-``s`` pull returns the identical decision — a rank can
never gather against peers that saw a different schedule.

The device half — the two sequencer lowerings that decode these slots —
lives in ``ops/pallas/cmdring.py``; the gang engine's session/refill
management in ``backends/xla/cmdring.py``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .constants import (
    CMDRING_FIELDS,
    CMDRING_FPARAM_ONE,
    CMDRING_LINGER_ENV,
    CMDRING_LINGER_MS_DEFAULT,
    CMDRING_MAX_RUN_WINDOWS,
    CMDRING_RUN_WINDOWS_DEFAULT,
    CMDRING_RUN_WINDOWS_ENV,
    CMDRING_SLOT_WORDS,
    CmdOpcode,
    FusedCompute,
    Operation,
    ReduceFunction,
)

__all__ = [
    "SequencerMailbox",
    "WindowShape",
    "complementary_pair",
    "decode_fparam",
    "decode_slot",
    "default_linger_s",
    "default_run_windows",
    "encode_fparam",
    "encode_slot",
    "encode_window",
    "fused_slot_eligible",
    "mailbox_for",
    "register_mailbox",
    "ring_widths",
    "unregister_mailbox",
]

_F = CMDRING_FIELDS  # the one layout table (constants.py)


# ---------------------------------------------------------------------------
# slot codec
# ---------------------------------------------------------------------------


def encode_slot(
    seqn: int,
    opcode: CmdOpcode,
    count: int,
    dtype: int = 0,
    function: ReduceFunction = ReduceFunction.SUM,
    root: int = 0,
    flags: int = 0,
    nseg: int = 1,
    peer: int = 0,
    wire: int = 0,
    fparam: int = 0,
) -> np.ndarray:
    """One command slot as ``(CMDRING_SLOT_WORDS,)`` int32 — every field
    written through :data:`CMDRING_FIELDS`, never a literal index.
    ``root`` doubles as the SEND/RECV source rank with ``peer`` the
    destination; ``wire`` is the compressed wire DataType (0 = none);
    ``fparam`` a fused epilogue's Q16.16 scalar (see ``encode_fparam``)."""
    words = np.zeros(CMDRING_SLOT_WORDS, np.int32)
    words[_F["seqn"]] = int(seqn) & 0x7FFFFFFF
    words[_F["opcode"]] = int(opcode)
    words[_F["count"]] = int(count)
    words[_F["dtype"]] = int(dtype)
    words[_F["function"]] = int(function)
    words[_F["root"]] = int(root)
    words[_F["flags"]] = int(flags)
    words[_F["nseg"]] = max(1, int(nseg))
    words[_F["peer"]] = int(peer)
    words[_F["wire"]] = int(wire)
    words[_F["fparam"]] = int(fparam)
    return words


def encode_fparam(x: float) -> int:
    """A fused epilogue's scalar as the Q16.16 fparam word: exact for
    the power-of-two alphas/lrs/scales that dominate training, and
    decoded identically by both lowerings (int-to-float divide — no
    float bit-pattern punning through the int32 slot plane)."""
    q = int(round(float(x) * CMDRING_FPARAM_ONE))
    return max(-(2 ** 31), min(2 ** 31 - 1, q))


def decode_fparam(word: int) -> float:
    """The host-side inverse of :func:`encode_fparam`."""
    return float(int(word)) / CMDRING_FPARAM_ONE


def decode_slot(words) -> dict:
    """The encoder's inverse (tests / debug dumps / ring introspection)."""
    w = np.asarray(words).reshape(-1)
    if w.size != CMDRING_SLOT_WORDS:
        raise ValueError(
            f"slot has {w.size} words, layout says {CMDRING_SLOT_WORDS}"
        )
    out = {name: int(w[idx]) for name, idx in _F.items()}
    out["opcode"] = CmdOpcode(out["opcode"])
    return out


def encode_window(slots: Sequence[np.ndarray], depth: int) -> np.ndarray:
    """Stack encoded slots into a ``(depth, CMDRING_SLOT_WORDS)`` window,
    NOP-padding the tail (padding slots decode to retcode OK and move no
    payload — the sequencer's idle slots)."""
    if len(slots) > depth:
        raise ValueError(f"{len(slots)} slots into a depth-{depth} window")
    rows = [np.asarray(s, np.int32).reshape(-1) for s in slots]
    while len(rows) < depth:
        rows.append(encode_slot(0, CmdOpcode.NOP, 0))
    return np.stack(rows).astype(np.int32)


def complementary_pair(calls) -> Optional[Tuple[int, int]]:
    """(src, dst) when a world-2 batch position holds a matched
    SEND/RECV pair — THE one pair definition the ring planner's slot
    eligibility and the engine's direct-delivery fallback both use (a
    divergence between them would let one path deliver what the other
    rejects).  A matched pair agrees on roles, count, tag and operand
    dtype, and carries no wire compression (compressed p2p keeps the
    channel's cast lanes).  None otherwise."""
    if len(calls) != 2:
        return None
    ops = [c.op for c in calls]
    if sorted(ops) != sorted((Operation.SEND, Operation.RECV)):
        return None
    src = ops.index(Operation.SEND)
    dst = ops.index(Operation.RECV)
    snd, rcv = calls[src], calls[dst]
    from .constants import CompressionFlags

    if (
        snd.root_dst != dst or rcv.root_src != src
        or snd.count != rcv.count or snd.tag != rcv.tag
        or snd.arithcfg.uncompressed != rcv.arithcfg.uncompressed
        or (snd.compression | rcv.compression)
        & CompressionFlags.ETH_COMPRESSED
    ):
        return None
    return src, dst


def ring_widths(
    op: Operation, count: int, size: int, fuse: int = 0
) -> Tuple[int, int]:
    """(operand width, result width) in elements for one ring slot —
    the sequencer analog of the engine's IN_W/OUT_W tables.  BARRIER
    rides a one-element token; SEND/RECV move ``count`` point-to-point.

    Fused slots pack their compute operands into the SAME operand row
    (one pull per slot — the fused epilogue never re-enters the host):

    * ``MATMUL_RS``: GEMM partials in reduce-scatter layout — the plain
      RS geometry, ``(n*size, n)``; the epilogue only scales.
    * ``APPLY``: gradients in allreduce layout plus this rank's param
      chunk riding the tail — ``(n*(size+1), n)``; the result is the
      applied param chunk, not the reduced gradient.
    * ``ATTN_HOP``: the kv block to relay plus the resident q block —
      ``(2n, n)``; the result is the scaled partial score block.

    The width RELATIONS fully determine the fused geometry: operand
    width ``out*(size+1)`` only arises for APPLY, ``2*out`` (size>2)
    only for ATTN_HOP — the sequencer lowerings classify slots by these
    relations with the opcode word selecting within a class."""
    n = int(count)
    fuse = FusedCompute(int(fuse))
    if fuse == FusedCompute.APPLY:
        return n * (size + 1), n
    if fuse == FusedCompute.ATTN_HOP:
        return 2 * n, n
    if op in (Operation.REDUCE_SCATTER, Operation.ALLTOALL):
        in_w = n * size
    elif op == Operation.BARRIER:
        in_w = 1
    else:
        in_w = n
    if op in (Operation.ALLGATHER, Operation.ALLTOALL):
        out_w = n * size
    elif op == Operation.BARRIER:
        out_w = 1
    else:
        out_w = n
    return in_w, out_w


#: FusedCompute -> the base Operation its call rides (the engine plans
#: the collective half with this op; the fuse hint selects the epilogue)
FUSED_BASE_OPS = {
    FusedCompute.MATMUL_RS: Operation.REDUCE_SCATTER,
    FusedCompute.APPLY: Operation.ALLREDUCE,
    FusedCompute.ATTN_HOP: Operation.ALLREDUCE,
}


def fused_slot_eligible(
    fuse: int,
    op: Operation,
    size: int,
    count: int,
    operand_count: int,
    npdt,
    compressed: bool = False,
) -> Optional[str]:
    """Why a fused call CANNOT ride a ring slot (None = eligible) — the
    ONE fused-eligibility predicate, numpy-only so the CI ring smoke
    gates it without jax and the engine planner counts the same reasons.

    Fused epilogues are float arithmetic fused into the relay: they need
    a real ring (size >= 2), a float operand, the fuse's base operation,
    an operand row packed to exactly the fused width, and no wire
    compression (the epilogue would otherwise run on lossy-cast chunks
    the plain path never produces)."""
    try:
        fuse = FusedCompute(int(fuse))
    except ValueError:
        return "unknown_fuse"
    if fuse == FusedCompute.NONE:
        return None
    base = FUSED_BASE_OPS.get(fuse)
    if base is None or op != base:
        return "fused_base_op"
    if int(size) < 2:
        return "fused_world_too_small"
    if np.dtype(npdt).kind != "f":
        return "fused_dtype"
    in_w, _ = ring_widths(base, count, size, fuse=fuse)
    if int(operand_count) != in_w:
        return "fused_operand_width"
    if compressed:
        return "fused_compressed"
    return None


# ---------------------------------------------------------------------------
# persistent-sequencer knobs
# ---------------------------------------------------------------------------


def default_run_windows() -> int:
    """Refill windows one sequencer run drains before returning (the
    ``fori``/scan bound of the mega-window program)."""
    try:
        n = int(
            os.environ.get(
                CMDRING_RUN_WINDOWS_ENV, CMDRING_RUN_WINDOWS_DEFAULT
            )
        )
    except ValueError:
        n = CMDRING_RUN_WINDOWS_DEFAULT
    return max(1, min(n, CMDRING_MAX_RUN_WINDOWS))


def default_linger_s() -> float:
    """How long a live run waits on an empty mailbox before halting.
    Small on purpose: a lingering sequencer occupies the device stream,
    so anything else dispatched to the mesh pays at most this bound."""
    try:
        ms = float(
            os.environ.get(CMDRING_LINGER_ENV, CMDRING_LINGER_MS_DEFAULT)
        )
    except ValueError:
        ms = CMDRING_LINGER_MS_DEFAULT
    return max(0.0, ms) / 1e3


# ---------------------------------------------------------------------------
# the mailbox
# ---------------------------------------------------------------------------


class WindowShape:
    """Static shape signature of a refill window — everything that keys
    the sequencer program's compile cache.  Slot CONTENT (opcode, reduce
    function, root, peer, seqn) stays data; only the payload geometry
    and the per-slot wire-cast dtypes are shape."""

    __slots__ = ("depth", "in_ws", "out_ws", "wires", "npdt")

    def __init__(self, depth: int, in_ws, out_ws, wires, npdt):
        self.depth = int(depth)
        self.in_ws = tuple(int(w) for w in in_ws)
        self.out_ws = tuple(int(w) for w in out_ws)
        self.wires = tuple(wires)  # numpy dtype name or None, per slot
        self.npdt = np.dtype(npdt)

    def key(self) -> tuple:
        return (self.depth, self.in_ws, self.out_ws, self.wires,
                self.npdt.name)

    def __eq__(self, other) -> bool:
        return isinstance(other, WindowShape) and self.key() == other.key()

    def __hash__(self) -> int:
        return hash(self.key())


class _PostedWindow:
    __slots__ = ("window_id", "slots", "payload", "status", "results",
                 "pushed")

    def __init__(self, window_id: int, slots: np.ndarray, payload):
        self.window_id = window_id
        self.slots = np.asarray(slots, np.int32)
        # payload[i][r]: rank r's operand row for slot i (a VIEW of the
        # committed immutable device array — snapshot semantics with no
        # copy; None rows pull as zeros), or payload[i] a (size, w)
        # array (the smoke/test convenience form)
        self.payload = payload
        self.status: Optional[np.ndarray] = None
        self.results: Dict[int, List[np.ndarray]] = {}  # rank -> per slot
        self.pushed = 0


class SequencerMailbox:
    """One sequencer run's host-visible mailbox (command FIFO in, status
    FIFO out).  ``pull(rank)`` is the device program's per-step window
    fetch; ``post`` the host's refill; ``push(rank, ...)`` the device's
    per-step status/result writeback.  ``on_window_done(window_id,
    status, results)`` fires — outside every mailbox lock — when all
    ranks pushed a window's step."""

    def __init__(self, size: int, shape: WindowShape,
                 run_windows: Optional[int] = None,
                 linger_s: Optional[float] = None,
                 on_window_done: Optional[Callable] = None):
        self.size = int(size)
        self.shape = shape
        self.run_windows = (
            run_windows if run_windows is not None else default_run_windows()
        )
        self.linger_s = (
            linger_s if linger_s is not None else default_linger_s()
        )
        self.on_window_done = on_window_done
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: List[_PostedWindow] = []
        self._decisions: List[Optional[_PostedWindow]] = []  # None = HALT
        self._pull_cursor = [0] * self.size
        self._push_cursor = [0] * self.size
        self._halt_seen = [False] * self.size
        self._accepted = 0
        self._halted = False
        self.drained = threading.Event()  # every rank pulled a HALT
        # per-window host-side timing (the introspection basis where
        # the lowering can't write device timestamps next to the
        # status words — labeled "host" honestly in every surface):
        # posted_ns (refill doorbell), pulled_ns (first rank's fetch —
        # the device-side dequeue point), pushed_ns (last rank's
        # status writeback).  Bounded: entries are pruned once read by
        # the session's window log.
        self._timings: Dict[int, Dict[str, int]] = {}

    # -- introspection -------------------------------------------------------
    def depth(self) -> int:
        """Queued refill windows not yet pulled (the mailbox-depth
        gauge: how far the host runs ahead of the sequencer)."""
        with self._lock:
            return len(self._queue)

    def take_timing(self, window_id: int) -> Optional[Dict[str, int]]:
        """The window's host-side timing record, removed (the window
        log consumes it exactly once)."""
        with self._lock:
            return self._timings.pop(int(window_id), None)

    # -- host side -----------------------------------------------------------
    def post(self, window_id: int, slots: np.ndarray, payload) -> bool:
        """Queue one refill window.  False when this run can no longer
        take it (halted, or its window budget is spent) — the caller
        must dispatch a fresh run instead."""
        with self._cv:
            if self._halted or self._accepted >= self.run_windows:
                return False
            self._accepted += 1
            self._queue.append(_PostedWindow(window_id, slots, payload))
            self._timings[int(window_id)] = {
                "posted_ns": time.perf_counter_ns()
            }
            if len(self._timings) > 4 * self.run_windows:
                for k in sorted(self._timings)[: -2 * self.run_windows]:
                    del self._timings[k]
            self._cv.notify_all()
            return True

    def halt(self) -> None:
        """Teardown doorbell (soft_reset / engine shutdown / shape
        change): stop accepting posts and let the run drain its backlog,
        then return.  Queued windows still execute — their requests are
        already parked."""
        with self._cv:
            self._halted = True
            self._cv.notify_all()

    @property
    def accepting(self) -> bool:
        with self._lock:
            return not self._halted and self._accepted < self.run_windows

    # -- device side (io_callback targets; XLA runtime threads) --------------
    def pull(self, rank: int):
        """Step decision + window fetch for one rank.  Returns
        ``(live, slots, payload_rows)`` with ``live=0`` zeros on a HALT
        step.  The first rank to reach a step decides it (bounded by
        ``linger_s`` on an empty queue); everyone else reads the same
        decision."""
        r = int(rank)
        with self._cv:
            step = self._pull_cursor[r]
            self._pull_cursor[r] += 1
            while len(self._decisions) <= step:
                if self._queue:
                    nxt = self._queue.pop(0)
                    t = self._timings.get(nxt.window_id)
                    if t is not None and "pulled_ns" not in t:
                        # the device-side dequeue point (host clock —
                        # the pull trampoline runs on the host)
                        t["pulled_ns"] = time.perf_counter_ns()
                    self._decisions.append(nxt)
                    break
                if self._halted:
                    self._decisions.append(None)
                    break
                # bounded linger, measured fresh per step: an idle
                # sequencer must hand the device stream back promptly
                deadline = time.monotonic() + self.linger_s
                decided = len(self._decisions)
                while (
                    not self._queue
                    and not self._halted
                    and len(self._decisions) == decided
                ):
                    rem = deadline - time.monotonic()
                    if rem <= 0:
                        break
                    self._cv.wait(min(rem, 0.05))
                if (
                    not self._queue
                    and not self._halted
                    and len(self._decisions) == decided
                ):
                    self._halted = True  # linger expired: park the run
                self._cv.notify_all()
            win = self._decisions[step]
            if win is None:
                # the run-loop exit: this rank saw the HALT; once every
                # rank has, the program has returned the device stream
                self._halt_seen[r] = True
                if all(self._halt_seen):
                    self.drained.set()
                self._cv.notify_all()
        if win is None:
            return self._halt_payload(r)
        sh = self.shape
        rows = []
        for i, p in enumerate(win.payload):
            row = p[r] if p is not None else None
            if row is None:
                row = np.zeros((sh.in_ws[i],), sh.npdt)
            rows.append(row)
        return (np.int32(1), win.slots, rows)

    def _halt_payload(self, rank: int):
        sh = self.shape
        return (
            np.int32(0),
            np.zeros((sh.depth, CMDRING_SLOT_WORDS), np.int32),
            [np.zeros((w,), sh.npdt) for w in sh.in_ws],
        )

    def push(self, rank: int, live: int, status: np.ndarray,
             outs: List[np.ndarray]) -> None:
        """Per-step status/result writeback from one rank.  Completion
        callbacks fire outside the lock once every rank pushed."""
        r = int(rank)
        done = None
        with self._cv:
            step = self._push_cursor[r]
            self._push_cursor[r] += 1
            win = (
                self._decisions[step]
                if step < len(self._decisions) else None
            )
            if win is not None and int(live):
                win.results[r] = [np.asarray(o) for o in outs]
                if win.status is None:
                    win.status = np.asarray(status, np.int32).copy()
                win.pushed += 1
                if win.pushed == self.size:
                    done = win
                    t = self._timings.get(win.window_id)
                    if t is not None:
                        t["pushed_ns"] = time.perf_counter_ns()
            self._cv.notify_all()
        if done is not None and self.on_window_done is not None:
            self.on_window_done(done.window_id, done.status, done.results)


# ---------------------------------------------------------------------------
# mailbox registry (the device program addresses its mailbox by id, so
# one compiled program serves every run of its shape — the callback
# trampolines in ops/pallas/cmdring.py dispatch through here)
# ---------------------------------------------------------------------------

_REGISTRY: Dict[int, SequencerMailbox] = {}
_REGISTRY_LOCK = threading.Lock()
_NEXT_ID = [1]


def register_mailbox(mbox: SequencerMailbox) -> int:
    with _REGISTRY_LOCK:
        mid = _NEXT_ID[0]
        _NEXT_ID[0] += 1
        _REGISTRY[mid] = mbox
        return mid


def mailbox_for(mid: int) -> Optional[SequencerMailbox]:
    with _REGISTRY_LOCK:
        return _REGISTRY.get(int(mid))


def unregister_mailbox(mid: int) -> None:
    with _REGISTRY_LOCK:
        _REGISTRY.pop(int(mid), None)
