"""Communicators: ordered groups of ranks with per-peer session state.

Role model: ``driver/xrt/include/accl/communicator.hpp`` — ``rank_t`` {ip,
port, session_id, max_segment_size} (:34-39) and the ``Communicator`` that
maintains per-rank inbound/outbound sequence numbers (:46-95).  TPU-natively
the "address" of a rank is transport-specific: an in-process engine id on the
emulator tier, a host:port on the socket tier, a (process, device) coordinate
on the ICI tier — so ``Rank.address`` is an opaque string and the engine's
transport resolves it.

Multiple communicators may exist over overlapping rank sets
(``ACCL::create_communicator``, split semantics tested by the reference's
``test_multicomm``); wire messages are scoped by the communicator id so
traffic in different communicators never cross-matches.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
from typing import Dict, List, Optional, Sequence

from .constants import DEFAULT_RX_BUFFER_SIZE


@dataclasses.dataclass
class Rank:
    address: str  # transport-specific endpoint for this rank
    session: int = 0  # stable per-peer session id
    max_segment_size: int = DEFAULT_RX_BUFFER_SIZE


_comm_ids = itertools.count(0)
# Epochs discriminate message seqn spaces between INSTANCES sharing a
# deterministic comm id (create_communicator derives ids from membership,
# so re-creating the same subgroup reuses the id while its sequence
# counters restart at 0 — without the epoch, receiver-side dedup would
# discard the fresh instance's traffic as duplicates).  Only uniqueness
# per (sender process, comm id) matters, so a process-local counter works
# across the socket tier too.
_comm_epochs = itertools.count(1)


class Communicator:
    def __init__(
        self,
        ranks: Sequence[Rank],
        local_rank: int,
        comm_id: Optional[int] = None,
    ):
        if not 0 <= local_rank < len(ranks):
            raise ValueError(f"local_rank {local_rank} out of range")
        self.ranks: List[Rank] = list(ranks)
        self.local_rank = int(local_rank)
        self.id = next(_comm_ids) if comm_id is None else comm_id
        self.epoch = next(_comm_epochs)
        self._lock = threading.Lock()
        # Per-peer monotone sequence numbers: ordering for eager matching.
        # (ref: inbound_seq/outbound_seq words in the exchange-memory comm
        # table, communicator.hpp:34-39, maintained by dma_mover.cpp:581-658.)
        self._outbound_seq: Dict[int, int] = {i: 0 for i in range(len(ranks))}
        self._inbound_seq: Dict[int, int] = {i: 0 for i in range(len(ranks))}
        # membership plane (accl_tpu.membership): the pre-shrink
        # membership stashed by shrink() so soft_reset can restore it
        self._full_ranks: Optional[List[Rank]] = None
        self._full_local: Optional[int] = None
        # topology plane (accl_tpu.topology): slice/link-class
        # descriptor in THIS communicator's rank space, or None (flat).
        # Attached by the facade at construction / set_topology and
        # derived through split/shrink/grow so a subcomm's link classes
        # stay truthful; _full_topology mirrors _full_ranks for the
        # restore path.
        self.topology = None
        self._full_topology = None

    # -- introspection ------------------------------------------------------
    @property
    def size(self) -> int:
        return len(self.ranks)

    def rank(self) -> int:
        return self.local_rank

    def prev_rank(self, distance: int = 1) -> int:
        return (self.local_rank - distance) % self.size

    def next_rank(self, distance: int = 1) -> int:
        return (self.local_rank + distance) % self.size

    # -- sequence numbers ---------------------------------------------------
    def next_outbound_seq(self, peer: int) -> int:
        with self._lock:
            seq = self._outbound_seq[peer]
            self._outbound_seq[peer] = seq + 1
            return seq

    def peek_inbound_seq(self, peer: int) -> int:
        with self._lock:
            return self._inbound_seq[peer]

    def advance_inbound_seq(self, peer: int) -> None:
        with self._lock:
            self._inbound_seq[peer] += 1

    def reset_sequences(self) -> None:
        """Zero every per-peer sequence counter (soft-reset recovery: after
        a faulted collective dropped messages, peers' counters disagree —
        every member resets so eager matching realigns)."""
        with self._lock:
            self.epoch = next(_comm_epochs)  # fresh seqn space
            for i in self._outbound_seq:
                self._outbound_seq[i] = 0
                self._inbound_seq[i] = 0

    # -- membership plane (accl_tpu.membership) ------------------------------
    def shrink(self, keep: Sequence[int]) -> Optional[Dict[int, int]]:
        """Cut this communicator over IN PLACE to the surviving members
        (``keep``: comm-relative ranks, ascending, local rank included)
        — the elastic-membership cutover.  A fresh epoch starts (plan
        caches and seqn dedup re-key instead of silently mis-bucketing)
        and every per-peer sequence counter restarts at 0, like the
        soft-reset realignment.  Returns the survivor-visible
        translation table ``{old comm-relative rank -> new}`` so
        callers re-key rank-indexed state; None when the local rank is
        not among the survivors (the evicted side never shrinks — it is
        out of the group entirely)."""
        keep = sorted(set(int(k) for k in keep))
        for k in keep:
            if not 0 <= k < self.size:
                raise ValueError(f"survivor rank {k} out of range")
        with self._lock:
            if self.local_rank not in keep:
                return None
            if self._full_ranks is None:
                self._full_ranks = list(self.ranks)
                self._full_local = self.local_rank
            translation = {old: new for new, old in enumerate(keep)}
            self.ranks = [self.ranks[k] for k in keep]
            self.local_rank = translation[self.local_rank]
            if self.topology is not None:
                if self._full_topology is None:
                    self._full_topology = self.topology
                self.topology = self.topology.subtopology(keep)
            self.epoch = next(_comm_epochs)
            self._outbound_seq = {i: 0 for i in range(len(self.ranks))}
            self._inbound_seq = {i: 0 for i in range(len(self.ranks))}
            return translation

    def grow(
        self, admit: Sequence[int],
        rank_info: Optional[Dict[int, Rank]] = None,
    ) -> Optional[Dict[int, int]]:
        """Cut this communicator over IN PLACE to a GROWN membership —
        the elastic-expansion cutover (the :meth:`shrink` discipline,
        other direction).  ``admit`` are world *sessions* to admit.
        Sessions known from the pre-shrink membership return to their
        ORIGINAL world slots (the ``_full_ranks`` ordering rule — every
        member derives the same post-join rank order without exchanging
        it); genuinely new sessions need a :class:`Rank` in
        ``rank_info`` and append in ascending session order.  A fresh
        epoch starts (plan caches and seqn dedup re-key — the admitted
        rank's PREVIOUS life, if it had one, can never cross-match) and
        every per-peer sequence counter restarts at 0.  Returns the
        translation table ``{old comm-relative rank -> new}``; an
        ``admit`` of sessions already present (the candidate's own
        re-key at admission) yields the identity translation with the
        fresh epoch."""
        admit = {int(s) for s in admit}
        with self._lock:
            base = (
                list(self._full_ranks) if self._full_ranks is not None
                else list(self.ranks)
            )
            current = {r.session for r in self.ranks}
            target = current | admit
            known = {r.session for r in base}
            extras = []
            for s in sorted(admit - known):
                if rank_info is None or s not in rank_info:
                    raise ValueError(
                        f"admitted session {s} unknown to this "
                        "communicator and no rank_info given"
                    )
                extras.append(rank_info[s])
            new_ranks = [r for r in base if r.session in target] + extras
            old_index = {r.session: i for i, r in enumerate(self.ranks)}
            translation = {
                old_index[r.session]: new
                for new, r in enumerate(new_ranks)
                if r.session in old_index
            }
            local_session = self.ranks[self.local_rank].session
            if self.topology is not None:
                # surviving members keep their slice through the
                # translation; admitted ranks land in singleton slices
                # (conservative DCN classification — a joiner's real
                # placement is unknown until re-described via
                # set_topology, and DCN can only over-pay, never
                # corrupt a fast-link assumption)
                if self._full_topology is None:
                    self._full_topology = self.topology
                from .topology import Topology as _Topology

                subs = [
                    [translation[r] for r in s if r in translation]
                    for s in self.topology.slices
                ]
                subs = [s for s in subs if s]
                covered = {r for s in subs for r in s}
                subs += [
                    [i] for i in range(len(new_ranks)) if i not in covered
                ]
                self.topology = _Topology(subs)
            self.ranks = new_ranks
            self.local_rank = next(
                i for i, r in enumerate(new_ranks)
                if r.session == local_session
            )
            if self._full_ranks is not None and len(new_ranks) >= len(
                self._full_ranks
            ) and known <= {r.session for r in new_ranks}:
                # grown back to (at least) the stashed membership: the
                # shrink is fully undone and soft_reset has nothing to
                # re-admit
                self._full_ranks = None
                self._full_local = None
            self.epoch = next(_comm_epochs)
            self._outbound_seq = {i: 0 for i in range(len(self.ranks))}
            self._inbound_seq = {i: 0 for i in range(len(self.ranks))}
            return translation

    def restore(self) -> bool:
        """Undo every shrink: re-admit the full pre-shrink membership
        (the soft_reset recovery path, collective by contract like the
        reset itself).  Fresh epoch + zeroed sequence counters; False
        when the communicator never shrank."""
        with self._lock:
            if self._full_ranks is None:
                return False
            self.ranks = list(self._full_ranks)
            self.local_rank = int(self._full_local)
            self._full_ranks = None
            self._full_local = None
            if self._full_topology is not None:
                self.topology = self._full_topology
                self._full_topology = None
            self.epoch = next(_comm_epochs)
            self._outbound_seq = {i: 0 for i in range(len(self.ranks))}
            self._inbound_seq = {i: 0 for i in range(len(self.ranks))}
            return True

    @property
    def shrunk(self) -> bool:
        return self._full_ranks is not None

    # -- derivation ---------------------------------------------------------
    def split(
        self, members: Sequence[int], comm_id: Optional[int] = None
    ) -> Optional["Communicator"]:
        """New communicator over a subset of this one's ranks.

        ``members`` are rank indices *in this communicator*, in the order they
        should appear in the new one.  Returns None if the local rank is not a
        member (matching MPI_Comm_split semantics the reference's multi-comm
        tests exercise).
        """
        members = list(members)
        if len(set(members)) != len(members):
            raise ValueError("duplicate members in communicator split")
        for m in members:
            if not 0 <= m < self.size:
                raise ValueError(f"member {m} out of range")
        if self.local_rank not in members:
            return None
        new_ranks = [self.ranks[m] for m in members]
        sub = Communicator(
            new_ranks, members.index(self.local_rank), comm_id=comm_id
        )
        if self.topology is not None:
            # the subcomm inherits truthful link classes: member m of the
            # parent becomes rank members.index(m) of the child, and
            # subtopology() maps slices through exactly that ordering
            sub.topology = self.topology.subtopology(members)
        return sub

    # -- debug --------------------------------------------------------------
    def as_dict(self) -> dict:
        """Structured form of :meth:`dump` (the telemetry plane's
        ``dump_communicator(as_dict=True)`` source; the legacy string is
        rendered from this dict)."""
        with self._lock:
            return {
                "id": self.id,
                "epoch": self.epoch,
                "size": self.size,
                "local_rank": self.local_rank,
                "topology": (
                    None if self.topology is None
                    else self.topology.signature()
                ),
                "ranks": [
                    {
                        "address": r.address,
                        "session": r.session,
                        "max_segment_size": r.max_segment_size,
                        "seq_out": self._outbound_seq[i],
                        "seq_in": self._inbound_seq[i],
                    }
                    for i, r in enumerate(self.ranks)
                ],
            }

    def dump(self) -> str:
        lines = [f"communicator {self.id}: size={self.size} local={self.local_rank}"]
        with self._lock:
            for i, r in enumerate(self.ranks):
                lines.append(
                    f"  rank {i}: addr={r.address} session={r.session} "
                    f"seg={r.max_segment_size} "
                    f"seq_out={self._outbound_seq[i]} seq_in={self._inbound_seq[i]}"
                )
        return "\n".join(lines)
