"""Hierarchical collective decomposition rules (pure math, no wire).

Role model: two-level collectives on two-tier networks (NCCL's
inter/intra-node trees, Horovod's hierarchical allreduce, the
reference's algorithm registers picking flat-vs-tree per network).
Given a :class:`~accl_tpu.topology.Topology`, every facade collective
that can decompose does so into sub-collectives on derived subcomms so
the slow DCN carries ``1/slice_size`` of the bytes a flat ring pushes
across it:

* **allreduce, rail mode** (symmetric topology, count divisible by the
  slice size): reduce-scatter within the slice (ICI) -> allreduce over
  the *rail* — the ranks holding the same chunk in every slice (DCN,
  count/S elements) -> allgather within the slice (ICI).  The rail is
  the per-chunk generalization of "cross-slice allreduce over slice
  leaders": after the intra reduce-scatter, chunk i's owners ARE the
  leaders for chunk i.
* **allreduce, leader mode** (anything else): reduce to the slice
  leader (ICI) -> allreduce over the leaders (DCN, full count) ->
  bcast within the slice (ICI).
* **allgather** (symmetric + contiguous): intra allgather -> rail
  allgather; contiguity makes the rail's slice-major placement equal
  the flat rank-major placement.
* **reduce_scatter** (symmetric + contiguous): permute send blocks
  (:func:`reduce_scatter_permutation`) -> intra reduce-scatter over
  L*n-element blocks -> rail reduce-scatter over n-element blocks;
  the permutation routes chunk ``s*S + i`` through intra block i /
  rail block s so every rank lands exactly its own chunk.
* **bcast** (any multi-slice topology): bcast over one representative
  per slice — the root for its own slice, the leader elsewhere
  (:func:`bcast_representatives`) — then bcast within each slice from
  its representative.

Every decision here is a function of (topology, op, count) only — all
SPMD-uniform facts — so every rank of a communicator picks the same
decomposition with zero wire bytes; the facade additionally
fingerprints the decomposed call on the PARENT communicator (op name
``"<op>.hier"``), so a flat-vs-hierarchical skew convicts within one
contract verify window like any other sequence divergence.

Jax- and numpy-free (analysis ``jax-free-module`` enforced): the
numpy-only CI smoke drives these rules directly.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .topology import Topology

__all__ = [
    "HIER_OPS",
    "allreduce_mode",
    "bcast_eligible",
    "bcast_representatives",
    "eligible",
    "gatherlike_eligible",
    "multi_slice",
    "reduce_scatter_permutation",
]

#: facade collectives with a hierarchical decomposition (lower-case op
#: names — the register/plan vocabulary)
HIER_OPS = ("allreduce", "allgather", "reduce_scatter", "bcast")


def multi_slice(topo: Optional[Topology]) -> bool:
    """The baseline eligibility every decomposition shares: at least
    two slices AND at least one slice with two members.  All-singleton
    slices (a pure-DCN comm — e.g. a rail subcomm) must NOT decompose:
    the decomposition would recurse into an identical call."""
    return (
        topo is not None
        and topo.num_slices >= 2
        and topo.world > topo.num_slices
    )


def allreduce_mode(topo: Optional[Topology],
                   count: int) -> Optional[str]:
    """``"rail"`` / ``"leader"`` / None (stay flat).  Rail needs every
    slice the same size and the count divisible by it (the intra
    reduce-scatter hands each rank an equal chunk); leader mode covers
    every other multi-slice shape at full-count DCN cost."""
    if not multi_slice(topo):
        return None
    if topo.symmetric and count > 0 and count % len(topo.slices[0]) == 0:
        return "rail"
    return "leader"


def gatherlike_eligible(topo: Optional[Topology]) -> bool:
    """allgather / reduce_scatter eligibility: the rail stage places
    blocks slice-major, which equals the flat rank-major placement only
    when slices are contiguous ascending runs of equal size."""
    return bool(multi_slice(topo) and topo.symmetric and topo.contiguous)


def bcast_eligible(topo: Optional[Topology]) -> bool:
    """bcast decomposes on any multi-slice topology (representatives
    need no symmetry or contiguity)."""
    return multi_slice(topo)


def eligible(op: str, topo: Optional[Topology], count: int) -> bool:
    """One predicate over (op name, topology, count) — the callable
    the facade and the autotuner share, so a raced ``hierarchical``
    register can only arm decompositions that exist."""
    if op == "allreduce":
        return allreduce_mode(topo, count) is not None
    if op in ("allgather", "reduce_scatter"):
        return gatherlike_eligible(topo)
    if op == "bcast":
        return bcast_eligible(topo)
    return False


def bcast_representatives(topo: Topology, root: int) -> List[int]:
    """One rank per slice for the cross-slice bcast stage: the ROOT
    for its own slice (no extra hop — the root already holds the
    payload), the slice leader elsewhere.  Sorted ascending: every
    rank derives the same member list, and the cross subcomm's rank
    order is reproducible."""
    rs = topo.slice_of(root)
    reps = [
        int(root) if si == rs else s[0]
        for si, s in enumerate(topo.slices)
    ]
    return sorted(reps)


def reduce_scatter_permutation(topo: Topology) -> List[int]:
    """Block permutation staging a hierarchical reduce-scatter.

    With L contiguous slices of size S (world W = L*S, flat chunk c
    belongs to global rank c), the staged send buffer orders the W
    per-rank blocks as::

        [ s*S + i  for i in range(S) for s in range(L) ]

    so intra block i (L consecutive blocks) carries the chunks of
    local index i across ALL slices.  The intra reduce-scatter (count
    L*n) then hands rank (s, i) the slice-partial sums of those L
    chunks; the rail reduce-scatter (count n) hands it block s of that
    — the fully-reduced chunk of global rank ``s*S + i``, exactly the
    flat result."""
    if not (topo.symmetric and topo.contiguous):
        raise ValueError(
            "reduce_scatter staging needs a symmetric contiguous "
            "topology"
        )
    L, S = topo.num_slices, len(topo.slices[0])
    return [s * S + i for i in range(S) for s in range(L)]
