"""vadd_put: a device kernel commanding the collective engine directly.

Role model: ``kernels/plugins/vadd_put/vadd_put.cpp:25-100`` + the HLS
bindings (``driver/hls/accl_hls.h``) — an FPGA compute kernel reads fp32,
adds a constant, streams the result into the CCLO and issues ``stream_put``
to a remote rank with NO host in the data path.

TPU-natively the "device kernel" is a jitted function and the stream port
is the engine's kernel-facing FIFO: compute happens under jit (on the
accelerator), the result is pushed into the local stream port, and the
engine forwards it to the destination's port — the host never touches the
payload between compute and wire."""

from __future__ import annotations

import numpy as np

import jax

from ..compat import install as _compat_install

_compat_install()  # legacy-jax shims (shard_map kwargs, lax.axis_size)
import jax.numpy as jnp

from ..backends.base import CallOptions
from ..constants import DataType, Operation, StreamFlags


@jax.jit
def _vadd(x: jax.Array, increment: float) -> jax.Array:
    return x + increment


def vadd_put(
    accl,
    data: np.ndarray,
    dst: int,
    stream_id: int = 0,
    increment: float = 1.0,
) -> None:
    """Compute x+increment on device, push into the local stream port, then
    send from the port to ``dst``'s tag-matched receive (OP0_STREAM path)."""
    out = np.asarray(_vadd(jnp.asarray(data, jnp.float32), increment))
    accl.stream_push(out, stream_id=stream_id)
    accl.send(
        None, out.size, dst=dst, tag=stream_id, from_stream=True,
        stream_id=stream_id,
    )


def vadd_put_streamed(
    accl,
    data: np.ndarray,
    dst: int,
    stream_id: int = 0,
    increment: float = 1.0,
) -> None:
    """Full device-to-device variant: operand from the local stream port AND
    delivery into the remote stream port (OP0_STREAM | RES_STREAM) — no
    tag-matched buffer anywhere, the exact vadd_put flow."""
    out = np.asarray(_vadd(jnp.asarray(data, jnp.float32), increment))
    accl.stream_push(out, stream_id=stream_id)
    cfg, flags = accl._resolve_arithcfg(DataType.FLOAT32, None)
    opts = CallOptions(
        op=Operation.SEND,
        comm=accl.comm,
        count=out.size,
        root_dst=dst,
        tag=stream_id,
        arithcfg=cfg,
        compression=flags,
        stream=StreamFlags.OP0_STREAM | StreamFlags.RES_STREAM,
        stream_id=stream_id,
    )
    accl._launch(opts, False, "vadd_put_streamed")


def vadd_put_pallas(stacked, mesh, increment: float = 1.0, distance: int = 1):
    """The fully-fused variant: compute AND wire in ONE Mosaic kernel.

    Where :func:`vadd_put` computes under jit and hands the result to the
    engine's stream port, this form is the exact analog of the FPGA flow —
    a single device kernel (``ops.pallas.fused_shift``) computes
    ``x + increment`` in VMEM and itself issues the remote DMA to the
    neighbor ``distance`` away, host and XLA collective scheduler both out
    of the data path.  ``stacked[r]`` is rank r's operand; returns stacked
    results (row r = what rank r received)."""
    try:
        from jax import shard_map
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..ops.driver import AXIS
    from ..ops.pallas import fused_shift

    fn = jax.jit(
        shard_map(
            lambda x: fused_shift(
                x[0], AXIS, distance, lambda v: v + increment
            )[None],
            mesh=mesh,
            in_specs=P(AXIS),
            out_specs=P(AXIS),
            check_vma=False,
        )
    )
    return fn(jnp.asarray(stacked, jnp.float32))
