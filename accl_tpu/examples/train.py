"""End-to-end distributed training with checkpoint/resume.

The flagship loop: the dp x tp transformer training step (every
cross-device edge an accl_tpu collective) driven over a mesh, with
orbax-backed checkpointing — save on an interval, resume after a restart.
The reference has no checkpoint/resume at all (SURVEY.md §5: "none —
library, not trainer"); this closes that aux-subsystem gap for the
framework's trainer surface.

Runnable anywhere:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python -m accl_tpu.examples.train --steps 20 --ckpt-dir /tmp/ckpt

Re-running the same command resumes from the last saved step.
"""

from __future__ import annotations

import argparse
import os
from typing import Optional

import numpy as np


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def train(
    steps: int = 20,
    ckpt_dir: Optional[str] = None,
    save_every: int = 10,
    dp: Optional[int] = None,
    tp: int = 2,
    seed: int = 0,
    log_every: int = 5,
    platform: Optional[str] = None,
    optimizer: str = "sgd",
    parallelism: str = "dp_tp",
    data: Optional[str] = None,
    accum_steps: int = 1,
    clip_grad_norm: Optional[float] = None,
    master_weights: bool = False,
    dtype: str = "float32",
    n_experts: int = 0,
    ep: int = 1,
    v_stages: int = 1,
    pp_schedule: str = "gpipe",
):
    """Train the flagship transformer.

    ``data`` points at an ``ACCLTOK1`` token file (see
    ``accl_tpu.data.write_token_file``): batches then come from the
    native prefetching loader — deterministic per (file, seed, step), so
    checkpoint resume consumes the exact stream an uninterrupted run
    would (the loader seeks to the resumed step).  Without ``data``,
    synthetic random tokens keyed by (seed, step) keep the same
    resume-exactness property.

    ``optimizer="zero_adam"`` switches the step to the ZeRO-sharded Adam
    (fp32 moments living 1/dp per chip, ``parallel/zero.py``); its
    optimizer state checkpoints and resumes alongside the params.
    ``accum_steps``/``clip_grad_norm``/``master_weights`` (zero_adam
    only) enable gradient accumulation, global-L2-norm clipping, and the
    fp32 master-weight track; ``dtype="bfloat16"`` trains bf16 params
    (pair with master_weights — bf16's ulp otherwise swallows small
    updates).

    ``parallelism="context"`` trains with context parallelism: the tp
    axis becomes the sequence ring (striped ring attention inside the
    blocks, activations sequence-sharded end-to-end).

    ``n_experts`` switches every block's FFN to the expert-parallel MoE
    (router aux in the loss).  Experts ride dp by default; ``ep > 1``
    un-welds them onto a DEDICATED expert axis of a (dp, ep, tp) mesh
    (the batch shards over dp x ep).  MoE composes with
    parallelism="context" (long-context MoE: expert a2a + K/V ring on
    different axes) but not with "pipeline".

    ``parallelism="pipeline"`` trains over the composed pp x dp x tp mesh
    (``models/composed.py``: pipeline stages of tp-sharded blocks,
    microbatched dp-sharded batch — pp=2, microbatches=2); params
    checkpoint in stacked form.  Composes with ``optimizer="zero_adam"``
    (ZeRO-1 moments nested inside the stage sharding, clipping and
    master weights included).  ``v_stages > 1`` switches to
    the interleaved virtual-stage schedule (that many round-robin layer
    chunks per pp rank, 1/v_stages the pipeline bubble; the model grows
    to 2 * v_stages layers so every chunk holds a layer, and checkpoints
    are layout-compatible only with the same --v-stages).

    Returns ``(steps_completed, final_loss)``; ``final_loss`` is ``None``
    when a restored checkpoint already covers the requested ``steps``
    (nothing ran)."""
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from ..models import (
        TransformerConfig,
        init_params,
        make_sharded_train_step,
    )
    from ..parallel import AdamConfig, make_zero_train_step

    devs = jax.devices()
    use_pp = parallelism == "pipeline"
    if parallelism not in ("dp_tp", "context", "pipeline"):
        raise ValueError(f"unknown parallelism {parallelism!r}")
    if use_pp and accum_steps != 1:
        raise ValueError(
            "parallelism='pipeline' accumulates through its "
            "microbatches; accum_steps is a dp_tp/context knob"
        )
    if (
        accum_steps != 1 or clip_grad_norm is not None or master_weights
    ) and optimizer != "zero_adam":
        raise ValueError(
            "accum_steps/clip_grad_norm/master_weights require "
            "optimizer='zero_adam'"
        )
    if dtype not in ("float32", "bfloat16"):
        raise ValueError(f"unknown dtype {dtype!r}")
    pp = 2 if use_pp else 1
    if use_pp and len(devs) < 2:
        raise ValueError(
            "parallelism='pipeline' needs >= 2 devices (pp=2); this host "
            f"exposes {len(devs)}"
        )
    if ep > 1 and not n_experts:
        raise ValueError("--ep > 1 requires --n-experts")
    if ep > 1 and use_pp:
        raise ValueError("--ep does not combine with parallelism='pipeline'")
    if v_stages > 1 and not use_pp:
        raise ValueError("--v-stages requires parallelism='pipeline'")
    if pp_schedule != "gpipe" and not use_pp:
        raise ValueError("--pp-schedule requires parallelism='pipeline'")
    if ep > len(devs):
        raise ValueError(
            f"--ep {ep} needs at least that many devices; this host "
            f"exposes {len(devs)} (the dp x ep x tp mesh cannot fold)"
        )
    tp = min(tp, max(len(devs) // (pp * ep), 1))  # 1-device hosts: tp=1
    if dp is None:
        dp = max(len(devs) // (pp * ep * tp), 1)
    if dp * ep * tp * pp > len(devs):
        raise ValueError(
            f"pp ({pp}) x dp ({dp}) x ep ({ep}) x tp ({tp}) = "
            f"{pp * dp * ep * tp} exceeds the {len(devs)} devices this "
            "host exposes — lower --dp or --ep (tp self-clamps)"
        )
    if use_pp:
        mesh = Mesh(
            np.array(devs[: pp * dp * tp]).reshape(pp, dp, tp),
            ("pp", "dp", "tp"),
        )
    elif ep > 1:
        # dedicated expert axis: experts shard over ep, batch over dp x ep
        mesh = Mesh(
            np.array(devs[: dp * ep * tp]).reshape(dp, ep, tp),
            ("dp", "ep", "tp"),
        )
    else:
        mesh = Mesh(np.array(devs[: dp * tp]).reshape(dp, tp), ("dp", "tp"))

    heads = max(4, tp)
    heads += (-heads) % tp  # tp must divide heads (and so d_model/d_ff)
    cfg = TransformerConfig(
        vocab=128, d_model=16 * heads, n_heads=heads,
        # interleaved pipeline: every virtual stage needs a layer
        n_layers=2 * v_stages if use_pp else 2,
        d_ff=32 * heads, max_seq=32,
        dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32,
        context_parallel=parallelism == "context",
        n_experts=n_experts,
        moe_mesh_axis="ep" if ep > 1 else "dp",
    )
    use_zero = optimizer == "zero_adam"
    # per-dp-rank batch: 2 samples per MICRObatch, so accumulation grows
    # the effective batch (its purpose) instead of shrinking microbatches
    per_rank_b = 2 * accum_steps
    params0 = init_params(jax.random.PRNGKey(seed), cfg)
    if use_pp:
        from ..models import make_pp_train_step

        if use_zero:
            step_fn, shard, init_state = make_pp_train_step(
                cfg, mesh, num_microbatches=2, v_stages=v_stages,
                schedule=pp_schedule,
                adam=AdamConfig(
                    lr=0.01, clip_grad_norm=clip_grad_norm,
                    master_weights=master_weights,
                ),
            )
            params = shard(params0)
            opt_state = init_state(params0)
        else:
            step_fn, shard = make_pp_train_step(
                cfg, mesh, num_microbatches=2, lr=0.1, v_stages=v_stages,
                schedule=pp_schedule,
            )
            params = shard(params0)
            opt_state = None
    elif use_zero:
        step_fn, shard, init_state = make_zero_train_step(
            cfg, mesh,
            AdamConfig(
                lr=0.01, clip_grad_norm=clip_grad_norm,
                master_weights=master_weights,
            ),
            accum_steps=accum_steps,
        )
        params = shard(params0)
        opt_state = init_state(params0)
    else:
        step_fn, shard = make_sharded_train_step(cfg, mesh, lr=0.1)
        params = shard(params0)
        opt_state = None
    def ckpt_tree():
        # ONE definition of the checkpoint layout: the restore reference
        # and every save must agree or orbax restore breaks
        return (
            {"params": params, "opt_state": opt_state}
            if use_zero else params
        )

    start_step = 0

    ckptr = None
    if ckpt_dir:
        ocp = _ocp()

        ckpt_dir = os.path.abspath(ckpt_dir)
        ckptr = ocp.CheckpointManager(
            ckpt_dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=2),
        )
        latest = ckptr.latest_step()
        if latest is not None:
            # restore with the sharded structure as the reference tree so
            # arrays come back on-mesh
            try:
                restored = ckptr.restore(
                    latest, args=ocp.args.StandardRestore(ckpt_tree())
                )
            except Exception as e:
                # only tree-structure mismatches suggest the optimizer
                # flag; anything else (corrupt file, sharding change,
                # orbax skew) must surface as itself
                msg = str(e).lower()
                if "structure" in msg or "tree" in msg:
                    raise ValueError(
                        f"failed to restore {ckpt_dir} at step {latest} "
                        f"with optimizer={optimizer!r}, "
                        f"parallelism={parallelism!r}, "
                        f"master_weights={master_weights}, "
                        f"n_experts={n_experts}; was the checkpoint "
                        "saved with a different --optimizer, "
                        "--parallelism, --master-weights, or "
                        "--n-experts? (pipeline mode stores layers "
                        "STACKED, dp_tp stores them as a list; master "
                        "weights add a 'w' subtree to the optimizer "
                        "state; MoE replaces w1/w2 with a 'moe' "
                        "subtree)"
                    ) from e
                raise
            if use_zero:
                params, opt_state = restored["params"], restored["opt_state"]
            else:
                params = restored
            start_step = latest + 1
            print(f"resumed from step {latest} in {ckpt_dir}")

    if start_step >= steps:
        print(
            f"nothing to do: checkpoint already at step {start_step - 1}, "
            f"requested --steps {steps}"
        )
        if ckptr is not None:
            ckptr.close()
        return start_step, None

    loss = None
    loader = None
    if data is not None:
        from ..data import TokenLoader

        # single-controller: one loader feeds the whole dp-sharded batch
        # (multi-process deployments shard via shard/num_shards instead)
        loader = TokenLoader(
            data, batch=per_rank_b * dp * ep, seq=cfg.max_seq, seed=seed,
            start_step=start_step,
        )
    try:
      for it in range(start_step, steps):
        if loader is not None:
            t_np, g_np, got_step = loader.next()
            if got_step != it:
                # not an assert: stripped under `python -O`, which would
                # turn a resume/seek mismatch into silent wrong-data
                # training
                raise RuntimeError(
                    f"loader/step misalignment: loader at {got_step}, "
                    f"trainer at {it}"
                )
            # validate the WHOLE window: targets carry one position the
            # tokens array doesn't (the shifted-off last column)
            if max(int(t_np.max()), int(g_np.max())) >= cfg.vocab:
                raise ValueError(
                    f"token file carries ids >= vocab ({cfg.vocab})"
                )
            tokens = jnp.asarray(t_np)
            targets = jnp.asarray(g_np)
        else:
            # per-step data stream keyed by (seed, step): a resumed run
            # consumes the exact token stream an uninterrupted run would,
            # so losses stay bit-comparable across restarts
            rng = np.random.default_rng([seed, it])
            # per-dp-rank batch of 2 per microbatch — which also divides
            # the pipeline mode's num_microbatches=2 exactly
            tokens = jnp.asarray(
                rng.integers(
                    0, cfg.vocab, (per_rank_b * dp * ep, cfg.max_seq)
                ),
                jnp.int32,
            )
            targets = jnp.roll(tokens, -1, axis=1)
        if use_zero:
            params, opt_state, loss = step_fn(
                params, opt_state, tokens, targets
            )
        else:
            params, loss = step_fn(params, tokens, targets)
        loss = float(loss)
        if log_every and (it + 1) % log_every == 0:
            print(f"step {it + 1}/{steps} loss {loss:.4f}", flush=True)
        if ckptr is not None and (it + 1) % save_every == 0:
            ckptr.save(it, args=_ocp().args.StandardSave(ckpt_tree()))
    finally:
      if loader is not None:
        loader.close()  # even when a step raises: stop the prefetch thread
    if ckptr is not None:
        ckptr.save(steps - 1, args=_ocp().args.StandardSave(ckpt_tree()))
        ckptr.wait_until_finished()
        ckptr.close()
    return steps, loss  # loss is the last completed step's global loss


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--save-every", type=int, default=10)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--platform", default=None)
    ap.add_argument(
        "--optimizer", default="sgd", choices=["sgd", "zero_adam"]
    )
    ap.add_argument(
        "--parallelism", default="dp_tp",
        choices=["dp_tp", "context", "pipeline"],
    )
    ap.add_argument(
        "--n-experts", type=int, default=0,
        help="MoE: expert count (sharded over dp, or over --ep); "
        "0 = dense FFN",
    )
    ap.add_argument(
        "--ep", type=int, default=1,
        help="dedicated expert-parallel mesh axis size (>1 un-welds "
        "experts from dp onto a (dp, ep, tp) mesh; requires --n-experts)",
    )
    ap.add_argument(
        "--v-stages", type=int, default=1,
        help="interleaved virtual stages per pipeline rank "
        "(parallelism=pipeline; bubble drops by this factor)",
    )
    ap.add_argument(
        "--pp-schedule", default="gpipe", choices=["gpipe", "1f1b"],
        help="composed pipeline backward: autodiff-through-GPipe or the "
        "hand-scheduled 1F1B (min(pp,M)-input stash + recompute)",
    )
    ap.add_argument(
        "--data", default=None,
        help="ACCLTOK1 token file (native prefetching loader); "
        "default: synthetic tokens",
    )
    ap.add_argument(
        "--accum-steps", type=int, default=1,
        help="gradient accumulation microbatches per step (zero_adam)",
    )
    ap.add_argument(
        "--clip-grad-norm", type=float, default=None,
        help="global-L2-norm gradient clipping (zero_adam)",
    )
    ap.add_argument(
        "--master-weights", action="store_true",
        help="fp32 master-weight track in the optimizer state (zero_adam)",
    )
    ap.add_argument(
        "--dtype", default="float32", choices=["float32", "bfloat16"],
        help="parameter/activation dtype",
    )
    args = ap.parse_args(argv)
    train(
        steps=args.steps, ckpt_dir=args.ckpt_dir,
        save_every=args.save_every, tp=args.tp, seed=args.seed,
        platform=args.platform, optimizer=args.optimizer,
        parallelism=args.parallelism, data=args.data,
        accum_steps=args.accum_steps, clip_grad_norm=args.clip_grad_norm,
        master_weights=args.master_weights, dtype=args.dtype,
        n_experts=args.n_experts, ep=args.ep, v_stages=args.v_stages,
        pp_schedule=args.pp_schedule,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
