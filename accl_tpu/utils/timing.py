"""Host-side microsecond timer (ref ``driver/xrt/include/accl/timing.hpp``:
a start/stop/elapsed µs timer used by the benchmark harness)."""

from __future__ import annotations

import time


class Timer:
    def __init__(self):
        self._t0 = 0
        self._t1 = 0
        self._running = False

    def start(self) -> None:
        self._t0 = time.perf_counter_ns()
        self._running = True

    def stop(self) -> None:
        self._t1 = time.perf_counter_ns()
        self._running = False

    def elapsed_us(self) -> float:
        end = time.perf_counter_ns() if self._running else self._t1
        return (end - self._t0) / 1e3

    def elapsed_ns(self) -> int:
        end = time.perf_counter_ns() if self._running else self._t1
        return end - self._t0

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
