"""Leveled engine logging (ref: the emulator's hlslib ``Log`` with a
verbosity flag, cclo_emu.cpp:511-514 — every DMA/switch/packet event is
printed at high verbosity).  Level comes from the ``ACCL_DEBUG`` env var
like the reference host driver's ``debug()`` gate (driver/xrt/src/common.cpp).
"""

from __future__ import annotations

import enum
import os
import sys
import threading
import time


class LogLevel(enum.IntEnum):
    NONE = 0
    ERROR = 1
    INFO = 2
    DEBUG = 3
    TRACE = 4  # per-message wire events


class Log:
    _lock = threading.Lock()

    def __init__(self, name: str, level=None):
        self.name = name
        if level is None:
            raw = os.environ.get("ACCL_DEBUG", "0")
            try:
                level = int(raw)
            except ValueError:
                # accept level names ("trace"); anything else means off —
                # a debug env var must never crash startup
                level = getattr(LogLevel, raw.strip().upper(), LogLevel.NONE)
        clamped = max(int(LogLevel.NONE), min(int(level), int(LogLevel.TRACE)))
        self.level = LogLevel(clamped)

    def _emit(self, lvl: LogLevel, msg: str) -> None:
        if lvl <= self.level:
            with Log._lock:
                print(
                    f"[{time.monotonic():12.6f}] {lvl.name:5s} {self.name}: {msg}",
                    file=sys.stderr,
                )

    def error(self, msg: str) -> None:
        self._emit(LogLevel.ERROR, msg)

    def info(self, msg: str) -> None:
        self._emit(LogLevel.INFO, msg)

    def debug(self, msg: str) -> None:
        self._emit(LogLevel.DEBUG, msg)

    def trace(self, msg: str) -> None:
        self._emit(LogLevel.TRACE, msg)
