"""Leveled engine logging (ref: the emulator's hlslib ``Log`` with a
verbosity flag, cclo_emu.cpp:511-514 — every DMA/switch/packet event is
printed at high verbosity).  Level comes from the ``ACCL_DEBUG`` env var
like the reference host driver's ``debug()`` gate (driver/xrt/src/common.cpp).

TRACE routing: per-message wire events (``ACCL_DEBUG=TRACE``) are
BUFFERED into the telemetry plane's ring (``accl_tpu.telemetry.wire_event``)
instead of written synchronously to stderr — a synchronous write under
the emitter's lock costs tens of microseconds per message and perturbs
exactly the timings tracing is meant to observe.  The buffered events
render on dump: ``ACCL.telemetry_snapshot()["wire_trace"]`` and as
instant events in the exported Chrome/Perfetto trace.  Set
``ACCL_TRACE_STDERR=1`` to opt the synchronous stderr sink back in
(sampling still applies to the ring via ``ACCL_TELEMETRY_SAMPLE``).
ERROR/INFO/DEBUG keep the stderr behavior — they are low-rate.
"""

from __future__ import annotations

import enum
import os
import sys
import threading
import time


class LogLevel(enum.IntEnum):
    NONE = 0
    ERROR = 1
    INFO = 2
    DEBUG = 3
    TRACE = 4  # per-message wire events


def trace_to_stderr() -> bool:
    """The opt-in synchronous sink for TRACE events (legacy behavior)."""
    return os.environ.get("ACCL_TRACE_STDERR", "0") == "1"


class Log:
    _lock = threading.Lock()

    def __init__(self, name: str, level=None):
        self.name = name
        if level is None:
            raw = os.environ.get("ACCL_DEBUG", "0")
            try:
                level = int(raw)
            except ValueError:
                # accept level names ("trace"); anything else means off —
                # a debug env var must never crash startup
                level = getattr(LogLevel, raw.strip().upper(), LogLevel.NONE)
        clamped = max(int(LogLevel.NONE), min(int(level), int(LogLevel.TRACE)))
        self.level = LogLevel(clamped)

    def _emit(self, lvl: LogLevel, msg: str) -> None:
        if lvl > self.level:
            return
        if lvl == LogLevel.TRACE and not trace_to_stderr():
            # buffered: the wire ring, rendered on dump (telemetry
            # snapshot / trace export) — never a synchronous write on
            # the path being traced
            from ..telemetry import wire_event

            wire_event(self.name, msg)
            return
        with Log._lock:
            print(
                f"[{time.monotonic():12.6f}] {lvl.name:5s} {self.name}: {msg}",
                file=sys.stderr,
            )

    def error(self, msg: str) -> None:
        self._emit(LogLevel.ERROR, msg)

    def info(self, msg: str) -> None:
        self._emit(LogLevel.INFO, msg)

    def debug(self, msg: str) -> None:
        self._emit(LogLevel.DEBUG, msg)

    def trace(self, msg: str) -> None:
        self._emit(LogLevel.TRACE, msg)
