from .timing import Timer  # noqa: F401
from .logging import Log, LogLevel  # noqa: F401
from .platform import mirror_platform_env  # noqa: F401
from .profiling import (  # noqa: F401
    annotate,
    device_memory_profile,
    device_scope,
    start_server,
    trace,
)
