from .timing import Timer  # noqa: F401
from .logging import Log, LogLevel  # noqa: F401
