"""Platform selection that actually sticks.

Site-installed PJRT hooks can initialize their own platform during
backend discovery even when ``JAX_PLATFORMS`` is set in the environment
— and if that platform's transport is unreachable, the first
``jax.devices()`` hangs.  Only the CONFIG path reliably wins, so every
standalone entry point mirrors the env var through
:func:`mirror_platform_env` before its first backend use (the test
conftest does the equivalent inline).
"""

from __future__ import annotations

import os
from typing import Optional


def mirror_platform_env(explicit: Optional[str] = None) -> Optional[str]:
    """Apply ``explicit`` (or the JAX_PLATFORMS env var) via
    ``jax.config`` — call BEFORE the first ``jax.devices()``.  Returns
    the platform string applied, or None if nothing was requested."""
    platform = explicit or os.environ.get("JAX_PLATFORMS")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    return platform or None
