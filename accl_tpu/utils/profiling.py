"""XLA profiler (xprof) hooks — the device-side tracing surface.

The reference's tracing story is a free-running hardware counter copied
into exchange memory per call (`ccl_offload_control.c:2279-2303`) plus
host timers; the TPU-native equivalents layer up:

* per-call ns: ``Request.get_duration_ns`` (already on every tier);
* host spans: :func:`annotate` marks facade calls so they appear as
  named ranges in the xprof timeline;
* device spans: :func:`device_scope` names a region *inside* a jitted
  program (XLA op metadata), so kernels show up attributed in the trace
  viewer;
* whole-program capture: :func:`trace` / :func:`start_server` drive
  ``jax.profiler`` — open the result in xprof/tensorboard or perfetto.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax

# host-side named range (shows on the Python/host rows of the trace)
annotate = jax.profiler.TraceAnnotation

# in-program named scope (attaches XLA op metadata; shows on device rows)
device_scope = jax.named_scope


@contextlib.contextmanager
def trace(logdir: str, *, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a profiler trace of everything inside the block into
    ``logdir`` (xprof format; load with tensorboard or xprof)."""
    options = jax.profiler.ProfileOptions()
    options.host_tracer_level = host_tracer_level
    jax.profiler.start_trace(logdir, profiler_options=options)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def start_server(port: int = 9012):
    """Live capture endpoint: run once, then point
    ``tensorboard --logdir`` profile capture (or xprof) at this port."""
    return jax.profiler.start_server(port)


def device_memory_profile(backend: Optional[str] = None) -> bytes:
    """pprof-format snapshot of live device allocations (the memory side
    of the reference's exchange-memory/buffer dumps)."""
    return jax.profiler.device_memory_profile(backend)
