"""XLA profiler (xprof) hooks — the device-side tracing surface.

The reference's tracing story is a free-running hardware counter copied
into exchange memory per call (`ccl_offload_control.c:2279-2303`) plus
host timers; the TPU-native equivalents layer up:

* per-call ns: ``Request.get_duration_ns`` (already on every tier);
* per-call records: the telemetry plane (``accl_tpu.telemetry``) rings
  every completion into the flight recorder and exports Chrome/Perfetto
  spans named ``accl::<op>`` — the SAME naming :func:`annotate` puts in
  the xprof timeline, so host ranges and exported spans line up;
* host spans: :func:`annotate` marks facade calls so they appear as
  named ranges in the xprof timeline;
* device spans: :func:`device_scope` names a region *inside* a jitted
  program (XLA op metadata), so kernels show up attributed in the trace
  viewer;
* whole-program capture: :func:`trace` / :func:`start_server` drive
  ``jax.profiler`` — open the result in xprof/tensorboard or perfetto.

jax is imported LAZILY: the emulator/native tiers (and the telemetry
plane's exporters) run in jax-free processes, and pulling a device
runtime into them just to name a span would be a side effect a tracing
utility must not have.  Off-jax, :func:`annotate` / :func:`device_scope`
degrade to no-op context managers.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional


def _jax():
    import jax

    return jax


class annotate:
    """Host-side named range (xprof Python/host rows); a no-op context
    manager when jax is unavailable (jax-free emulator processes)."""

    def __init__(self, name: str):
        self._name = name
        try:
            self._inner = _jax().profiler.TraceAnnotation(name)
        except Exception:
            self._inner = None

    def __enter__(self):
        if self._inner is not None:
            self._inner.__enter__()
        return self

    def __exit__(self, *exc):
        if self._inner is not None:
            return self._inner.__exit__(*exc)
        return False


def device_scope(name: str):
    """In-program named scope (XLA op metadata; device rows of the
    trace); no-op off-jax."""
    try:
        return _jax().named_scope(name)
    except Exception:
        return contextlib.nullcontext()


@contextlib.contextmanager
def trace(logdir: str, *, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a profiler trace of everything inside the block into
    ``logdir`` (xprof format; load with tensorboard or xprof).

    ``ProfileOptions`` is a recent jax addition — legacy installs
    degrade to an optionless capture (default host tracer level) instead
    of raising, gated by ``compat.has_profiler_options``."""
    from ..compat import has_profiler_options

    jax = _jax()
    if has_profiler_options():
        options = jax.profiler.ProfileOptions()
        options.host_tracer_level = host_tracer_level
        jax.profiler.start_trace(logdir, profiler_options=options)
    else:
        jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def start_server(port: int = 9012):
    """Live capture endpoint: run once, then point
    ``tensorboard --logdir`` profile capture (or xprof) at this port."""
    return _jax().profiler.start_server(port)


def device_memory_profile(backend: Optional[str] = None) -> bytes:
    """pprof-format snapshot of live device allocations (the memory side
    of the reference's exchange-memory/buffer dumps)."""
    return _jax().profiler.device_memory_profile(backend)
