"""Error-feedback accounting for compressed (quantized-wire) allreduce.

The convergence half of the quantized wire plane: a wire lane that
rounds every gradient contribution to 8 bits throws information away
each step, and with deterministic rounding the thrown-away part is
systematically biased — training on compressed gradients stalls.  The
standard fix (1-bit SGD / EF-SGD lineage) is **error feedback**: carry
the per-element compression error forward and add it back into the next
contribution before compressing,

    x_eff     = grad + residual
    wire      = compress(x_eff)          # what the fabric moves
    residual' = x_eff - decompress(wire) # carried to the next call

so the error the wire drops this step re-enters the sum next step and
the compressed series converges to the uncompressed one in expectation.

:class:`ResidualStore` keeps one residual accumulator per ``(comm id,
comm epoch, op, size bucket)`` — **beside the plan cache, with the plan
cache's lifecycle**: a communicator epoch change re-keys entries
naturally (the PR 2/PR 3 epoch lesson), and every event that
invalidates plans (``SET_TUNING``, ``soft_reset``, eager-threshold
writes, membership churn) clears residuals too via the plan-cache
invalidation hook — a residual accumulated under one wire verdict must
never feed a call dispatched under another.

The one exception is elastic *expansion*: a JOIN cutover changes the
comm epoch but does NOT change the wire verdict the survivors'
residuals were accumulated under (the grown plan re-tunes lazily, and
zeros-vs-carried only affects convergence speed, never correctness).
Dropping every residual there would silently restart EF convergence on
each admission, so :meth:`migrate_epoch` records an old→new epoch
mapping instead and :meth:`apply` re-keys each bucket **lazily on its
first post-cutover touch** — per-bucket, behind that bucket's drain
point (the cutover only fires at a call boundary after the in-flight
window drained), never a global drain.  The admitted rank's previous
life never aliases: its fresh epochs have no mapping, so its old keys
just age out under the entry cap.

The residual update itself is computed with the SAME shared codec
(:mod:`accl_tpu.wire`) and the call's SR seed the engine lane uses, so
where the engine rounds each contribution once with that seed (the
command ring's decode loop, the gang's host-staged casts) the
accounting is **exact**: ``decompress(compress(x_eff))`` at the facade
bit-matches what peers receive.  It is approximate — zero-mean rounding
noise — on the emulator's ring algorithm (re-rounds partial sums per
hop) and the gang's cold in-program compressed path (deterministic
rounding; seeds would re-specialize the cached program); documented,
and the convergence gate measures end-to-end anyway.

SPMD-uniform by construction: whether error feedback applies to a call
is a function of the armed flag (config state), the plan's wire verdict
and the reduce function — never of buffer identity, rank, or health.
Module scope stays jax/numpy-free (lazy numpy, the ``constants.py``
pattern): this module joins the acclint jax-free closure.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from . import wire as wirecodec

__all__ = ["ResidualStore"]

#: entry cap — residuals are per (comm, epoch, op, bucket), so growth
#: only comes from pathological epoch churn; clearing wholesale is
#: correct (residuals are an optimization, zeros are always safe)
DEFAULT_MAX_ENTRIES = 64

#: pending epoch-migration cap — one mapping per JOIN cutover per comm;
#: exceeding it means pathological membership churn, where restarting
#: EF from zeros is the safe answer
MAX_MIGRATIONS = 16


class ResidualStore:
    """Per-(comm, epoch, op, bucket) compression-residual accumulators.

    ``apply()`` is the whole protocol: add the carried residual into
    the contribution, round the sum through the wire codec, store the
    new residual, return what to send.  Counters + a residual-norm
    gauge surface through ``stats()`` into the telemetry snapshot."""

    def __init__(self, max_entries: int = DEFAULT_MAX_ENTRIES):
        self.max_entries = int(max_entries)
        self._lock = threading.Lock()
        self._entries: Dict[Tuple, object] = {}
        self.updates = 0
        self.invalidations = 0
        self.last_invalidation: Optional[str] = None
        # elastic-expansion lazy re-key: {(comm, new epoch) -> (comm,
        # old epoch)} recorded at the JOIN cutover, consumed bucket by
        # bucket on first touch (see migrate_epoch)
        self._migrations: Dict[Tuple, Tuple] = {}
        self.migrations = 0
        # running L2 norm of the most recent residual per key (the
        # convergence health signal: a norm that grows without bound
        # means the wire lane is too aggressive for this workload)
        self._norms: Dict[Tuple, float] = {}

    def apply(self, key: Tuple, x, wire_dtype, seed: int = 0):
        """One error-feedback step for contribution ``x`` (a 1-D float
        numpy array): returns the residual-corrected array to dispatch.
        A count change within the key (bucket) restarts the residual at
        zeros — carrying a stale shape would be wrong, and zeros are
        always safe."""
        import numpy as np

        x = np.asarray(x)
        with self._lock:
            r = self._entries.get(key)
            if r is None and self._migrations:
                # lazy per-bucket epoch migration (JOIN cutover): walk
                # the mapping chain — sequential joins before this
                # bucket's first touch compose — and move the residual
                # under the new key exactly once
                src = self._migrations.get((key[0], key[1]))
                seen = set()
                while src is not None and src not in seen:
                    seen.add(src)
                    old_key = src + key[2:]
                    r = self._entries.pop(old_key, None)
                    if r is not None:
                        self._entries[key] = r
                        self._norms[key] = self._norms.pop(old_key, 0.0)
                        self.migrations += 1
                        break
                    src = self._migrations.get(src)
            if r is not None and (
                r.shape != x.shape or r.dtype != x.dtype
            ):
                r = None
        x_eff = x + r if r is not None else x.copy()
        q = wirecodec.roundtrip(x_eff, wire_dtype, seed).astype(x.dtype)
        new_r = x_eff - q
        norm = float(np.sqrt(float(np.dot(
            new_r.astype(np.float64), new_r.astype(np.float64)
        ))))
        with self._lock:
            if (
                len(self._entries) >= self.max_entries
                and key not in self._entries
            ):
                self._entries.clear()
                self._norms.clear()
            self._entries[key] = new_r
            self._norms[key] = norm
            self.updates += 1
        return x_eff

    def residual(self, key: Tuple):
        """The carried residual for a key (introspection/tests)."""
        with self._lock:
            r = self._entries.get(key)
            return None if r is None else r.copy()

    def migrate_epoch(
        self, comm_id: int, old_epoch: int, new_epoch: int
    ) -> None:
        """Record that ``comm_id``'s residual stream continues under
        ``new_epoch`` (a JOIN cutover re-epoched the communicator
        without changing the wire verdict).  O(1) at the cutover:
        entries stay put and each bucket re-keys lazily on its first
        post-cutover :meth:`apply` — behind that bucket's drain point
        by construction.  Beyond :data:`MAX_MIGRATIONS` pending
        mappings everything clears (zeros are always safe)."""
        with self._lock:
            if len(self._migrations) >= MAX_MIGRATIONS:
                self._entries.clear()
                self._norms.clear()
                self._migrations.clear()
                return
            if int(old_epoch) != int(new_epoch):
                self._migrations[(int(comm_id), int(new_epoch))] = (
                    int(comm_id), int(old_epoch),
                )

    def invalidate(self, reason: str = "") -> None:
        """Drop every residual (the plan-cache hook: register writes,
        soft_reset, membership churn — anything that may change the
        wire verdict a key's calls ride).  A ``membership_join``
        invalidation is the one migration-preserving exception: the
        grow cutover re-epochs comms but leaves wire verdicts intact,
        so entries with a registered epoch migration survive to be
        re-keyed lazily (see :meth:`migrate_epoch`)."""
        with self._lock:
            if not (
                reason.startswith("membership_join") and self._migrations
            ):
                self._entries.clear()
                self._norms.clear()
                self._migrations.clear()
            self.invalidations += 1
            self.last_invalidation = reason or None

    def stats(self) -> dict:
        """The ``telemetry_snapshot()["compression"]["error_feedback"]``
        report."""
        with self._lock:
            worst = max(self._norms.values()) if self._norms else 0.0
            return {
                "entries": len(self._entries),
                "updates": self.updates,
                "invalidations": self.invalidations,
                "last_invalidation": self.last_invalidation,
                "max_residual_norm": round(worst, 6),
                "migrations": self.migrations,
                "pending_migrations": len(self._migrations),
            }
