"""Arithmetic/compression configuration.

Plays the role of the reference's ``ArithConfig`` table
(``driver/xrt/include/accl/arithconfig.hpp:32-119``): for each
(uncompressed dtype, compressed dtype) pair it records element sizes, the
ratio between them, and which reduction implementations are usable.  In the
reference these map to hardware TDEST routes into the ``reduce_ops`` and
``hp_compression`` kernels; here they select numpy/C++ reduction codepaths in
the emulator and XLA reduction computations / cast stages on the TPU tier.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

from .constants import DataType, ReduceFunction, dtype_size


@dataclasses.dataclass(frozen=True)
class ArithConfig:
    uncompressed: DataType
    compressed: DataType
    reduce_functions: Tuple[ReduceFunction, ...] = (
        ReduceFunction.SUM,
        ReduceFunction.MAX,
    )

    @property
    def uncompressed_elem_bytes(self) -> int:
        return dtype_size(self.uncompressed)

    @property
    def compressed_elem_bytes(self) -> int:
        return dtype_size(self.compressed)

    @property
    def is_compressed(self) -> bool:
        return self.uncompressed != self.compressed

    @property
    def elem_ratio(self) -> int:
        """How many compressed elements fit in one uncompressed element's bytes."""
        return max(1, self.uncompressed_elem_bytes // self.compressed_elem_bytes)

    def supports(self, fn: ReduceFunction) -> bool:
        return fn in self.reduce_functions


def _identity(dt: DataType) -> ArithConfig:
    return ArithConfig(dt, dt)


# Default table: identity configs for every supported dtype plus the
# fp32 -> fp16 wire-compression pair (ref arithconfig.hpp DEFAULT_ARITH_CONFIG),
# extended with fp32 -> bf16 which is the natural TPU compression pair.
DEFAULT_ARITH_CONFIG: Dict[Tuple[DataType, DataType], ArithConfig] = {
    (DataType.FLOAT16, DataType.FLOAT16): _identity(DataType.FLOAT16),
    (DataType.FLOAT32, DataType.FLOAT32): _identity(DataType.FLOAT32),
    (DataType.FLOAT64, DataType.FLOAT64): _identity(DataType.FLOAT64),
    (DataType.INT32, DataType.INT32): _identity(DataType.INT32),
    (DataType.INT64, DataType.INT64): _identity(DataType.INT64),
    (DataType.BFLOAT16, DataType.BFLOAT16): _identity(DataType.BFLOAT16),
    (DataType.FLOAT32, DataType.FLOAT16): ArithConfig(
        DataType.FLOAT32, DataType.FLOAT16
    ),
    (DataType.FLOAT32, DataType.BFLOAT16): ArithConfig(
        DataType.FLOAT32, DataType.BFLOAT16
    ),
    # fp8 wire pairs (beyond the reference's f16 lane): this TPU
    # generation moves and computes fp8 natively, so the compression
    # surface exposes both formats — e4m3 (precision) and e5m2 (range)
    (DataType.FLOAT32, DataType.FLOAT8_E4M3): ArithConfig(
        DataType.FLOAT32, DataType.FLOAT8_E4M3
    ),
    (DataType.FLOAT32, DataType.FLOAT8_E5M2): ArithConfig(
        DataType.FLOAT32, DataType.FLOAT8_E5M2
    ),
    (DataType.BFLOAT16, DataType.FLOAT8_E4M3): ArithConfig(
        DataType.BFLOAT16, DataType.FLOAT8_E4M3
    ),
    (DataType.BFLOAT16, DataType.FLOAT8_E5M2): ArithConfig(
        DataType.BFLOAT16, DataType.FLOAT8_E5M2
    ),
    # int8 wire pairs: blockwise absmax-scaled quantization (one fp32
    # scale per constants.WIRE_SEGMENT_ELEMS elements rides the wire
    # beside the int8 payload — see accl_tpu.wire).  SUM only: MAX over
    # per-block rescaled integers is not order-independent across
    # differently-scaled contributions.
    (DataType.FLOAT32, DataType.INT8): ArithConfig(
        DataType.FLOAT32, DataType.INT8,
        reduce_functions=(ReduceFunction.SUM,),
    ),
    (DataType.BFLOAT16, DataType.INT8): ArithConfig(
        DataType.BFLOAT16, DataType.INT8,
        reduce_functions=(ReduceFunction.SUM,),
    ),
}


def lookup(
    table: Dict[Tuple[DataType, DataType], ArithConfig],
    uncompressed: DataType,
    compressed: DataType,
) -> ArithConfig:
    key = (uncompressed, compressed)
    if key not in table:
        raise KeyError(
            f"no arithmetic configuration for dtype pair {uncompressed.name}"
            f" -> {compressed.name}"
        )
    return table[key]
