"""Measurement-driven collective autotuning.

Role model: the reference's runtime tuning registers
(``ccl_offload_control.h:86-90``) hold hand-picked flat-vs-tree
thresholds, written once by the host (``accl.cpp:1198-1208``).  This
module closes the gap NCCL-style tuners and collective-algorithm
synthesis work (SCCL / MSCCLang) close: **measure once per (collective,
size bucket, world, tier), then dispatch from a cached plan**.

Three pieces:

* the measurement harness (:func:`rank_op` / :func:`run_group_op`) — one
  synchronized collective run across a group of rank handles, returning
  the max engine-reported duration.  ``benchmarks/sweep.py`` drives its
  CSV sweeps through these same functions, so the autotuner and the
  committed sweep artifacts measure identically.
* :func:`autotune` — sweeps candidate register sets (algorithm x
  ``RING_SEGMENTS`` x eager threshold, tier-appropriate) per
  (collective, size) and emits a :class:`TuningPlan`: a JSON document
  with provenance, per-size-bucket register winners, and the defaults
  they override.
* :class:`TuningPlan` — load via :meth:`ACCL.load_tuning_plan` or the
  ``ACCL_TUNING_PLAN`` env var.  Plan defaults apply through the
  existing ``SET_TUNING`` config path (so all four engine tiers —
  emulator, native, XLA gang, dist — benefit); the per-size-bucket
  register sets ride the facade's :class:`~accl_tpu.plans.CollectivePlan`
  cache as per-call overlays, generalizing the reference's flat-tree
  ``*_MAX_COUNT`` thresholds into per-size selection at dispatch.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from .constants import (
    AllreduceAlgorithm,
    CMDRING_MAX_RUN_WINDOWS,
    DataType,
    EAGER_THRESHOLD_DEFAULT,
    MAX_EAGER_SIZE_LIMIT,
    ROOTED_ALGORITHMS,
    TUNING_DEFAULTS,
    TUNING_KEY_NAMES,
    WIRE_LANE_DTYPES,
)
from .hierarchical import HIER_OPS, multi_slice
from .plans import size_bucket

#: env var naming a TuningPlan JSON file; loaded (non-strict) by every
#: ACCL handle at construction, so one-process-per-rank tiers inherit it
TUNING_PLAN_ENV = "ACCL_TUNING_PLAN"

#: the nine facade collectives the harness can drive
COLLECTIVES = [
    "sendrecv",
    "bcast",
    "scatter",
    "gather",
    "allgather",
    "reduce",
    "reduce_scatter",
    "allreduce",
    "alltoall",
]

#: register names a plan may carry: the engine tuning tables' names plus
#: the eager-protocol threshold (applied via SET_MAX_EAGER_SIZE)
VALID_REGISTERS = frozenset(TUNING_KEY_NAMES.values()) | {"max_eager_size"}

#: algorithm-select registers (string values from AllreduceAlgorithm)
_ALGO_REGISTERS = frozenset(
    n for n in TUNING_KEY_NAMES.values() if n.endswith("_algorithm")
)


def wire_dtype_value(val) -> int:
    """Normalize a wire-dtype register value to its DataType int: 0 /
    "off" disables; DataType member names ("INT8", "float8_e4m3") and
    numpy lane names ("float8_e4m3fn", "int8") both resolve — plan
    files should be writable by humans."""
    if isinstance(val, str):
        name = val.strip().lower()
        if name in ("", "0", "off", "none"):
            return 0
        for member, np_name in WIRE_LANE_DTYPES.items():
            if name in (member.lower(), np_name.lower()):
                return int(DataType[member])
        raise ValueError(
            f"unknown wire dtype {val!r}; valid: off, "
            f"{sorted(n.lower() for n in WIRE_LANE_DTYPES)}"
        )
    ival = int(val)
    if ival != 0 and DataType(ival).name not in WIRE_LANE_DTYPES:
        raise ValueError(
            f"wire_dtype {ival} ({DataType(ival).name}) is not a "
            f"registered wire lane ({sorted(WIRE_LANE_DTYPES)})"
        )
    return ival

#: the full restoration state: every register the autotuner may touch,
#: at its engine default
REGISTER_DEFAULTS = dict(
    TUNING_DEFAULTS,
    allreduce_algorithm="xla",
    bcast_algorithm="xla",
    reduce_algorithm="xla",
    scatter_algorithm="xla",
    gather_algorithm="xla",
    ring_segments=1,
    max_eager_size=EAGER_THRESHOLD_DEFAULT,
)


def validate_registers(regs: Dict[str, object]) -> Dict[str, object]:
    """Reject unknown register names / malformed algorithm values before
    they reach an engine (a stale plan file must fail loudly at load, not
    as a CONFIG_ERROR mid-collective)."""
    out: Dict[str, object] = {}
    for name, val in (regs or {}).items():
        if name not in VALID_REGISTERS:
            raise ValueError(
                f"unknown tuning register {name!r}; valid: "
                f"{sorted(VALID_REGISTERS)}"
            )
        if name in _ALGO_REGISTERS:
            if isinstance(val, str):
                try:
                    algo = AllreduceAlgorithm[val.upper()]
                except KeyError:
                    raise ValueError(
                        f"register {name}: unknown algorithm {val!r}"
                    ) from None
            else:
                algo = AllreduceAlgorithm(int(val))
            if name != "allreduce_algorithm" and algo not in ROOTED_ALGORITHMS:
                # same rule the engines enforce at SET_TUNING: no
                # ppermute-ring/bidir form exists for rooted collectives
                # — fail at plan load, not as CONFIG_ERROR mid-apply (or
                # worse, a silent xla fallback on the overlay path)
                raise ValueError(
                    f"register {name}: {algo.name.lower()!r} is not a "
                    "rooted lowering (valid: "
                    f"{[a.name.lower() for a in ROOTED_ALGORITHMS]})"
                )
            val = algo.name.lower()
        elif name in ("wire_dtype", "wire_dtype_ici", "wire_dtype_dcn"):
            # the per-link-class lanes validate exactly like the generic
            # register: 0 on a per-class lane means "defer to wire_dtype",
            # not "uncompressed" — the facade's resolution order
            try:
                val = wire_dtype_value(val)
            except ValueError as e:
                raise ValueError(f"register {name}: {e}") from None
        elif name == "hierarchical":
            val = int(val)
            if val not in (0, 1):
                # same bound the engines enforce at SET_TUNING
                raise ValueError(f"register {name}: {val} not in (0, 1)")
        else:
            val = int(val)
            if val < 0:
                raise ValueError(f"register {name}: negative value {val}")
            # engine-parity bounds, enforced at load: the overlay path
            # bypasses SET_TUNING validation entirely, and a defaults
            # value the engine would CONFIG_ERROR must not half-apply
            if name == "max_eager_size" and not (
                0 < val <= MAX_EAGER_SIZE_LIMIT
            ):
                raise ValueError(
                    f"register {name}: {val} outside "
                    f"(0, {MAX_EAGER_SIZE_LIMIT}]"
                )
            if name in ("ring_segments", "gather_flat_tree_max_fanin") \
                    and val < 1:
                raise ValueError(f"register {name}: {val} < 1")
            # persistent-sequencer posture registers: the same clamps
            # the engines enforce at SET_TUNING (an unbounded run /
            # >1s linger would pin the device stream)
            if name == "cmdring_run_windows" and val > CMDRING_MAX_RUN_WINDOWS:
                raise ValueError(
                    f"register {name}: {val} > {CMDRING_MAX_RUN_WINDOWS}"
                )
            if name == "cmdring_linger_us" and val > 1_000_000:
                raise ValueError(
                    f"register {name}: {val} > 1000000 (1s)"
                )
        out[name] = val
    return out


# ---------------------------------------------------------------------------
# TuningPlan: the serializable measurement artifact
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class TuningPlan:
    """Per-(collective, size bucket) register selections with provenance.

    ``entries[collective][bucket]`` is ``{"registers": {...},
    "measured_ns": float, "candidates": {label: ns}}`` — ``registers``
    holds only the overrides vs ``defaults`` (empty = the defaults won).
    Buckets are ``floor(log2(element count))`` (see
    :func:`accl_tpu.plans.size_bucket`)."""

    world: int
    tier: str
    defaults: Dict[str, object] = dataclasses.field(default_factory=dict)
    entries: Dict[str, Dict[int, dict]] = dataclasses.field(
        default_factory=dict
    )
    provenance: Dict[str, object] = dataclasses.field(default_factory=dict)
    version: int = 1
    #: link-class layout the race ran under: a Topology signature string
    #: (e.g. "2x4"), or None for a flat/unclassified group.  Load-time
    #: provenance, not a register: `ACCL.load_tuning_plan` refuses a
    #: plan raced on a different layout — a hierarchical/per-class-wire
    #: winner is only meaningful on the topology it was measured on.
    topology: Optional[str] = None

    # -- dispatch-side lookup ------------------------------------------------
    def registers_for(self, collective: str, bucket: int) -> Dict[str, object]:
        """Register overrides for a collective at a size bucket; the
        nearest measured bucket answers for unmeasured sizes (clamping —
        a 2^20 call uses the 2^19 winner when the sweep stopped there)."""
        per_op = self.entries.get(collective)
        if not per_op:
            return {}
        if bucket in per_op:
            return dict(per_op[bucket].get("registers") or {})
        nearest = min(per_op, key=lambda b: (abs(b - bucket), b))
        return dict(per_op[nearest].get("registers") or {})

    # -- serialization -------------------------------------------------------
    def to_json(self) -> str:
        doc = {
            "version": self.version,
            "world": self.world,
            "tier": self.tier,
            "defaults": self.defaults,
            "entries": {
                op: {str(b): e for b, e in per_op.items()}
                for op, per_op in self.entries.items()
            },
            "provenance": self.provenance,
            "topology": self.topology,
        }
        return json.dumps(doc, indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "TuningPlan":
        doc = json.loads(text)
        entries: Dict[str, Dict[int, dict]] = {}
        for op, per_op in (doc.get("entries") or {}).items():
            entries[op] = {}
            for b, e in per_op.items():
                e = dict(e)
                e["registers"] = validate_registers(e.get("registers") or {})
                entries[op][int(b)] = e
        return cls(
            world=int(doc.get("world", 0)),
            tier=str(doc.get("tier", "")),
            defaults=validate_registers(doc.get("defaults") or {}),
            entries=entries,
            provenance=dict(doc.get("provenance") or {}),
            version=int(doc.get("version", 1)),
            topology=(
                None if doc.get("topology") is None
                else str(doc["topology"])
            ),
        )

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json() + "\n")

    @classmethod
    def load(cls, path: str) -> "TuningPlan":
        with open(path) as f:
            return cls.from_json(f.read())


# ---------------------------------------------------------------------------
# Measurement harness (shared with benchmarks/sweep.py)
# ---------------------------------------------------------------------------


def post_rank_op(accl, rank: int, world: int, op: str, n: int):
    """Post one rank's side of one collective run asynchronously;
    returns the Request, or None when this rank does not participate.
    Shared by the in-process sweeps (emulator/xla gang), the
    one-OS-process-per-rank dist sweep, and the autotuner."""
    if op == "sendrecv":
        if rank == 0:
            buf = accl.create_buffer_from(np.ones(n, np.float32))
            req = accl.send(buf, n, dst=1, tag=0, run_async=True)
        elif rank == 1:
            buf = accl.create_buffer(n, np.float32)
            req = accl.recv(buf, n, src=0, tag=0, run_async=True)
        else:
            return None
    elif op == "bcast":
        buf = accl.create_buffer_from(np.ones(n, np.float32))
        req = accl.bcast(buf, n, root=0, run_async=True)
    elif op == "scatter":
        send = accl.create_buffer_from(np.ones(world * n, np.float32))
        recv = accl.create_buffer(n, np.float32)
        req = accl.scatter(send, recv, n, root=0, run_async=True)
    elif op == "gather":
        send = accl.create_buffer_from(np.ones(n, np.float32))
        recv = accl.create_buffer(world * n, np.float32)
        req = accl.gather(send, recv, n, root=0, run_async=True)
    elif op == "allgather":
        send = accl.create_buffer_from(np.ones(n, np.float32))
        recv = accl.create_buffer(world * n, np.float32)
        req = accl.allgather(send, recv, n, run_async=True)
    elif op == "reduce":
        send = accl.create_buffer_from(np.ones(n, np.float32))
        recv = accl.create_buffer(n, np.float32)
        req = accl.reduce(send, recv, n, root=0, run_async=True)
    elif op == "reduce_scatter":
        send = accl.create_buffer_from(np.ones(world * n, np.float32))
        recv = accl.create_buffer(n, np.float32)
        req = accl.reduce_scatter(send, recv, n, run_async=True)
    elif op == "allreduce":
        send = accl.create_buffer_from(np.ones(n, np.float32))
        recv = accl.create_buffer(n, np.float32)
        req = accl.allreduce(send, recv, n, run_async=True)
    elif op == "alltoall":
        send = accl.create_buffer_from(np.ones(world * n, np.float32))
        recv = accl.create_buffer(world * n, np.float32)
        req = accl.alltoall(send, recv, n, run_async=True)
    else:
        raise ValueError(op)
    return req


def rank_op(accl, rank: int, world: int, op: str, n: int):
    """One rank's side of one collective run, posted and WAITED (the
    per-process body of the dist sweep); returns the engine-reported
    duration in ns, or None when this rank does not participate."""
    req = post_rank_op(accl, rank, world, op, n)
    if req is None:
        return None
    assert req.wait(120), f"{op} count={n} rank={rank} timed out"
    req.check()
    return req.get_duration_ns()


def run_group_op(group, op: str, count: int) -> float:
    """One synchronized run across all rank handles; returns max engine
    duration in ns (the reference records device cycle counts per rank).

    All ranks post ASYNCHRONOUSLY from this one thread, then drain: a
    thread-per-rank harness would bill each run the spawn/scheduling
    skew of its slowest thread (~ms under load on shared-CPU hosts),
    which drowned the <=5% tuned-vs-default artifact gate in noise."""
    world = len(group)
    reqs: List = []
    for i in range(world):
        req = post_rank_op(group[i], i, world, op, count)
        if req is not None:
            reqs.append((i, req))
    durations = [0] * world
    for i, req in reqs:
        assert req.wait(120), f"{op} count={count} rank={i} timed out"
        req.check()
        durations[i] = req.get_duration_ns()
    return max(durations)


# ---------------------------------------------------------------------------
# The autotuner
# ---------------------------------------------------------------------------


def detect_tier(group) -> str:
    """Engine tier of a rank-handle group: emulator | native | xla | dist."""
    name = type(group[0].engine).__name__
    return {
        "EmuEngine": "emulator",
        "NativeEngine": "native",
        "XLAEngine": "xla",
        "DistEngine": "dist",
    }.get(name, name.lower())


def _candidates(
    tier: str,
    op: str,
    world: int,
    include_pallas: bool,
    eager_candidates: Sequence[int],
    segments: Sequence[int],
    pipeline_thresholds: Sequence[int] = (),
    wire_dtypes: Sequence = (),
    cmdring_run_windows: Sequence[int] = (),
    cmdring_linger_us: Sequence[int] = (),
    race_hierarchical: bool = False,
    wire_dtypes_ici: Sequence = (),
    wire_dtypes_dcn: Sequence = (),
) -> List[Dict[str, object]]:
    """Tier-appropriate register sets to race for one collective.  The
    empty dict (the defaults) is always candidate 0 — a plan can only
    ever *beat* the defaults, never silently regress them (the >5%
    not-slower gate in parse_results holds the artifact to that)."""
    cands: List[Dict[str, object]] = [{}]
    if tier in ("xla", "dist"):
        if op == "allreduce":
            cands += [
                {"allreduce_algorithm": "ring", "ring_segments": int(s)}
                for s in segments
            ]
            if include_pallas:
                cands += [
                    {"allreduce_algorithm": "pallas_ring",
                     "ring_segments": int(segments[0])},
                    {"allreduce_algorithm": "pallas_ring_bidir",
                     "ring_segments": int(segments[0])},
                ]
        elif op in ("bcast", "reduce", "scatter", "gather") and include_pallas:
            cands += [
                {f"{op}_algorithm": "pallas_ring", "ring_segments": int(s)}
                for s in segments
            ]
        if op in ("allreduce", "bcast"):
            # overlap plane axes: host-level segmented pipelining —
            # threshold x segment count (the split only fires above the
            # threshold, so small sizes race it as a no-op and the
            # hysteresis margin keeps the defaults)
            cands += [
                {"pipeline_threshold": int(t), "ring_segments": int(s)}
                for t in pipeline_thresholds
                for s in segments
                if int(s) > 1
            ]
    elif tier in ("emulator", "native"):
        if op == "bcast":
            cands += [
                {"bcast_flat_tree_max_ranks": 0},          # always tree
                {"bcast_flat_tree_max_ranks": 1 << 20},    # always flat
            ]
        elif op == "reduce":
            cands += [
                {"reduce_flat_tree_max_ranks": 0,
                 "reduce_flat_tree_max_count": 0},
                {"reduce_flat_tree_max_ranks": 1 << 20,
                 "reduce_flat_tree_max_count": 1 << 30},
            ]
        elif op == "gather":
            fanins = sorted({1, 2, max(1, world - 1)})
            cands += [{"gather_flat_tree_max_fanin": f} for f in fanins]
    if tier in ("xla", "dist") and op == "allreduce":
        # persistent-sequencer posture axes (command ring): the
        # run-window budget and mailbox linger raced per size bucket —
        # winners dispatch per plan key through the per-bucket overlay
        # (CallOptions.effective_tuning -> the gang ring's
        # _window_posture), so a hot training bucket can hold a long
        # resident run while cold buckets keep the env defaults
        cands += [
            {"cmdring_run_windows": int(rw)}
            for rw in cmdring_run_windows
            if 0 < int(rw) <= CMDRING_MAX_RUN_WINDOWS
        ]
        cands += [
            {"cmdring_linger_us": int(lu)}
            for lu in cmdring_linger_us
            if 0 < int(lu) <= 1_000_000
        ]
    if op == "allreduce":
        # quantized wire plane: per-bucket compression verdicts raced
        # like any register — off is always candidate 0 (the defaults),
        # so a lane only wins where the byte saving beats its cast cost
        # by the hysteresis margin (the wall-clock race; correctness is
        # gated separately by check_compression's convergence leg)
        cands += [
            {"wire_dtype": wire_dtype_value(wd)}
            for wd in wire_dtypes
            if wire_dtype_value(wd) != 0
        ]
        # per-link-class wire ladders: an ICI/DCN lane only resolves on a
        # communicator whose link class is uniform — on a mixed parent
        # comm it no-ops (and ties with the defaults), on the derived
        # slice/leader subcomms it is the actual per-hop verdict
        cands += [
            {"wire_dtype_ici": wire_dtype_value(wd)}
            for wd in wire_dtypes_ici
            if wire_dtype_value(wd) != 0
        ]
        cands += [
            {"wire_dtype_dcn": wire_dtype_value(wd)}
            for wd in wire_dtypes_dcn
            if wire_dtype_value(wd) != 0
        ]
    if race_hierarchical and op in HIER_OPS:
        # topology plane: race the slice/cross-slice decomposition
        # against the flat lowering per bucket; for allreduce also race
        # "hierarchical + fp8-on-DCN" — the cross-slice leader hop is
        # the only leg a DCN lane compresses, so the combination is the
        # shape the paper's multi-slice numbers come from
        cands.append({"hierarchical": 1})
        if op == "allreduce":
            cands += [
                {"hierarchical": 1, "wire_dtype_dcn": wire_dtype_value(wd)}
                for wd in wire_dtypes_dcn
                if wire_dtype_value(wd) != 0
            ]
    for e in eager_candidates:
        cands.append({"max_eager_size": int(e)})
    return cands


def _apply_registers(group, regs: Dict[str, object]) -> None:
    """Write a full register state (defaults overlaid with ``regs``)
    through the facade's SET_TUNING / SET_MAX_EAGER_SIZE paths on every
    rank handle of the group."""
    full = dict(REGISTER_DEFAULTS)
    full.update(regs)
    for a in group:
        a.set_max_eager_size(int(full["max_eager_size"]))
        for name, val in full.items():
            if name == "max_eager_size":
                continue
            a.set_tuning(name, val)


def _cand_label(regs: Dict[str, object]) -> str:
    if not regs:
        return "defaults"
    return ",".join(f"{k}={v}" for k, v in sorted(regs.items()))


def autotune(
    group,
    collectives: Optional[Sequence[str]] = None,
    sizes: Optional[Sequence[int]] = None,
    runs: int = 3,
    include_pallas: bool = False,
    eager_candidates: Sequence[int] = (),
    segments: Sequence[int] = (1, 2, 4),
    pipeline_thresholds: Sequence[int] = (),
    wire_dtypes: Sequence = (),
    cmdring_run_windows: Sequence[int] = (),
    cmdring_linger_us: Sequence[int] = (),
    wire_dtypes_ici: Sequence = (),
    wire_dtypes_dcn: Sequence = (),
    topology=None,
    margin: float = 0.10,
    log=None,
) -> TuningPlan:
    """Race tier-appropriate register sets per (collective, size) over a
    live rank-handle group and return the winning :class:`TuningPlan`.

    Measurement discipline matches the sweep harness: one warm run per
    candidate (the device tiers jit-compile per wire shape), then
    ``runs`` measured runs, scored by the **minimum** — the steady-state
    number a cached-plan dispatch path will see.  A non-default
    candidate only wins by beating the defaults by ``margin`` (ties go
    to the defaults): host-timer noise must never bake a fake winner
    into the plan, which the committed artifacts' <=5% not-slower gate
    (parse_results.check_tuned_not_slower) would then refuse.
    Registers are restored to the defaults before returning (the group
    keeps serving)."""
    world = len(group)
    tier = detect_tier(group)
    if topology is None:
        # the group's attached descriptor, when the caller didn't pass
        # one explicitly — hierarchical candidates only make sense on
        # the layout the group actually dispatches under
        topology = getattr(group[0], "topology", None)
    race_hier = topology is not None and multi_slice(topology)
    collectives = list(collectives or COLLECTIVES)
    sizes = list(sizes or [2**e for e in range(4, 17, 4)])
    say = log or (lambda msg: None)

    entries: Dict[str, Dict[int, dict]] = {}
    try:
        for op in collectives:
            if op == "sendrecv":
                continue  # p2p has no algorithm registers to race
            per_op: Dict[int, dict] = {}
            for n in sizes:
                scores: Dict[str, float] = {}
                measured: List[tuple] = []
                for regs in _candidates(
                    tier, op, world, include_pallas, eager_candidates,
                    segments, pipeline_thresholds, wire_dtypes,
                    cmdring_run_windows, cmdring_linger_us,
                    race_hierarchical=race_hier,
                    wire_dtypes_ici=wire_dtypes_ici,
                    wire_dtypes_dcn=wire_dtypes_dcn,
                ):
                    try:
                        # the register writes are part of the candidate:
                        # one the engine refuses (e.g. an out-of-bounds
                        # --eager value) is a SKIP, not a lost race
                        _apply_registers(group, regs)
                        run_group_op(group, op, n)  # warm (compile)
                        ns = min(
                            run_group_op(group, op, n)
                            for _ in range(max(1, runs))
                        )
                    except Exception as e:  # candidate can't run here
                        say(f"# {op} n={n} {_cand_label(regs)}: SKIP ({e})")
                        continue
                    scores[_cand_label(regs)] = ns
                    measured.append((ns, regs))
                if not measured:
                    continue
                default_ns = scores.get("defaults")
                best_ns, best_regs = min(measured, key=lambda t: t[0])
                if (
                    best_regs
                    and default_ns is not None
                    and best_ns >= (1.0 - margin) * default_ns
                ):
                    # not a clear win over the defaults: keep them
                    best_ns, best_regs = default_ns, {}
                bucket = size_bucket(n)
                per_op[bucket] = {
                    "registers": dict(best_regs),
                    "measured_ns": best_ns,
                    "default_ns": default_ns,
                    "size": int(n),
                    "candidates": scores,
                }
                say(
                    f"{op} n={n} (bucket {bucket}): "
                    f"{_cand_label(best_regs)} @ {best_ns:.0f} ns"
                )
            if per_op:
                entries[op] = per_op
    finally:
        _apply_registers(group, {})  # restore defaults

    provenance: Dict[str, object] = {
        "generated_by": "accl_tpu.tuning.autotune",
        "engine": type(group[0].engine).__name__,
        "sizes": sizes,
        "runs": int(runs),
        "include_pallas": bool(include_pallas),
        "eager_candidates": [int(e) for e in eager_candidates],
        "segments": [int(s) for s in segments],
        "pipeline_thresholds": [int(t) for t in pipeline_thresholds],
        "wire_dtypes": [wire_dtype_value(w) for w in wire_dtypes],
        "cmdring_run_windows": [int(r) for r in cmdring_run_windows],
        "cmdring_linger_us": [int(u) for u in cmdring_linger_us],
        "wire_dtypes_ici": [wire_dtype_value(w) for w in wire_dtypes_ici],
        "wire_dtypes_dcn": [wire_dtype_value(w) for w in wire_dtypes_dcn],
        "topology": None if topology is None else topology.signature(),
        "hierarchical_raced": bool(race_hier),
        "margin": float(margin),
    }
    try:
        import jax

        provenance["jax"] = jax.__version__
        import sys

        if "jax" in sys.modules:
            from jax._src import xla_bridge

            if xla_bridge._backends:
                provenance["platform"] = jax.default_backend()
    except Exception:  # pragma: no cover - jax-free emulator processes
        pass
    return TuningPlan(
        world=world,
        tier=tier,
        defaults=dict(REGISTER_DEFAULTS),
        entries=entries,
        provenance=provenance,
        topology=None if topology is None else topology.signature(),
    )


# ---------------------------------------------------------------------------
# CLI: python -m accl_tpu.tuning --backend emulator --world 4 --out plan.json
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        description="Autotune collective algorithm registers; emit a "
        "TuningPlan JSON artifact."
    )
    ap.add_argument("--backend", choices=["emulator", "xla"],
                    default="emulator")
    ap.add_argument("--world", type=int, default=4)
    ap.add_argument("--min-exp", type=int, default=4)
    ap.add_argument("--max-exp", type=int, default=16)
    ap.add_argument("--step-exp", type=int, default=2,
                    help="exponent stride between swept sizes")
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--collectives", nargs="*", default=None)
    ap.add_argument("--include-pallas", action="store_true",
                    help="also race the Pallas ring lowerings (slow "
                    "off-TPU: they run interpreted)")
    ap.add_argument("--eager", nargs="*", type=int, default=[],
                    help="max_eager_size candidates (bytes) to race")
    ap.add_argument("--segments", nargs="*", type=int, default=[1, 2, 4])
    ap.add_argument(
        "--pipeline-thresholds", nargs="*", type=int, default=[],
        help="pipeline_threshold candidates (bytes) to race against the "
             "segment counts — the overlap plane's host-level segmented "
             "pipelining axes (e.g. 65536 262144)",
    )
    ap.add_argument(
        "--wire-dtypes", nargs="*", default=[],
        help="wire-compression verdicts to race for allreduce (per-"
             "bucket WIRE_DTYPE register): names from the registered "
             "lanes, e.g. float16 bfloat16 float8_e4m3 int8 — 'off' "
             "(the defaults) is always candidate 0",
    )
    ap.add_argument(
        "--cmdring-run-windows", nargs="*", type=int, default=[],
        help="command-ring run-window budgets to race for allreduce "
             "(per-bucket CMDRING_RUN_WINDOWS register, XLA gang tier; "
             "e.g. 32 128) — 0/default is always candidate 0",
    )
    ap.add_argument(
        "--cmdring-linger-us", nargs="*", type=int, default=[],
        help="command-ring mailbox linger candidates in microseconds "
             "(per-bucket CMDRING_LINGER_US register, XLA gang tier; "
             "e.g. 500 5000)",
    )
    ap.add_argument(
        "--wire-dtypes-ici", nargs="*", default=[],
        help="per-link-class wire lanes to race on ICI-uniform "
             "communicators (WIRE_DTYPE_ICI register); same names as "
             "--wire-dtypes",
    )
    ap.add_argument(
        "--wire-dtypes-dcn", nargs="*", default=[],
        help="per-link-class wire lanes to race on DCN-crossing hops "
             "(WIRE_DTYPE_DCN register) — with --slice-size this also "
             "races 'hierarchical + lane' for allreduce",
    )
    ap.add_argument(
        "--slice-size", type=int, default=None,
        help="emulator backend only: attach a symmetric multi-slice "
             "Topology (world/slice-size slices) to the group, which "
             "arms the hierarchical-vs-flat race and stamps the plan's "
             "topology provenance",
    )
    ap.add_argument(
        "--ici-gbps", type=float, default=None,
        help="emulator backend only: modeled intra-slice link rate for "
             "the two-class paced fabric (with --dcn-gbps)",
    )
    ap.add_argument(
        "--dcn-gbps", type=float, default=None,
        help="emulator backend only: modeled cross-slice link rate — "
             "the slow class hierarchical decomposition exists to avoid",
    )
    ap.add_argument(
        "--wire-gbps", type=float, default=None,
        help="emulator backend only: pace the in-process fabric at this "
             "modeled link rate (Fabric.set_wire_rate) for the whole "
             "race — the regime wire-compression verdicts exist for; "
             "unpaced loopback is memcpy and every lane loses to its "
             "own codec cost.  Recorded in the plan's provenance.",
    )
    ap.add_argument(
        "--margin", type=float, default=0.10,
        help="a non-default candidate must beat the defaults by this "
             "fraction to win its bucket (noise hysteresis)",
    )
    ap.add_argument("--out", default="-")
    ap.add_argument(
        "--csv-default", default=None,
        help="also write the race's defaults-candidate measurements as "
             "a sweep CSV (one session with --csv-tuned: the committed "
             "tuned-vs-default pair parse_results --check-tuned gates)",
    )
    ap.add_argument(
        "--csv-tuned", default=None,
        help="also write the race's per-point winner measurements as a "
             "sweep CSV (the winner is the defaults unless a candidate "
             "beat them by --margin, so the pair passes the not-slower "
             "gate unless the selection logic itself regresses)",
    )
    ap.add_argument(
        "--platform", default=None,
        help="force a jax platform (e.g. 'cpu') before device discovery",
    )
    args = ap.parse_args(argv)

    if args.backend == "xla":
        from .utils import mirror_platform_env

        mirror_platform_env(args.platform)

    from . import core

    topology = None
    if args.slice_size:
        if args.backend != "emulator":
            raise SystemExit("--slice-size attaches an emulated-fabric "
                             "topology (use --backend emulator)")
        from .topology import Topology

        topology = Topology.from_slice_size(args.world, args.slice_size)
    group = (
        core.emulated_group(args.world, topology=topology)
        if args.backend == "emulator"
        else core.xla_group(args.world)
    )
    if args.wire_gbps:
        if args.backend != "emulator":
            raise SystemExit("--wire-gbps models the emulated fabric "
                             "(use --backend emulator)")
        group[0].engine.fabric.set_wire_rate(args.wire_gbps)
    if args.ici_gbps or args.dcn_gbps:
        if args.backend != "emulator":
            raise SystemExit("--ici-gbps/--dcn-gbps model the emulated "
                             "fabric (use --backend emulator)")
        group[0].engine.fabric.set_wire_rates(
            ici_gbps=args.ici_gbps, dcn_gbps=args.dcn_gbps
        )
    try:
        plan = autotune(
            group,
            collectives=args.collectives,
            sizes=[2**e for e in range(
                args.min_exp, args.max_exp + 1, max(1, args.step_exp)
            )],
            runs=args.runs,
            include_pallas=args.include_pallas,
            eager_candidates=args.eager,
            segments=args.segments,
            pipeline_thresholds=args.pipeline_thresholds,
            wire_dtypes=args.wire_dtypes,
            cmdring_run_windows=args.cmdring_run_windows,
            cmdring_linger_us=args.cmdring_linger_us,
            wire_dtypes_ici=args.wire_dtypes_ici,
            wire_dtypes_dcn=args.wire_dtypes_dcn,
            topology=topology,
            margin=args.margin,
            log=lambda msg: print(msg, file=sys.stderr),
        )
    finally:
        for a in group:
            a.deinit()
    plan.provenance["backend"] = args.backend
    if args.wire_gbps:
        plan.provenance["wire_gbps_model"] = float(args.wire_gbps)
    if args.ici_gbps or args.dcn_gbps:
        plan.provenance["wire_class_gbps_model"] = {
            "ici": args.ici_gbps, "dcn": args.dcn_gbps,
        }
    text = plan.to_json()
    if args.out == "-":
        print(text)
    else:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"wrote {args.out}", file=sys.stderr)
    for path, key in (
        (args.csv_default, "default_ns"),
        (args.csv_tuned, "measured_ns"),
    ):
        if not path:
            continue
        import csv

        with open(path, "w", newline="") as f:
            w = csv.DictWriter(
                f,
                fieldnames=["collective", "count", "bytes", "duration_ns",
                            "gbps"],
            )
            w.writeheader()
            # same writer-side refusal as benchmarks/sweep.py write_row:
            # a sentinel/garbage duration must be an ERROR here, not a
            # committed chip artifact (chip_session.sh autotune leg)
            ceiling = float(
                os.environ.get("ACCL_SWEEP_GBPS_CEILING", "10000")
            )
            for op in sorted(plan.entries):
                for bucket in sorted(plan.entries[op]):
                    e = plan.entries[op][bucket]
                    ns = e.get(key)
                    n = e.get("size")
                    if ns is None or n is None:
                        continue
                    gbps = 8 * n * 4 / max(ns, 1)
                    if gbps > ceiling:
                        raise RuntimeError(
                            f"{op} count={n}: {gbps:.2f} Gb/s from "
                            f"duration_ns={ns:.0f} exceeds the "
                            f"{ceiling:.0f} Gb/s sanity ceiling — the "
                            "engine reported a sentinel/garbage "
                            "duration; refusing to write the row"
                        )
                    w.writerow({
                        "collective": op, "count": n, "bytes": n * 4,
                        "duration_ns": int(ns),
                        "gbps": gbps,
                    })
        print(f"wrote {path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
