"""Communication/compute overlap primitives.

The reference's segmented ring pipelines overlap the wire with the
reduction per chunk (firmware hot loop, ccl_offload_control.c:1940-1982
— recv/reduce/send in flight simultaneously).  At the model layer the
TPU-native form of that idea is the *ring-scheduled matmul*: a
row-parallel matmul whose cross-rank reduction is decomposed into ring
hops interleaved with the matmul's own output chunks, so XLA can hide
each ``ppermute`` behind the next chunk's MXU work instead of waiting
for one monolithic matmul before one monolithic collective.

``matmul_reduce_scatter`` is the fused form of
``reduce_scatter(x @ w, axis)`` (the Megatron-SP row-parallel exit);
``matmul_allreduce`` adds the allgather leg.  Both are exact — the
decomposition reorders a sum — and both run anywhere ``shard_map``
runs; the overlap benefit appears on real ICI where the compiler
schedules the permute DMA concurrently with the MXU.
"""

from __future__ import annotations

import jax

from ..compat import install as _compat_install

_compat_install()  # legacy-jax shims (shard_map kwargs, lax.axis_size)
import jax.numpy as jnp
from jax import lax

from . import collectives


def matmul_reduce_scatter(
    x: jax.Array, w: jax.Array, axis_name: str
) -> jax.Array:
    """``reduce_scatter(x @ w, axis_name)`` with the reduction ring
    interleaved into the matmul's output chunks.

    ``x``: (..., K_local), ``w``: (K_local, N) — the row-parallel layout
    (K sharded over the axis).  N must divide by the axis size; rank r
    returns chunk r of the summed product, shape (..., N/size).

    Schedule: at step s every rank computes the PARTIAL product for the
    chunk that is ``size-1-s`` hops upstream of its own, adds the
    accumulator arriving from its neighbor, and forwards — after
    ``size`` steps the accumulator holds the fully-summed home chunk.
    Each ppermute is independent of the next chunk's matmul, which is
    what lets the scheduler overlap wire and MXU.
    """
    size = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    N = w.shape[-1]
    if N % size:
        raise ValueError(f"N ({N}) must divide by axis size ({size})")
    blk = N // size
    perm = [(i, (i + 1) % size) for i in range(size)]

    acc = jnp.zeros(x.shape[:-1] + (blk,), jnp.promote_types(x.dtype, w.dtype))
    for s in range(size):
        # chunk index this rank contributes to at step s: after the
        # remaining (size-1-s) forward hops it lands on its home rank
        c = jnp.mod(me + (size - 1 - s), size)
        w_c = lax.dynamic_slice_in_dim(w, c * blk, blk, axis=-1)
        partial = x @ w_c
        if s:
            acc = lax.ppermute(acc, axis_name, perm)
        acc = acc + partial
    # result dtype matches reduce_scatter(x @ w): the matmul's natural
    # promoted dtype, NOT a downcast to the activation dtype
    return acc


def matmul_allreduce(
    x: jax.Array, w: jax.Array, axis_name: str
) -> jax.Array:
    """``allreduce(x @ w, axis_name)`` as the ring-scheduled
    reduce-scatter above plus an allgather of the chunks — the fused
    row-parallel matmul+allreduce of tensor parallelism."""
    scattered = matmul_reduce_scatter(x, w, axis_name)
    # invariant form: the allreduce result is replicated by construction,
    # and callers may legitimately claim so in their out_specs
    return collectives.allgather_invariant(
        scattered, axis_name, axis=scattered.ndim - 1
    )
