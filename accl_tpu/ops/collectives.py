"""SPMD collective primitives with the reference op vocabulary.

These functions run *inside* ``shard_map`` (or any SPMD context with a named
mesh axis) and lower to single XLA collectives over ICI — the TPU-native
replacement for the reference's CCLO offload engine: where ACCL's firmware
dispatches ring/tree programs onto the FPGA dataplane
(``ccl_offload_control.c``), here XLA's collective scheduler owns the wire
and we express only the semantics.

Reduction functions mirror ``reduceFunction`` (constants.hpp:218-221):
SUM and MAX, extended with MIN/PROD which fall out naturally on TPU.
"""

from __future__ import annotations

from typing import Optional

import jax

from ..compat import install as _compat_install

_compat_install()  # legacy-jax shims (shard_map kwargs, lax.axis_size)
import jax.numpy as jnp
from jax import lax

from ..constants import ReduceFunction

_REDUCERS = {
    ReduceFunction.SUM: lax.psum,
    ReduceFunction.MAX: lax.pmax,
}


def axis_size(axis_name: str) -> int:
    return lax.axis_size(axis_name)


def rank(axis_name: str):
    return lax.axis_index(axis_name)


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------


def allreduce(
    x: jax.Array,
    axis_name: str,
    function: ReduceFunction = ReduceFunction.SUM,
) -> jax.Array:
    """ref ``ACCL::allreduce`` (accl.hpp) — every rank gets the reduction."""
    try:
        return _REDUCERS[function](x, axis_name)
    except KeyError:
        raise ValueError(f"unsupported reduce function {function}") from None


def reduce(
    x: jax.Array,
    axis_name: str,
    root: int = 0,
    function: ReduceFunction = ReduceFunction.SUM,
) -> jax.Array:
    """ref ``ACCL::reduce`` — full result on ``root``, zeros elsewhere.

    SPMD programs have no 'no result' rank, so non-roots get zeros (the
    analog of the reference's DummyBuffer operand on non-roots)."""
    full = allreduce(x, axis_name, function)
    return jnp.where(lax.axis_index(axis_name) == root, full, jnp.zeros_like(full))


def reduce_scatter(
    x: jax.Array,
    axis_name: str,
    function: ReduceFunction = ReduceFunction.SUM,
    tiled: bool = False,
    axis: int = 0,
) -> jax.Array:
    """ref ``ACCL::reduce_scatter`` — rank i gets block i of the reduction
    along ``axis``.

    SUM lowers to a single XLA reduce-scatter (``psum_scatter``); MAX is
    composed as pmax + local slice (XLA fuses the slice)."""
    if function == ReduceFunction.SUM:
        return lax.psum_scatter(
            x, axis_name, scatter_dimension=axis, tiled=tiled
        )
    full = allreduce(x, axis_name, function)
    size = lax.axis_size(axis_name)
    if not tiled:
        # match psum_scatter(tiled=False): the scatter dimension must
        # equal the axis size and is squeezed from the result
        if x.shape[axis] != size:
            raise ValueError(
                f"reduce_scatter: tiled=False requires axis {axis} length "
                f"{x.shape[axis]} == axis size {size}"
            )
        out = lax.dynamic_slice_in_dim(
            full, lax.axis_index(axis_name), 1, axis=axis
        )
        return lax.squeeze(out, (axis,))
    if x.shape[axis] % size != 0:
        raise ValueError(
            f"reduce_scatter: axis {axis} length {x.shape[axis]} is not "
            f"divisible by axis size {size} (non-SUM path has no padding; "
            "pad the operand or use a divisible count)"
        )
    block = x.shape[axis] // size
    start = lax.axis_index(axis_name) * block
    return lax.dynamic_slice_in_dim(full, start, block, axis=axis)


# ---------------------------------------------------------------------------
# data movement
# ---------------------------------------------------------------------------


def allgather(
    x: jax.Array, axis_name: str, tiled: bool = True, axis: int = 0
) -> jax.Array:
    """ref ``ACCL::allgather`` — concatenation of every rank's block
    along ``axis``."""
    return lax.all_gather(x, axis_name, tiled=tiled, axis=axis)


try:  # Varying -> Invariant allgather (not yet re-exported publicly)
    from jax._src.lax.parallel import all_gather_invariant as _ag_invariant
except ImportError:  # pragma: no cover - older jax
    _ag_invariant = None


def allgather_invariant(
    x: jax.Array, axis_name: str, axis: int = 0, tiled: bool = True
) -> jax.Array:
    """Allgather whose output shard_map's replication checker accepts as
    axis-invariant — required whenever the gathered value flows to a
    replicated (``P(None)``) output.  Falls back to a psum of scattered
    slices (provably invariant, 2x the wire bytes) on jax versions
    without ``all_gather_invariant``."""
    if _ag_invariant is not None:
        return _ag_invariant(x, axis_name, axis=axis, tiled=tiled)
    return _allgather_invariant_fallback(x, axis_name, axis=axis, tiled=tiled)


def _allgather_invariant_fallback(
    x: jax.Array, axis_name: str, axis: int = 0, tiled: bool = True
) -> jax.Array:
    """Psum-of-scattered-slices allgather: provably axis-invariant on any
    jax, at 2x the wire bytes.  Kept directly testable (tests force
    ``_ag_invariant=None``) so a jax upgrade that drops the private op
    cannot silently change semantics."""
    # The assembly needs the STATIC axis size for its shapes; a jax old
    # enough to lack both the private op and lax.axis_size gets a clear
    # error instead of a trace-time mystery.
    if not hasattr(lax, "axis_size"):
        raise RuntimeError(
            "allgather_invariant needs jax with lax.axis_size or "
            "all_gather_invariant"
        )
    size = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    block = x.shape[axis]
    full_shape = list(x.shape)
    full_shape[axis] = block * size
    contrib = lax.dynamic_update_slice_in_dim(
        jnp.zeros(tuple(full_shape), x.dtype), x, idx * block, axis=axis
    )
    out = lax.psum(contrib, axis_name)
    if tiled:
        return out
    return out.reshape(
        x.shape[:axis] + (size, block) + x.shape[axis + 1:]
    )


def bcast(x: jax.Array, axis_name: str, root: int = 0) -> jax.Array:
    """ref ``ACCL::bcast`` — root's block everywhere.

    Expressed as a masked psum, which XLA lowers to a broadcast-shaped
    collective; avoids materializing an allgather of world size."""
    masked = jnp.where(lax.axis_index(axis_name) == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def scatter(x: jax.Array, axis_name: str, root: int = 0) -> jax.Array:
    """ref ``ACCL::scatter`` — rank i gets block i of root's array.

    ``x`` is the full (size*count) array on root (don't-care elsewhere)."""
    size = lax.axis_size(axis_name)
    block = x.shape[0] // size
    full = bcast(x, axis_name, root)
    start = lax.axis_index(axis_name) * block
    return lax.dynamic_slice_in_dim(full, start, block, axis=0)


def gather(x: jax.Array, axis_name: str, root: int = 0) -> jax.Array:
    """ref ``ACCL::gather`` — concatenation on root, zeros elsewhere."""
    full = lax.all_gather(x, axis_name, tiled=True)
    return jnp.where(
        lax.axis_index(axis_name) == root, full, jnp.zeros_like(full)
    )


def alltoall(x: jax.Array, axis_name: str) -> jax.Array:
    """ref ``ACCL::alltoall`` — block-transpose across the axis.

    ``x`` has leading dim size*count; rank r's output block p is rank p's
    input block r — one XLA all-to-all on ICI."""
    size = lax.axis_size(axis_name)
    blocks = x.reshape((size, -1) + x.shape[1:])
    out = lax.all_to_all(blocks, axis_name, split_axis=0, concat_axis=0)
    return out.reshape(x.shape)


# ---------------------------------------------------------------------------
# point-to-point (SPMD style)
# ---------------------------------------------------------------------------


def sendrecv(
    x: jax.Array, axis_name: str, distance: int = 1
) -> jax.Array:
    """Shift along the ring: every rank sends to rank+distance and receives
    from rank-distance — the SPMD form of matched ``send``/``recv`` pairs,
    one ``collective-permute`` on ICI (the reference's eager send/recv pair
    collapses into this under a synchronous schedule)."""
    size = lax.axis_size(axis_name)
    perm = [(i, (i + distance) % size) for i in range(size)]
    return lax.ppermute(x, axis_name, perm)


def send_to(
    x: jax.Array, axis_name: str, src: int, dst: int
) -> jax.Array:
    """Single directed transfer src -> dst (other ranks receive zeros)."""
    return lax.ppermute(x, axis_name, [(src, dst)])


def barrier(axis_name: str) -> jax.Array:
    """ref ``ACCL::barrier`` — a zero-payload allreduce; XLA's collective
    already synchronizes the axis, we return the token-like scalar."""
    return lax.psum(jnp.zeros((), jnp.int32), axis_name)


# ---------------------------------------------------------------------------
# wire compression (ref hp_compression plugin + ETH_COMPRESSED flag)
# ---------------------------------------------------------------------------


def compressed_allreduce(
    x: jax.Array,
    axis_name: str,
    wire_dtype: jnp.dtype = jnp.bfloat16,
    function: ReduceFunction = ReduceFunction.SUM,
) -> jax.Array:
    """Allreduce with operands cast to a narrow dtype before crossing the
    wire — the TPU-native form of the reference's fp32->fp16 'ethernet
    compression' (hp_compression kernels + ETH_COMPRESSED): reduce-scatter
    in wire dtype, accumulate locally in the original dtype, allgather the
    narrow result.  Counts that don't divide the axis size are padded
    (statically) around the scatter/gather pair.

    Sub-byte-precision lanes (fp8) and the scaled int8 lane round each
    CONTRIBUTION through the wire once and then reduce at the original
    dtype — accumulating AT 2-3 mantissa bits (or across differently
    scaled int8 blocks) is numerically meaningless, and single-rounding
    is exactly the command-ring decode loop's semantic, so warm (ring)
    and cold (this program) compressed calls agree."""
    orig = x.dtype
    n = x.shape[0]
    size = lax.axis_size(axis_name)
    pad = (-n) % size
    from ..constants import numpy_to_dtype
    from ..wire import dropped_mantissa_bits, is_scaled

    _dt = numpy_to_dtype(jnp.dtype(wire_dtype))
    if is_scaled(_dt) or (dropped_mantissa_bits(_dt) or 0) >= 20:
        from . import wire as devwire

        rounded = devwire.wire_lane_roundtrip(x, jnp.dtype(wire_dtype))
        if function == ReduceFunction.SUM:
            return lax.psum(rounded, axis_name)
        return _REDUCERS[function](rounded, axis_name)
    narrow = x.astype(wire_dtype)
    if pad:
        narrow = jnp.concatenate(
            [narrow, jnp.zeros((pad,) + x.shape[1:], wire_dtype)]
        )
    if function == ReduceFunction.SUM:
        partial = lax.psum_scatter(
            narrow, axis_name, scatter_dimension=0, tiled=True
        ).astype(orig)
    else:
        partial_full = _REDUCERS[function](narrow, axis_name).astype(orig)
        block = (n + pad) // size
        partial = lax.dynamic_slice_in_dim(
            partial_full, lax.axis_index(axis_name) * block, block, axis=0
        )
    gathered = lax.all_gather(partial.astype(wire_dtype), axis_name, tiled=True)
    return gathered[:n].astype(orig)
